"""mgdelta (ISSUE 14): incremental semiring fixpoints on a
device-resident graph — commit-to-fresh-result in O(changed edges).

Layers of coverage:

1. EdgeDelta splice correctness: delta-refresh vs full-rebuild
   BIT-EXACT ShardedCSR equivalence over adds/removes/weight updates,
   uneven shard counts, both owning endpoints, mesh-of-1 and the full
   8-virtual-device mesh; capacity overflow and removal mismatch return
   None (the loud rebuild path), never a partial splice.
2. Warm-started fixpoints per algorithm: pagerank/katz residual
   equivalence at the same tol on segment AND mesh backends; WCC /
   labelprop warm results identical to cold under adds-only deltas;
   the monotone-unsafe LOUD cold start (delta.cold_start_total) when a
   removal poisons the seed.
3. ResidentGraph generations: empty-delta version bumps, bounded
   delta-accumulation compaction, registry LRU + gauge.
4. LocalWarmPool (in-process commit-then-CALL) against a REAL storage
   change log, including the wrap fallback matrix.
5. Kernel-server protocol: full import → delta-only request (changed +
   incident edges, no full edge arrays) → warm-started reply; removal
   delta forcing the typed cold start; stale-generation honesty.
6. Change-log wrap: monotone oldest_logged_version, the typed
   ChangeLogUnknowable verdict, and every consumer's explicit fallback.
7. device_chaos: a device fault mid-(delta-apply → dispatch) yields a
   typed outcome and the retry serves the CONSISTENT new generation.
"""

import threading
import time

import numpy as np
import pytest

from memgraph_tpu.observability.metrics import global_metrics
from memgraph_tpu.ops import csr
from memgraph_tpu.ops import delta as D
from memgraph_tpu.ops.csr import from_coo, shard_edges
from memgraph_tpu.storage.storage import (ChangeLogUnknowable,
                                          InMemoryStorage)
from memgraph_tpu.utils import faultinject as FI

TOL = 1e-6


@pytest.fixture(autouse=True)
def _clean_faults():
    FI.reset()
    yield
    FI.reset()


def _metric(name):
    return dict((n, v) for n, _k, v
                in global_metrics.snapshot()).get(name, 0.0)


def _coo(seed=0, n=200, e=1500, weighted=True):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n, e).astype(np.int64)
    dst = rng.integers(0, n, e).astype(np.int64)
    w = (rng.random(e).astype(np.float32) if weighted
         else np.ones(e, dtype=np.float32))
    return src, dst, w


def _delta_of(src, dst, w, seed=1, n=200, n_add=40, n_rem=30, n_upd=10):
    """A mixed delta: adds + removes + weight updates over existing
    edges. Returns (delta, updated (src, dst, w))."""
    rng = np.random.default_rng(seed)
    e = len(src)
    rem_i = rng.choice(e, n_rem, replace=False)
    upd_i = rng.choice(np.setdiff1d(np.arange(e), rem_i), n_upd,
                       replace=False)
    add_src = rng.integers(0, n, n_add).astype(np.int64)
    add_dst = rng.integers(0, n, n_add).astype(np.int64)
    add_w = rng.random(n_add).astype(np.float32)
    d = D.EdgeDelta(
        0, 1,
        add_src=np.concatenate([add_src, src[upd_i]]),
        add_dst=np.concatenate([add_dst, dst[upd_i]]),
        add_w=np.concatenate([add_w,
                              (w[upd_i] * 2).astype(np.float32)]),
        rem_src=np.concatenate([src[rem_i], src[upd_i]]),
        rem_dst=np.concatenate([dst[rem_i], dst[upd_i]]),
        rem_w=np.concatenate([w[rem_i], w[upd_i]]))
    coo = D.splice_coo((src, dst, w), d, n)
    assert coo is not None
    return d, coo


# ==========================================================================
# 1. splice correctness
# ==========================================================================


@pytest.mark.parametrize("n_shards", [1, 3, 8])
@pytest.mark.parametrize("by", ["src", "dst"])
def test_apply_edge_delta_matches_full_reshard(n_shards, by):
    """Per affected shard row the spliced layout must carry exactly the
    edges a from-scratch reshard of the updated edge list carries, with
    the (dst, src) sort and block_ptr invariants intact; unaffected
    rows must be untouched. n=203 makes the last shard uneven."""
    n = 203
    src, dst, w = _coo(seed=0, n=n)
    scsr = shard_edges(src, dst, w, n, n_shards, by=by)
    d, coo = _delta_of(src, dst, w, seed=1, n=n)
    out = D.apply_edge_delta(scsr, d)
    ref = shard_edges(*coo, n, n_shards, by=by)
    if out is None:
        # legal ONLY on real per-row capacity overflow (1-shard layouts
        # have zero padding slack) — never a silent partial apply
        key = coo[0] if by == "src" else coo[1]
        counts = np.bincount((key // scsr.block).astype(np.int64),
                             minlength=n_shards)
        assert counts.max() > scsr.per
        return
    assert out.n_edges == ref.n_edges == len(coo[0])
    sink = n
    for p in range(n_shards):
        rc_o = int(np.searchsorted(out.dst[p], sink))
        rc_r = int(np.searchsorted(ref.dst[p], sink))
        assert rc_o == rc_r
        got = sorted(zip(out.dst[p][:rc_o].tolist(),
                         out.src[p][:rc_o].tolist(),
                         out.weights[p][:rc_o].tolist()))
        want = sorted(zip(ref.dst[p][:rc_r].tolist(),
                          ref.src[p][:rc_r].tolist(),
                          ref.weights[p][:rc_r].tolist()))
        assert got == want
        # layout invariants: (dst) non-decreasing incl. the sink tail,
        # block_ptr = searchsorted of the shard bounds
        assert np.all(np.diff(out.dst[p].astype(np.int64)) >= 0)
        bounds = np.arange(n_shards + 1, dtype=np.int64) * out.block
        assert np.array_equal(out.block_ptr[p],
                              np.searchsorted(out.dst[p], bounds))
        # padding convention: src = shard base, w = 0
        assert np.all(out.src[p][rc_o:] == p * out.block)
        assert np.all(out.weights[p][rc_o:] == 0.0)


def test_apply_edge_delta_untouched_rows_not_copied_content():
    """Rows no delta edge touches keep identical content (the O(delta +
    affected rows) claim's observable half)."""
    n = 640
    src, dst, w = _coo(seed=3, n=n, e=4000)
    scsr = shard_edges(src, dst, w, n, 8, by="src")
    # confine the delta to shard 2's vertex range
    lo, hi = 2 * scsr.block, 3 * scsr.block
    add_src = np.arange(lo, lo + 8, dtype=np.int64)
    add_dst = np.arange(8, dtype=np.int64)
    d = D.EdgeDelta(0, 1, add_src, add_dst,
                    np.ones(8, dtype=np.float32),
                    np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, np.float32))
    out = D.apply_edge_delta(scsr, d)
    assert out is not None
    for p in range(8):
        if p == 2:
            continue
        np.testing.assert_array_equal(out.src[p], scsr.src[p])
        np.testing.assert_array_equal(out.dst[p], scsr.dst[p])
        np.testing.assert_array_equal(out.weights[p], scsr.weights[p])
        np.testing.assert_array_equal(out.block_ptr[p],
                                      scsr.block_ptr[p])


def test_apply_edge_delta_removal_mismatch_is_loud_none():
    n = 100
    src, dst, w = _coo(seed=2, n=n, e=500)
    scsr = shard_edges(src, dst, w, n, 4, by="src")
    ghost = D.EdgeDelta(
        0, 1, np.zeros(0, np.int64), np.zeros(0, np.int64),
        np.zeros(0, np.float32),
        rem_src=np.asarray([src[0]]), rem_dst=np.asarray([dst[0]]),
        rem_w=np.asarray([w[0] + 1.0], dtype=np.float32))  # wrong weight
    assert D.apply_edge_delta(scsr, ghost) is None


def test_fixpoint_bit_exact_after_splice_mesh():
    """The whole point: pagerank over the SPLICED resident layout is
    bit-exact vs the same kernel over a from-scratch reshard of the
    updated edge list — on mesh-of-1 and the 8-virtual-device mesh."""
    from memgraph_tpu.parallel.distributed import \
        pagerank_partition_centric
    from memgraph_tpu.parallel.mesh import get_mesh_context
    n = 300
    src, dst, w = _coo(seed=5, n=n, e=2400)
    for n_shards in (1, 8):
        ctx = get_mesh_context(n_shards)
        scsr = shard_edges(src, dst, w, n, n_shards, by="src")
        # net-negative delta: the splice always fits the resident rows
        # (capacity-overflow compaction has its own test above)
        d, coo = _delta_of(src, dst, w, seed=6, n=n, n_add=8,
                           n_rem=30, n_upd=5)
        spliced = D.apply_edge_delta(scsr, d)
        assert spliced is not None
        fresh = shard_edges(*coo, n, n_shards, by="src")
        # identical shapes -> identical compiled program; identical
        # edge order within rows -> bit-identical reductions
        r1, e1, i1 = pagerank_partition_centric(
            spliced.to_device(ctx), ctx, tol=TOL)
        r2, e2, i2 = pagerank_partition_centric(
            fresh.to_device(ctx), ctx, tol=TOL)
        np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
        assert (e1, i1) == (e2, i2)


def test_refresh_device_graph_equals_from_coo():
    n = 250
    src, dst, w = _coo(seed=7, n=n)
    g = from_coo(src, dst, w, n_nodes=n)
    d, coo = _delta_of(src, dst, w, seed=8, n=n)
    g2 = D.refresh_device_graph(g, d)
    ref = from_coo(*coo, n_nodes=n)
    assert g2.n_edges == ref.n_edges
    for field in ("row_ptr", "col_idx", "src_idx", "weights",
                  "csc_src", "csc_dst", "csc_weights", "out_degree"):
        np.testing.assert_array_equal(np.asarray(getattr(g2, field)),
                                      np.asarray(getattr(ref, field)))
    # wsum_adjust really is the rescale vector the delta implies
    deg_old = np.bincount(src, weights=w, minlength=n)
    deg_new = np.bincount(coo[0], weights=coo[2], minlength=n)
    np.testing.assert_allclose(d.wsum_adjust(n), deg_new - deg_old,
                               atol=1e-5)


# ==========================================================================
# 2. warm-started fixpoints per algorithm
# ==========================================================================


def _perturbed(seed=10, n=300, e=2400, adds_only=False):
    src, dst, w = _coo(seed=seed, n=n, e=e)
    g = from_coo(src, dst, w, n_nodes=n)
    rng = np.random.default_rng(seed + 1)
    add_src = rng.integers(0, n, 16).astype(np.int64)
    add_dst = rng.integers(0, n, 16).astype(np.int64)
    add_w = rng.random(16).astype(np.float32)
    if adds_only:
        d = D.EdgeDelta(0, 1, add_src, add_dst, add_w,
                        np.zeros(0, np.int64), np.zeros(0, np.int64),
                        np.zeros(0, np.float32))
    else:
        rem_i = rng.choice(e, 10, replace=False)
        d = D.EdgeDelta(0, 1, add_src, add_dst, add_w,
                        src[rem_i], dst[rem_i], w[rem_i])
    g2 = D.refresh_device_graph(g, d)
    assert g2 is not None
    return g, g2, d


@pytest.mark.parametrize("mesh", [None, 1, 8])
def test_pagerank_warm_residual_equivalent(mesh):
    """Warm-started pagerank converges to the SAME answer at the SAME
    tol (residual equivalence: final err <= tol on both paths), in no
    more iterations than cold."""
    from memgraph_tpu.ops.pagerank import pagerank
    g, g2, _ = _perturbed(seed=11)
    prev, _, _ = pagerank(g, tol=TOL, mesh=mesh)
    cold, err_c, it_c = pagerank(g2, tol=TOL, mesh=mesh)
    warm, err_w, it_w = pagerank(g2, tol=TOL, mesh=mesh,
                                 x0=np.asarray(prev))
    assert err_w <= TOL and err_c <= TOL
    assert it_w <= it_c
    # same fixpoint: both inside the tol ball of each other
    assert np.abs(np.asarray(cold) - np.asarray(warm)).max() < 10 * TOL


@pytest.mark.parametrize("mesh", [None, 8])
def test_katz_warm_residual_equivalent(mesh):
    from memgraph_tpu.ops.katz import katz_centrality
    g, g2, _ = _perturbed(seed=12)
    prev, _, _ = katz_centrality(g, tol=TOL, max_iterations=300,
                                 mesh=mesh)
    cold, err_c, it_c = katz_centrality(g2, tol=TOL, max_iterations=300,
                                        mesh=mesh)
    warm, err_w, it_w = katz_centrality(g2, tol=TOL, max_iterations=300,
                                        mesh=mesh, x0=np.asarray(prev))
    assert err_w <= TOL and err_c <= TOL
    assert it_w <= it_c
    assert np.abs(np.asarray(cold) - np.asarray(warm)).max() < 10 * TOL


@pytest.mark.parametrize("mesh", [None, 8])
def test_wcc_warm_adds_only_identical(mesh):
    """Adds-only: min-label propagation from the previous assignment
    lands on exactly the cold labels (components only merge)."""
    from memgraph_tpu.ops.components import weakly_connected_components
    g, g2, d = _perturbed(seed=13, adds_only=True)
    assert d.adds_only
    prev, _ = weakly_connected_components(g, mesh=mesh)
    cold, it_c = weakly_connected_components(g2, mesh=mesh)
    warm, it_w = weakly_connected_components(g2, mesh=mesh,
                                             comp0=np.asarray(prev))
    np.testing.assert_array_equal(np.asarray(cold), np.asarray(warm))
    assert it_w <= it_c


@pytest.mark.parametrize("mesh", [None, 8])
def test_labelprop_warm_adds_only_is_stable_fixpoint(mesh):
    """Adds-only labelprop warm start: the warm result must be a
    FIXPOINT of the election (re-running seeded with it converges in
    one unchanged round) — labelprop's answer is init-dependent, so
    fixpoint-ness (not bit-equality to cold) is the contract."""
    from memgraph_tpu.ops.labelprop import label_propagation
    g, g2, d = _perturbed(seed=14, adds_only=True)
    prev, _ = label_propagation(g, mesh=mesh)
    warm, _ = label_propagation(g2, mesh=mesh, labels0=np.asarray(prev))
    again, it2 = label_propagation(g2, mesh=mesh,
                                   labels0=np.asarray(warm))
    np.testing.assert_array_equal(np.asarray(warm), np.asarray(again))
    assert it2 <= 1


def test_monotone_unsafe_delta_forces_loud_cold():
    """A removal-carrying delta poisons WCC/labelprop seeds: warm_x0
    returns None, delta.cold_start_total moves, and the seed is
    dropped; pagerank's contraction seed survives the same delta."""
    g, g2, d = _perturbed(seed=15, adds_only=False)
    assert not d.adds_only
    gen = D.ResidentGraph("k", 0, g)
    gen.note_solution("wcc", ("wcc",), np.arange(g.n_nodes))
    gen.note_solution("pagerank", ("p",),
                      np.full(g.n_nodes, 1.0 / g.n_nodes))
    assert gen.apply(d)
    before = _metric("delta.cold_start_total")
    x0, reason = gen.warm_x0("wcc", ("wcc",))
    assert x0 is None and reason == "monotone_unsafe"
    assert _metric("delta.cold_start_total") == before + 1
    assert "wcc" not in gen.solutions          # poisoned seed dropped
    x0p, reason_p = gen.warm_x0("pagerank", ("p",))
    assert x0p is not None and reason_p == "contraction"


# ==========================================================================
# 3. ResidentGraph generations
# ==========================================================================


def test_empty_delta_bumps_version_without_rebuild():
    g = from_coo(*_coo(seed=16), n_nodes=200)
    gen = D.ResidentGraph("k", 3, g)
    gen.note_solution("pagerank", ("p",), np.zeros(200))
    snapshot = gen.graph
    assert gen.apply(D.empty_delta(3, 7))
    assert gen.version == 7
    assert gen.graph is snapshot               # no rebuild
    assert gen.solutions["pagerank"].monotone_ok


def test_accumulated_deltas_trigger_compaction(monkeypatch):
    monkeypatch.setattr(D, "DELTA_COMPACT_FRACTION", 0.01)
    n = 200
    src, dst, w = _coo(seed=17, n=n)
    gen = D.ResidentGraph("k", 0, from_coo(src, dst, w, n_nodes=n))
    from memgraph_tpu.parallel.mesh import get_mesh_context
    ctx = get_mesh_context(4)
    gen.ensure_sharded(ctx, by="src")
    before = _metric("delta.compacted_total")
    rng = np.random.default_rng(18)
    version = 0
    for i in range(4):
        version += 1
        add_s = rng.integers(0, n, 8).astype(np.int64)
        add_d = rng.integers(0, n, 8).astype(np.int64)
        d = D.EdgeDelta(version - 1, version, add_s, add_d,
                        np.ones(8, np.float32), np.zeros(0, np.int64),
                        np.zeros(0, np.int64), np.zeros(0, np.float32))
        assert gen.apply(d, ctx)
    assert _metric("delta.compacted_total") > before
    assert gen.delta_edges == 0                # accumulation reset
    # post-compaction the resident layout still matches from-scratch
    hv = gen.host_variants[("src", False)]
    ref = shard_edges(*gen.graph.host_coo, n, 4, by="src")
    sink = n
    for p in range(4):
        rc = int(np.searchsorted(hv.dst[p], sink))
        rr = int(np.searchsorted(ref.dst[p], sink))
        assert rc == rr
        assert sorted(zip(hv.dst[p][:rc], hv.src[p][:rc])) == \
            sorted(zip(ref.dst[p][:rr], ref.src[p][:rr]))


def test_resident_registry_lru_and_gauge():
    reg = D.ResidentRegistry(capacity=2)
    for i in range(3):
        g = from_coo(*_coo(seed=20 + i, n=50, e=200), n_nodes=50)
        reg.put(D.ResidentGraph(f"k{i}", 0, g))
    assert len(reg) == 2
    assert reg.get("k0") is None               # LRU-evicted
    assert reg.get("k2") is not None
    assert _metric("delta.resident_generations") == 2.0


# ==========================================================================
# 4. LocalWarmPool against a real storage change log
# ==========================================================================


def _storage_graph(n=40, extra_edges=()):
    storage = InMemoryStorage()
    acc = storage.access()
    vas = [acc.create_vertex() for _ in range(n)]
    rng = np.random.default_rng(0)
    for _ in range(n * 4):
        a, b = rng.integers(0, n, 2)
        acc.create_edge(vas[a], vas[b],
                        storage.edge_type_mapper.name_to_id("E"))
    acc.commit()
    return storage


def _export(storage):
    acc = storage.access()
    g = csr.export_csr(acc, to_device=False)
    return acc, g, acc.topology_snapshot


def test_local_warm_pool_commit_then_call():
    from memgraph_tpu.ops.pagerank import pagerank
    pool = D.LocalWarmPool()
    storage = _storage_graph()
    acc1, g1, v1 = _export(storage)
    assert pool.prepare(storage, g1, v1, "pagerank",
                        ("p",)) == (None, None)
    r1, _, _ = pagerank(g1, tol=TOL)
    pool.store(storage, g1, v1, "pagerank", ("p",), np.asarray(r1))
    # unchanged graph: the stored solution serves VERBATIM (result-
    # cache semantics — identical CALLs return identical bytes)
    hit, seed = pool.prepare(storage, g1, v1, "pagerank", ("p",))
    assert seed is None
    np.testing.assert_array_equal(hit, np.asarray(r1))
    acc1.abort()

    # commit: one new edge -> warm seed (not a hit) at the new version
    acc = storage.access()
    verts = list(storage._vertices.keys())
    acc.create_edge(acc.find_vertex(verts[0]),
                    acc.find_vertex(verts[1]),
                    storage.edge_type_mapper.name_to_id("E"))
    acc.commit()
    acc2, g2, v2 = _export(storage)
    assert v2 > v1
    hit, x0 = pool.prepare(storage, g2, v2, "pagerank", ("p",))
    assert hit is None and x0 is not None
    np.testing.assert_array_equal(x0, np.asarray(r1))
    acc2.abort()


def test_local_warm_pool_wcc_cold_on_removal_and_wrap():
    pool = D.LocalWarmPool()
    storage = _storage_graph()
    acc1, g1, v1 = _export(storage)
    pool.store(storage, g1, v1, "wcc", ("wcc",), np.arange(g1.n_nodes))
    acc1.abort()

    # removal commit -> monotone-unsafe -> LOUD cold
    acc = storage.access()
    edge_gid = next(iter(storage._edges))
    ea = acc.find_edge(edge_gid)
    acc.delete_edge(ea)
    acc.commit()
    acc2, g2, v2 = _export(storage)
    before = _metric("delta.cold_start_total")
    assert pool.prepare(storage, g2, v2, "wcc",
                        ("wcc",)) == (None, None)
    assert _metric("delta.cold_start_total") == before + 1
    acc2.abort()

    # wrapped log -> unknowable -> cold for the monotone-gated algo
    pool.store(storage, g2, v2, "wcc", ("wcc",), np.arange(g2.n_nodes))
    for i in range(1100):
        storage._bump_topology({0})
    acc3, g3, v3 = _export(storage)
    assert isinstance(storage.changes_between(v2, v3),
                      ChangeLogUnknowable)
    assert pool.prepare(storage, g3, v3, "wcc",
                        ("wcc",)) == (None, None)
    acc3.abort()


# ==========================================================================
# 5. kernel-server delta protocol
# ==========================================================================


@pytest.fixture(scope="module")
def dserver(tmp_path_factory):
    from memgraph_tpu.server.kernel_server import (KernelClient,
                                                   KernelServer)
    sock = str(tmp_path_factory.mktemp("ks") / "kernel.sock")
    srv = KernelServer(sock, wedge_after_s=60)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    deadline = time.monotonic() + 30
    client = None
    while time.monotonic() < deadline:
        try:
            client = KernelClient(sock, timeout=120)
            break
        except OSError:
            time.sleep(0.05)
    assert client is not None
    yield srv, client, sock
    try:
        client.shutdown()
        client.close()
    except OSError:
        pass


def _incident_payload(src, dst, changed, n):
    bitmap = np.zeros(n, dtype=bool)
    bitmap[np.asarray(changed, dtype=np.int64)] = True
    sel = bitmap[src] | bitmap[dst]
    return (src[sel].astype(np.int64), dst[sel].astype(np.int64),
            np.ones(int(sel.sum()), dtype=np.float32))


def test_kernel_server_delta_refresh_and_warm_start(dserver):
    """Full import at v1; commit ships ONLY the delta payload at v2;
    the server splices the resident generation and warm-starts — the
    reply matches a cold run on the updated graph, residual-equivalent
    at the same tol, with warm_started=True on the second call."""
    _srv, client, _ = dserver
    rng = np.random.default_rng(30)
    n, e = 400, 3000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    r1, _, _ = client.pagerank(src=src, dst=dst, n_nodes=n,
                               graph_key="dg1", graph_version=1,
                               tol=TOL)
    add_src = rng.integers(0, n, 20)
    add_dst = rng.integers(0, n, 20)
    src2 = np.concatenate([src, add_src])
    dst2 = np.concatenate([dst, add_dst])
    changed = np.unique(np.concatenate([add_src,
                                        add_dst])).astype(np.int32)
    inc_src, inc_dst, inc_w = _incident_payload(src2, dst2, changed, n)
    r2, err2, it2 = client.pagerank(
        n_nodes=n, graph_key="dg1", graph_version=2, base_version=1,
        changed=changed, inc_src=inc_src, inc_dst=inc_dst, inc_w=inc_w,
        tol=TOL)
    assert err2 <= TOL
    from memgraph_tpu.parallel.analytics import pagerank_mesh
    from memgraph_tpu.parallel.mesh import get_mesh_context
    ref, _, it_ref = pagerank_mesh(from_coo(src2, dst2, n_nodes=n),
                                   get_mesh_context(1), tol=TOL)
    assert np.abs(np.asarray(ref)
                  - np.asarray(r2)[:n]).max() < 10 * TOL
    assert it2 <= it_ref                      # warm never slower
    applied = _metric("delta.applied_total")
    assert applied >= 1


def test_kernel_server_wcc_monotone_gate(dserver):
    """WCC over the resident generation: warm on repeat, typed LOUD
    cold (warm_started=False) after a removal delta — and the results
    always match the cold reference."""
    from memgraph_tpu.ops.components import weakly_connected_components
    _srv, client, _ = dserver
    rng = np.random.default_rng(31)
    n, e = 300, 1600
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    h1, out1 = client.semiring(algorithm="wcc", src=src, dst=dst,
                               n_nodes=n, graph_key="dg2",
                               graph_version=1)
    assert h1["warm_started"] is False
    h2, out2 = client.semiring(algorithm="wcc", graph_key="dg2",
                               n_nodes=n, graph_version=1)
    assert h2["warm_started"] is True
    np.testing.assert_array_equal(out1["components"],
                                  out2["components"])
    # removal: drop two edges -> monotone-unsafe -> loud cold
    src3, dst3 = np.delete(src, [0, 1]), np.delete(dst, [0, 1])
    changed = np.unique(np.concatenate(
        [src[:2], dst[:2]])).astype(np.int32)
    inc_src, inc_dst, inc_w = _incident_payload(src3, dst3, changed, n)
    before = _metric("delta.cold_start_total")
    h3, out3 = client.semiring(
        algorithm="wcc", graph_key="dg2", n_nodes=n, graph_version=2,
        base_version=1, changed=changed, inc_src=inc_src,
        inc_dst=inc_dst, inc_w=inc_w)
    assert h3["warm_started"] is False
    assert _metric("delta.cold_start_total") == before + 1
    ref, _ = weakly_connected_components(from_coo(src3, dst3,
                                                  n_nodes=n))
    np.testing.assert_array_equal(np.asarray(ref),
                                  out3["components"][:n])


def test_kernel_server_stale_generation_is_never_served(dserver):
    """A version bump with NO usable delta and NO edge arrays must fail
    typed (invalid), never silently serve the old generation."""
    from memgraph_tpu.server.kernel_server import KernelServerError
    _srv, client, _ = dserver
    rng = np.random.default_rng(32)
    n, e = 100, 500
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    client.pagerank(src=src, dst=dst, n_nodes=n, graph_key="dg3",
                    graph_version=1, tol=TOL)
    with pytest.raises(KernelServerError):
        client.pagerank(n_nodes=n, graph_key="dg3", graph_version=2,
                        tol=TOL)


def test_serving_meta_ships_delta_then_full_after_wrap():
    """The route layer's envelope: delta payload (no edge re-ship)
    while the change log covers the gap; full re-ship once it wrapped
    (the typed ChangeLogUnknowable fallback)."""
    from memgraph_tpu.procedures.graph_algorithms import (
        _PPR_PUSHED, _PPR_PUSHED_LOCK, _note_ppr_pushed,
        _serving_delta_meta)
    from memgraph_tpu.procedures.mock import mock_context

    storage = _storage_graph()
    acc, g, v = _export(storage)

    class _Ctx:
        pass

    ctx = _Ctx()
    ctx.storage = storage
    ctx.accessor = acc
    key = "analytics:test"
    meta = _serving_delta_meta(ctx, g, "sock", key)
    assert meta["send_graph"]                  # never pushed
    _note_ppr_pushed("sock", key, v, g.node_gids)
    # same version: resident, nothing to ship
    meta = _serving_delta_meta(ctx, g, "sock", key)
    assert not meta["send_graph"] and meta["base_version"] == v
    acc.abort()

    # one commit -> delta payload, no full graph
    acc2 = storage.access()
    gids = list(storage._vertices.keys())
    acc2.create_edge(acc2.find_vertex(gids[0]),
                     acc2.find_vertex(gids[1]),
                     storage.edge_type_mapper.name_to_id("E"))
    acc2.commit()
    acc3, g3, v3 = _export(storage)
    ctx.accessor = acc3
    meta = _serving_delta_meta(ctx, g3, "sock", key)
    assert not meta["send_graph"]
    assert meta["base_version"] == v and len(meta["inc_src"]) > 0
    acc3.abort()

    # wrap the log -> unknowable -> full re-ship
    for _ in range(1100):
        storage._bump_topology({0})
    acc4, g4, v4 = _export(storage)
    ctx.accessor = acc4
    meta = _serving_delta_meta(ctx, g4, "sock", key)
    assert meta["send_graph"] and meta["base_version"] is None
    acc4.abort()
    with _PPR_PUSHED_LOCK:
        _PPR_PUSHED.pop(("sock", key), None)


# ==========================================================================
# 6. change-log wrap matrix
# ==========================================================================


def test_oldest_logged_version_monotone_and_wrap_typed():
    storage = InMemoryStorage()
    assert storage.oldest_logged_version == 1
    lows = []
    for i in range(1500):
        storage._bump_topology({i})
        lows.append(storage.oldest_logged_version)
    assert all(b >= a for a, b in zip(lows, lows[1:]))
    assert storage.oldest_logged_version == \
        storage.topology_version - 1024 + 1
    verdict = storage.changes_between(0, storage.topology_version)
    assert isinstance(verdict, ChangeLogUnknowable) and not verdict
    assert verdict.reason == "log_wrapped"
    assert verdict.oldest_logged_version == \
        storage.oldest_logged_version
    # in-range queries still answer exactly
    v = storage.topology_version
    assert storage.changes_between(v - 3, v) == \
        frozenset({1497, 1498, 1499})


def test_graph_cache_full_export_on_wrapped_log():
    """GraphCache's delta export consumer: a wrapped log must fall back
    to the full export (counted fallback_rebuild) and still serve the
    CORRECT fresh snapshot."""
    from memgraph_tpu.ops.csr import GraphCache
    storage = _storage_graph()
    cache = GraphCache()
    acc1, _, _ = _export(storage)
    g1 = cache.get(acc1)
    acc1.abort()
    # wrap, then commit one more edge
    for _ in range(1100):
        storage._bump_topology(set())
    acc = storage.access()
    gids = list(storage._vertices.keys())
    acc.create_edge(acc.find_vertex(gids[2]), acc.find_vertex(gids[3]),
                    storage.edge_type_mapper.name_to_id("E"))
    acc.commit()
    before = _metric("delta.fallback_rebuild_total")
    acc2 = storage.access()
    g2 = cache.get(acc2)
    assert g2.n_edges == g1.n_edges + 1
    assert _metric("delta.fallback_rebuild_total") >= before
    acc2.abort()


def test_compile_edge_delta_typed_verdicts():
    storage = _storage_graph()
    acc1, g1, v1 = _export(storage)
    acc1.abort()
    acc = storage.access()
    gids = list(storage._vertices.keys())
    acc.create_edge(acc.find_vertex(gids[4]), acc.find_vertex(gids[5]),
                    storage.edge_type_mapper.name_to_id("E"))
    acc.commit()
    acc2, g2, v2 = _export(storage)
    d = D.compile_edge_delta(storage, g1, g2, v1, v2)
    assert isinstance(d, D.EdgeDelta)
    assert len(d.add_src) == 1 and d.adds_only
    # same-version: the empty delta
    d0 = D.compile_edge_delta(storage, g2, g2, v2, v2)
    assert d0.n_delta == 0
    # wrapped: the typed verdict rides through
    for _ in range(1100):
        storage._bump_topology({0})
    acc3, g3, v3 = _export(storage)
    verdict = D.compile_edge_delta(storage, g2, g3, v2, v3)
    assert isinstance(verdict, ChangeLogUnknowable)
    acc2.abort()
    acc3.abort()


# ==========================================================================
# 7. device_chaos: fault mid-(delta apply -> dispatch)
# ==========================================================================


@pytest.mark.device_chaos
def test_device_fault_after_delta_apply_resumes_consistent(dserver):
    """A device fault on the FIRST chunk dispatch AFTER a delta apply
    is absorbed by the checkpoint layer (resume from the iteration-0
    checkpoint) and the reply must come from the CONSISTENT new
    generation — never a half-applied or stale layout. A payload-free
    follow-up must also serve generation v2."""
    from memgraph_tpu.parallel.analytics import pagerank_mesh
    from memgraph_tpu.parallel.mesh import get_mesh_context
    _srv, client, _ = dserver
    rng = np.random.default_rng(40)
    n, e = 200, 1200
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    client.pagerank(src=src, dst=dst, n_nodes=n, graph_key="chaos",
                    graph_version=1, tol=TOL)
    add_src = rng.integers(0, n, 10)
    add_dst = rng.integers(0, n, 10)
    src2 = np.concatenate([src, add_src])
    dst2 = np.concatenate([dst, add_dst])
    changed = np.unique(np.concatenate([add_src,
                                        add_dst])).astype(np.int32)
    inc_src, inc_dst, inc_w = _incident_payload(src2, dst2, changed, n)
    resumes0 = _metric("analytics.resume_total")
    # hit 1 is the _supervised entry fault point (before the resolve);
    # hit 2 is the first CHUNK dispatch — i.e. after the delta apply
    FI.arm("device.call", "raise", at=2)
    try:
        r, err, _ = client.pagerank(
            n_nodes=n, graph_key="chaos", graph_version=2,
            base_version=1, changed=changed, inc_src=inc_src,
            inc_dst=inc_dst, inc_w=inc_w, tol=TOL)
    finally:
        FI.reset()
    assert _metric("analytics.resume_total") > resumes0  # fault FIRED
    assert err <= TOL
    ref, _, _ = pagerank_mesh(from_coo(src2, dst2, n_nodes=n),
                              get_mesh_context(1), tol=TOL)
    assert np.abs(np.asarray(ref) - np.asarray(r)[:n]).max() < 10 * TOL
    # payload-free follow-up: the generation must already be at v2
    # (the apply survived the dispatch fault exactly once)
    r2, err2, _ = client.pagerank(n_nodes=n, graph_key="chaos",
                                  graph_version=2, base_version=2,
                                  tol=TOL)
    assert err2 <= TOL
    assert np.abs(np.asarray(ref)
                  - np.asarray(r2)[:n]).max() < 10 * TOL
