"""RBAC enforcement e2e: privileges checked per query over Bolt."""

import socket

import pytest

from memgraph_tpu.auth.auth import Auth
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.server.bolt import BoltServer
from memgraph_tpu.server.client import BoltClient, BoltClientError
from memgraph_tpu.storage import InMemoryStorage


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def rbac():
    auth = Auth()
    auth.create_user("admin", "adminpw")  # first user → all privileges
    auth.create_user("reader", "readerpw")
    auth.grant("reader", ["MATCH"])
    ictx = InterpreterContext(InMemoryStorage())
    ictx.auth_store = auth
    port = _free_port()
    srv = BoltServer(ictx, "127.0.0.1", port, auth)
    thread, loop = srv.run_in_thread()
    yield {"port": port, "auth": auth, "ictx": ictx}
    loop.call_soon_threadsafe(loop.stop)


def test_admin_has_all(rbac):
    c = BoltClient(port=rbac["port"], username="admin", password="adminpw")
    c.execute("CREATE (:T {v: 1})")
    c.execute("CREATE INDEX ON :T(v)")
    _, rows, _ = c.execute("MATCH (n:T) RETURN count(n)")
    assert rows == [[1]]
    c.close()


def test_reader_read_only(rbac):
    admin = BoltClient(port=rbac["port"], username="admin",
                       password="adminpw")
    admin.execute("CREATE (:T {v: 1})")
    admin.close()
    c = BoltClient(port=rbac["port"], username="reader",
                   password="readerpw")
    _, rows, _ = c.execute("MATCH (n:T) RETURN count(n)")
    assert rows == [[1]]
    with pytest.raises(BoltClientError):
        c.execute("CREATE (:Nope)")
    c.reset()
    with pytest.raises(BoltClientError):
        c.execute("CREATE INDEX ON :T(x)")
    c.reset()
    with pytest.raises(BoltClientError):
        c.execute("SHOW USERS")  # AUTH privilege missing
    c.close()


def test_grant_and_revoke_via_cypher(rbac):
    admin = BoltClient(port=rbac["port"], username="admin",
                       password="adminpw")
    admin.execute("GRANT CREATE TO reader")
    c = BoltClient(port=rbac["port"], username="reader",
                   password="readerpw")
    c.execute("CREATE (:Allowed)")
    c.close()
    admin.execute("REVOKE CREATE FROM reader")
    c = BoltClient(port=rbac["port"], username="reader",
                   password="readerpw")
    with pytest.raises(BoltClientError):
        c.execute("CREATE (:DeniedAgain)")
    c.close()
    _, rows, _ = admin.execute("SHOW PRIVILEGES FOR reader")
    privs = [r[0] for r in rows]
    assert "MATCH" in privs and "CREATE" not in privs
    admin.close()


def test_roles_via_cypher(rbac):
    admin = BoltClient(port=rbac["port"], username="admin",
                       password="adminpw")
    admin.execute("CREATE ROLE writers")
    admin.execute("GRANT CREATE TO writers")
    admin.execute("SET ROLE FOR reader TO writers")
    c = BoltClient(port=rbac["port"], username="reader",
                   password="readerpw")
    c.execute("CREATE (:ViaRole)")  # privilege via the role
    c.close()
    _, rows, _ = admin.execute("SHOW ROLES")
    assert rows == [["writers"]]
    admin.close()


def test_fine_grained_write_privileges(rbac):
    admin = BoltClient(port=rbac["port"], username="admin",
                       password="adminpw")
    admin.execute("CREATE (:FG {v: 1})")
    admin.execute("GRANT MATCH, SET TO reader")
    c = BoltClient(port=rbac["port"], username="reader",
                   password="readerpw")
    c.execute("MATCH (n:FG) SET n.v = 2")  # SET granted
    with pytest.raises(BoltClientError):
        c.execute("MATCH (n:FG) DETACH DELETE n")  # DELETE not granted
    c.reset()
    with pytest.raises(BoltClientError):
        c.execute("CREATE (:Nope)")  # CREATE not granted
    c.close()
    admin.close()


def test_triggers_bypass_rbac(rbac):
    """Triggers run as the system even when users exist."""
    from memgraph_tpu.query.triggers import global_trigger_store
    global_trigger_store(rbac["ictx"])
    admin = BoltClient(port=rbac["port"], username="admin",
                       password="adminpw")
    admin.execute("CREATE TRIGGER t ON CREATE AFTER COMMIT "
                  "EXECUTE MERGE (c:Cnt) SET c.n = coalesce(c.n, 0) + 1")
    admin.execute("CREATE (:Fire)")
    _, rows, _ = admin.execute("MATCH (c:Cnt) RETURN c.n")
    assert rows == [[1]]
    admin.close()


def test_grant_all_privileges_syntax(rbac):
    admin = BoltClient(port=rbac["port"], username="admin",
                       password="adminpw")
    admin.execute("CREATE USER power")
    admin.execute("GRANT ALL PRIVILEGES TO power")
    _, rows, _ = admin.execute("SHOW PRIVILEGES FOR power")
    assert len(rows) >= 20
    admin.close()


def test_roles_function(rbac):
    rbac["auth"].create_role("analyst")
    rbac["auth"].set_role("reader", "analyst")
    c = BoltClient(port=rbac["port"], username="reader",
                   password="readerpw")
    _, rows, _ = c.execute("RETURN roles(), username()")
    assert rows == [[["analyst"], "reader"]]
    c.close()


def test_roles_db_name_type_checked(rbac):
    c = BoltClient(port=rbac["port"], username="reader",
                   password="readerpw")
    with pytest.raises(BoltClientError):
        c.execute("RETURN roles(123)")
    c.reset()
    _, rows, _ = c.execute("RETURN roles('memgraph')")
    assert rows == [[[]]]
    c.close()


def test_do_subqueries_enforce_rbac(rbac):
    # reader can CALL read procedures but has no write privileges: a write
    # smuggled through do.when's sub-query must still be denied
    rbac["auth"].grant("reader", ["MODULE_READ"])
    c = BoltClient(port=rbac["port"], username="reader",
                   password="readerpw")
    with pytest.raises(BoltClientError):
        c.execute("CALL do.when(true, 'CREATE (:Smuggled)', 'RETURN 1') "
                  "YIELD value RETURN 1")
    c.reset()
    admin = BoltClient(port=rbac["port"], username="admin",
                       password="adminpw")
    _, out, _ = admin.execute("MATCH (n:Smuggled) RETURN count(n)")
    assert out == [[0]]
    # read-only sub-queries still work for the reader
    _, out, _ = c.execute(
        "CALL do.when(true, 'RETURN 1 AS a', 'RETURN 2 AS a') "
        "YIELD value RETURN value.a")
    assert out == [[1]]
    c.close()
    admin.close()


def test_load_csv_requires_read_file(rbac, tmp_path):
    # advisor finding: LOAD CSV must require READ_FILE, else any
    # authenticated user can read arbitrary server files
    # (reference: required_privileges.cpp:283-293)
    p = tmp_path / "x.csv"
    p.write_text("a,b\n1,2\n")
    c = BoltClient(port=rbac["port"], username="reader",
                   password="readerpw")
    with pytest.raises(BoltClientError):
        c.execute(f"LOAD CSV FROM '{p}' WITH HEADER AS row RETURN row")
    c.reset()
    rbac["auth"].grant("reader", ["READ_FILE"])
    _, rows, _ = c.execute(
        f"LOAD CSV FROM '{p}' WITH HEADER AS row RETURN row.a")
    assert rows == [["1"]]
    c.close()


def test_free_memory_requires_privilege(rbac):
    c = BoltClient(port=rbac["port"], username="reader",
                   password="readerpw")
    with pytest.raises(BoltClientError):
        c.execute("FREE MEMORY")
    c.reset()
    rbac["auth"].grant("reader", ["FREE_MEMORY"])
    c.execute("FREE MEMORY")
    c.close()


def test_effective_privileges_matches_enforcement(rbac):
    # advisor finding: SHOW PRIVILEGES must use the same resolution order
    # as enforcement (user deny > user grant > role deny > role grant)
    auth = rbac["auth"]
    auth.create_role("denier")
    auth.deny("denier", ["MATCH"])
    auth.set_role("reader", "denier")
    # user-level GRANT (set in the fixture) beats role-level DENY
    assert auth.has_privilege("reader", "MATCH")
    eff = dict(auth.effective_privileges("reader"))
    assert eff["MATCH"] == "GRANT"
    # remove the user-level grant: role deny now wins for both views
    auth.revoke("reader", ["MATCH"])
    assert not auth.has_privilege("reader", "MATCH")
    eff = dict(auth.effective_privileges("reader"))
    assert eff["MATCH"] == "DENY"
