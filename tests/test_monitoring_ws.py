"""Websocket monitoring server (observability/monitoring_ws.py):
handshake, auth gate, live log streaming, metrics frames.
Reference behavior: communication/websocket/{listener,session}.cpp.
"""

import base64
import hashlib
import json
import logging
import os
import socket
import struct
import time

import pytest

from memgraph_tpu.observability.monitoring_ws import MonitoringServer

GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class WSClient:
    """Minimal RFC 6455 client for the tests (masked frames, as the RFC
    requires of clients — which also exercises the server's unmasking)."""

    def __init__(self, port, timeout=5.0):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            (f"GET / HTTP/1.1\r\nHost: 127.0.0.1:{port}\r\n"
             "Upgrade: websocket\r\nConnection: Upgrade\r\n"
             f"Sec-WebSocket-Key: {key}\r\n"
             "Sec-WebSocket-Version: 13\r\n\r\n").encode())
        resp = b""
        while b"\r\n\r\n" not in resp:
            resp += self.sock.recv(4096)
        assert b"101" in resp.split(b"\r\n")[0]
        want = base64.b64encode(
            hashlib.sha1((key + GUID).encode()).digest())
        assert want in resp

    def send_json(self, obj):
        payload = json.dumps(obj).encode()
        mask = os.urandom(4)
        head = bytes([0x81])
        n = len(payload)
        if n < 126:
            head += bytes([0x80 | n])
        else:
            head += bytes([0x80 | 126]) + struct.pack(">H", n)
        body = bytes(b ^ mask[i & 3] for i, b in enumerate(payload))
        self.sock.sendall(head + mask + body)

    def recv_json(self):
        op, payload = self._recv_frame()
        assert op == 0x1
        return json.loads(payload)

    def _recv_frame(self):
        def rx(n):
            buf = b""
            while len(buf) < n:
                chunk = self.sock.recv(n - len(buf))
                if not chunk:
                    raise ConnectionError
                buf += chunk
            return buf
        b0, b1 = rx(2)
        n = b1 & 0x7F
        if n == 126:
            (n,) = struct.unpack(">H", rx(2))
        elif n == 127:
            (n,) = struct.unpack(">Q", rx(8))
        assert not (b1 & 0x80), "server frames must be unmasked"
        return b0 & 0x0F, rx(n)

    def close(self):
        self.sock.close()


@pytest.fixture
def server():
    root = logging.getLogger()
    old_level = root.level
    root.setLevel(logging.INFO)   # main.py's --log-level does this in prod
    srv = MonitoringServer("127.0.0.1", 0)
    srv.start()
    yield srv
    srv.stop()
    root.setLevel(old_level)


def test_log_streaming(server):
    c = WSClient(server.port)
    time.sleep(0.2)     # session registration is async
    logging.getLogger("memgraph_tpu.test").info("hello from the log")
    msg = c.recv_json()
    assert msg["event"] == "log"
    assert msg["message"] == "hello from the log"
    assert msg["level"] == "info"
    c.close()


def test_metrics_frame(server):
    class FakeMetrics:
        def snapshot(self):
            return {"QueryExecutionLatency_us_count": 42}
    server.metrics = FakeMetrics()
    c = WSClient(server.port)
    c.send_json({"command": "show_metrics"})
    msg = c.recv_json()
    assert msg["event"] == "metrics"
    assert msg["metrics"]["QueryExecutionLatency_us_count"] == 42
    c.close()


def test_multiple_sessions_all_receive(server):
    c1, c2 = WSClient(server.port), WSClient(server.port)
    time.sleep(0.2)
    logging.getLogger("x").warning("broadcast me")
    for c in (c1, c2):
        msg = c.recv_json()
        assert msg["message"] == "broadcast me"
        assert msg["level"] == "warning"
        c.close()


def test_auth_gate(tmp_path):
    from memgraph_tpu.auth.auth import Auth
    auth = Auth(str(tmp_path / "auth.json"))
    auth.create_user("admin", "pw")
    srv = MonitoringServer("127.0.0.1", 0, auth=auth)
    srv.start()
    try:
        # wrong password: refused and disconnected
        c = WSClient(srv.port)
        c.send_json({"username": "admin", "password": "nope"})
        assert c.recv_json()["success"] is False
        c.close()
        # correct password: authenticated, then receives logs
        c = WSClient(srv.port)
        c.send_json({"username": "admin", "password": "pw"})
        assert c.recv_json()["success"] is True
        time.sleep(0.2)
        logging.getLogger("y").error("secured line")
        assert c.recv_json()["message"] == "secured line"
        c.close()
    finally:
        srv.stop()


def test_e2e_through_main(tmp_path):
    """--monitoring-port on the composition root serves live logs."""
    import subprocess
    import sys
    with socket.socket() as p:
        p.bind(("127.0.0.1", 0))
        port = p.getsockname()[1]
    with socket.socket() as p:
        p.bind(("127.0.0.1", 0))
        bolt_port = p.getsockname()[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "memgraph_tpu.main",
         "--bolt-port", str(bolt_port),
         "--monitoring-port", str(port),
         "--data-directory", str(tmp_path / "data"),
         "--log-level", "INFO"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + 30
        c = None
        while time.time() < deadline:
            try:
                c = WSClient(port, timeout=5)
                break
            except OSError:
                time.sleep(0.3)
        assert c is not None, "websocket monitoring never came up"
        # a Bolt connection generates server log lines -> pushed frames
        from memgraph_tpu.server.client import BoltClient
        bc = BoltClient(port=bolt_port)
        bc.execute("RETURN 1")
        bc.close()
        msg = c.recv_json()
        assert msg["event"] == "log" and msg["message"]
        c.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_broadcast_never_blocks_caller(server):
    """A stalled client must not delay logging threads: broadcast() only
    enqueues; the dedicated drain thread owns every network send."""
    c = WSClient(server.port)
    time.sleep(0.2)              # session registered
    t0 = time.time()
    for i in range(200):
        server.broadcast({"event": "log", "message": f"m{i}"})
    # 200 enqueues complete far faster than one 5s send timeout
    assert time.time() - t0 < 1.0
    got = c.recv_json()
    assert got["event"] == "log"
    c.close()


def test_full_queue_drops_records_not_callers():
    srv = MonitoringServer("127.0.0.1", 0)
    # not started: no drain thread, so the queue fills deterministically
    for i in range(srv.QUEUE_CAPACITY + 50):
        srv.broadcast({"event": "log", "message": str(i)})
    assert srv.dropped_records == 50
