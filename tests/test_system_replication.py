"""System-state replication: auth + multi-database DDL survive failover.

Reference contract (/root/reference/src/system/transaction.cpp +
single-writer gate interpreter.cpp:9908-9917): non-graph state changes on
MAIN — users, roles, privileges, CREATE/DROP DATABASE — replicate to
replicas as ordered system transactions, so a promoted replica serves the
same users and databases.
"""

import socket

import pytest

from memgraph_tpu.auth.auth import Auth
from memgraph_tpu.dbms.dbms import DbmsHandler
from memgraph_tpu.query.interpreter import Interpreter, InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _rows(interp, q):
    _, rows, _ = interp.execute(q)
    return rows


@pytest.fixture
def cluster(tmp_path):
    def make(name):
        dbms = DbmsHandler(recover_on_startup=False)
        ictx = dbms.get("memgraph")
        ictx.auth_store = Auth()
        interp = Interpreter(ictx)
        # first user gets all privileges; run the session as it so RBAC
        # does not reject the test's admin DDL
        ictx.auth_store.create_user("root", "rootpw")
        interp.username = "root"
        return ictx, interp

    main_ictx, main = make("main")
    rep_ictx, rep = make("replica")
    port = _free_port()
    rep.execute(f"SET REPLICATION ROLE TO REPLICA WITH PORT {port}")
    yield main, rep, main_ictx, rep_ictx, port
    if getattr(rep_ictx, "replication", None) and \
            rep_ictx.replication.replica_server:
        rep_ictx.replication.replica_server.stop()
    if getattr(main_ictx, "replication", None):
        for c in main_ictx.replication.replicas.values():
            c.close()


def test_auth_and_ddl_replicate_live(cluster):
    main, rep, main_ictx, rep_ictx, port = cluster
    main.execute(f"REGISTER REPLICA r1 SYNC TO '127.0.0.1:{port}'")

    main.execute("CREATE USER ada IDENTIFIED BY 'pw1'")
    main.execute("CREATE ROLE admin")
    main.execute("GRANT MATCH, CREATE TO admin")
    main.execute("SET ROLE FOR ada TO admin")
    main.execute("CREATE DATABASE analytics")

    # replica has the same users/roles/databases
    assert "ada" in rep_ictx.auth_store.users()
    assert "admin" in rep_ictx.auth_store.roles()
    assert rep_ictx.auth_store.user_roles("ada") == ["admin"]
    assert rep_ictx.auth_store.authenticate("ada", "pw1")
    assert "analytics" in rep_ictx.dbms.names()

    # drops replicate too
    main.execute("DROP DATABASE analytics")
    main.execute("DROP USER ada")
    assert "ada" not in rep_ictx.auth_store.users()
    assert "analytics" not in rep_ictx.dbms.names()


def test_system_state_in_catchup(cluster):
    """State created BEFORE registration reaches the replica via the
    full-state system catch-up at registration."""
    main, rep, main_ictx, rep_ictx, port = cluster
    main.execute("CREATE USER grace IDENTIFIED BY 's3cret'")
    main.execute("CREATE DATABASE ml")
    main.execute(f"REGISTER REPLICA r1 SYNC TO '127.0.0.1:{port}'")

    assert "grace" in rep_ictx.auth_store.users()
    assert rep_ictx.auth_store.authenticate("grace", "s3cret")
    assert "ml" in rep_ictx.dbms.names()


def test_failover_preserves_system_state(cluster):
    """The VERDICT e2e: create user + database on MAIN, fail over, both
    exist on the new MAIN."""
    main, rep, main_ictx, rep_ictx, port = cluster
    main.execute(f"REGISTER REPLICA r1 SYNC TO '127.0.0.1:{port}'")
    main.execute("CREATE USER oncall IDENTIFIED BY 'page'")
    main.execute("GRANT MATCH TO oncall")
    main.execute("CREATE DATABASE prod")
    main.execute("CREATE (:Doc {id: 1})")

    # MAIN dies; promote the replica
    for c in main_ictx.replication.replicas.values():
        c.close()
    rep.execute("SET REPLICATION ROLE TO MAIN")

    # graph data AND system state are present on the new MAIN
    assert _rows(rep, "MATCH (n:Doc) RETURN n.id") == [[1]]
    assert "oncall" in rep_ictx.auth_store.users()
    assert rep_ictx.auth_store.authenticate("oncall", "page")
    assert rep_ictx.auth_store.has_privilege("oncall", "MATCH")
    assert "prod" in rep_ictx.dbms.names()
    # and the new MAIN can keep evolving system state
    rep.execute("CREATE USER next IDENTIFIED BY 'x'")
    assert "next" in rep_ictx.auth_store.users()
