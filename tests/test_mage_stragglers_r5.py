"""Round-5 MAGE stragglers: llm_util.schema, embeddings.*,
cross_database.* (reference: mage/python/{llm_util,embeddings,
cross_database}.py)."""

import numpy as np
import pytest

from memgraph_tpu.query import Interpreter
from memgraph_tpu.query.interpreter import InterpreterContext
from memgraph_tpu.storage import InMemoryStorage


@pytest.fixture
def interp():
    i = Interpreter(InterpreterContext(InMemoryStorage()))
    i.execute("CREATE (a:Person {name: 'ann', age: 34})-[:KNOWS "
              "{since: 2020}]->(b:Person {name: 'bob'}), "
              "(a)-[:LIKES]->(c:Movie {title: 'Heat'})")
    return i


class TestLlmUtil:
    def test_prompt_ready(self, interp):
        _, rows, _ = interp.execute(
            "CALL llm_util.schema() YIELD schema RETURN schema")
        text = rows[0][0]
        assert 'Node name: "Person"' in text
        assert "(:Person)-[:KNOWS]->(:Person)" in text
        assert "name: String" in text

    def test_raw(self, interp):
        _, rows, _ = interp.execute(
            "CALL llm_util.schema('raw') YIELD schema RETURN schema")
        raw = rows[0][0]
        kinds = {item["kind"] for item in raw}
        assert kinds == {"node", "relationship"}

    def test_empty_graph_errors(self):
        interp = Interpreter(InterpreterContext(InMemoryStorage()))
        with pytest.raises(Exception, match="no data"):
            interp.execute("CALL llm_util.schema() YIELD schema "
                           "RETURN schema")


class TestEmbeddings:
    def test_compute_and_knn_compose(self, interp):
        _, rows, _ = interp.execute(
            "CALL embeddings.compute_embeddings({dimension: 64}) "
            "YIELD success, count, dimension "
            "RETURN success, count, dimension")
        assert rows[0] == [True, 3, 64]
        _, rows, _ = interp.execute(
            "MATCH (n:Person {name: 'ann'}) RETURN size(n.embedding)")
        assert rows[0][0] == 64
        # deterministic: same config -> same vectors
        _, v1, _ = interp.execute(
            "MATCH (n:Person {name: 'ann'}) RETURN n.embedding")
        interp.execute(
            "CALL embeddings.compute_embeddings({dimension: 64}) "
            "YIELD count RETURN count")
        _, v2, _ = interp.execute(
            "MATCH (n:Person {name: 'ann'}) RETURN n.embedding")
        np.testing.assert_allclose(v1[0][0], v2[0][0], rtol=1e-5)

    def test_similar_text_closer_than_different(self, interp):
        interp.execute("CREATE (:Person {name: 'ann smith'})")
        interp.execute(
            "CALL embeddings.compute_embeddings({dimension: 128}) "
            "YIELD count RETURN count")
        _, rows, _ = interp.execute(
            "MATCH (n) WHERE n.name IS NOT NULL OR n.title IS NOT NULL "
            "RETURN coalesce(n.name, n.title), n.embedding")
        vecs = {r[0]: np.asarray(r[1]) for r in rows}
        sim_same = float(vecs["ann"] @ vecs["ann smith"])
        sim_diff = float(vecs["ann"] @ vecs["Heat"])
        assert sim_same > sim_diff

    def test_node_sentence(self, interp):
        _, rows, _ = interp.execute(
            "CALL embeddings.node_sentence() YIELD node, sentence "
            "WHERE node.name = 'ann' RETURN sentence")
        assert "Person" in rows[0][0]
        assert "name: ann" in rows[0][0]
        assert "age: 34" in rows[0][0]

    def test_model_info(self, interp):
        _, rows, _ = interp.execute(
            "CALL embeddings.model_info() YIELD name, dimension, device "
            "RETURN name, dimension, device")
        assert "hashing" in rows[0][0]


class TestCrossDatabase:
    def test_bolt_roundtrip_against_own_server(self, interp, tmp_path):
        import socket
        from memgraph_tpu.server.bolt import BoltServer
        remote = InterpreterContext(InMemoryStorage())
        Interpreter(remote).execute(
            "CREATE (:City {name: 'berlin', pop: 3600000}), "
            "(:City {name: 'zagreb', pop: 800000})")
        with socket.socket() as p:
            p.bind(("127.0.0.1", 0))
            port = p.getsockname()[1]
        server = BoltServer(remote, "127.0.0.1", port)
        thread, loop = server.run_in_thread()
        try:
            _, rows, _ = interp.execute(
                f"CALL cross_database.bolt('MATCH (c:City) RETURN "
                f"c.name AS name, c.pop AS pop', "
                f"{{host: '127.0.0.1', port: {port}}}) YIELD row "
                f"RETURN row.name, row.pop ORDER BY row.name")
            assert rows == [["berlin", 3600000], ["zagreb", 800000]]
            # label shorthand expands to a properties() scan
            _, rows, _ = interp.execute(
                f"CALL cross_database.neo4j('City', "
                f"{{host: '127.0.0.1', port: {port}}}) YIELD row "
                f"RETURN row.props.name ORDER BY row.props.name")
            assert [r[0] for r in rows] == ["berlin", "zagreb"]
        finally:
            loop.call_soon_threadsafe(loop.stop)

    def test_connection_refused_is_query_error(self, interp):
        from memgraph_tpu.exceptions import QueryException
        with pytest.raises(QueryException, match="cannot connect"):
            interp.execute(
                "CALL cross_database.bolt('RETURN 1', "
                "{host: '127.0.0.1', port: 1}) YIELD row RETURN row")

    def test_sqlite_alias(self, interp, tmp_path):
        import sqlite3
        db = tmp_path / "t.db"
        con = sqlite3.connect(db)
        con.execute("CREATE TABLE users (id INTEGER, name TEXT)")
        con.execute("INSERT INTO users VALUES (1, 'ann'), (2, 'bob')")
        con.commit()
        con.close()
        _, rows, _ = interp.execute(
            f"CALL cross_database.sqlite('users', "
            f"{{database: '{db}'}}) YIELD row "
            f"RETURN row.id, row.name ORDER BY row.id")
        assert rows == [[1, "ann"], [2, "bob"]]
