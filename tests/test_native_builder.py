"""Native C++ CSR builder parity vs the numpy path."""

import numpy as np
import pytest

from memgraph_tpu.ops.native import build_csr_csc_native, get_lib


@pytest.fixture(scope="module")
def lib():
    lib = get_lib()
    if lib is None:
        pytest.skip("native builder unavailable (no compiler)")
    return lib


def test_native_matches_numpy(lib):
    rng = np.random.default_rng(0)
    n, e = 500, 4000
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    w = rng.uniform(0.1, 2.0, e).astype(np.float32)
    n_pad, e_pad = 1024, 4096

    native = build_csr_csc_native(src, dst, w, n, n_pad, e_pad)
    assert native is not None

    order = np.lexsort((dst, src))
    np.testing.assert_array_equal(native["csr_src"][:e], src[order])
    np.testing.assert_array_equal(native["csr_dst"][:e], dst[order])
    np.testing.assert_allclose(native["csr_w"][:e], w[order])
    corder = np.lexsort((src, dst))
    np.testing.assert_array_equal(native["csc_src"][:e], src[corder])
    np.testing.assert_array_equal(native["csc_dst"][:e], dst[corder])
    # padding
    assert (native["csr_src"][e:] == n).all()
    assert (native["csr_w"][e:] == 0).all()
    # row_ptr and degrees
    counts = np.bincount(src, minlength=n_pad)
    row_ptr = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
    np.testing.assert_array_equal(native["row_ptr"], row_ptr)
    np.testing.assert_allclose(native["out_degree"][:n],
                               counts[:n].astype(np.float32))
    assert (native["out_degree"][n:] == 0).all()


def test_native_rejects_bad_ids(lib):
    src = np.array([0, 5], dtype=np.int64)  # 5 >= n_nodes
    dst = np.array([0, 1], dtype=np.int64)
    with pytest.raises(ValueError):  # corrupt input must not fall back
        build_csr_csc_native(src, dst, None, 3, 8, 8)


def test_from_coo_uses_native_and_kernels_agree(lib):
    # end-to-end: pagerank over a native-built graph matches networkx
    import networkx as nx
    from memgraph_tpu.ops import csr
    from memgraph_tpu.ops.pagerank import pagerank
    g = nx.gnp_random_graph(50, 0.1, seed=3, directed=True)
    src = np.array([u for u, v in g.edges()])
    dst = np.array([v for u, v in g.edges()])
    graph = csr.from_coo(src, dst, n_nodes=50)
    ranks, _, _ = pagerank(graph, tol=1e-10, max_iterations=300)
    expected = nx.pagerank(g, alpha=0.85, tol=1e-12, max_iter=500)
    exp = np.array([expected[i] for i in range(50)])
    np.testing.assert_allclose(np.asarray(ranks), exp, atol=1e-5)
