"""Exception-flow contracts for the serving planes (mgflow, r24).

Every long-lived dispatch loop and RPC handler in the framework is a
**serving root**: a function whose uncaught exceptions kill a daemon,
wedge a session, or silently drop a request. The registry below is the
machine-checked ground truth for what each root is ALLOWED to let
escape — ``python -m tools.mgflow check`` computes the interprocedural
escape set of every root (raise sites + known-raising calls, narrowed
by except clauses, re-raises and RetryPolicy wrappers) and fails the
gate when an escape is not covered by the root's ``raises`` contract.

The same file declares the typed-outcome **wires**: every outcome
string a server emits on the kernel/mp/2PC protocols must have a
client-side decoder, and every decoder must correspond to an outcome a
server can actually emit (both directions, MG005-style). Drift in
either direction is a gate failure, not a code review hope.

This module is product code (the registries ARE the contract surface,
exported at runtime through ``GET /stats``); the analyzers in
``tools/mgflow`` read it via AST so fixtures can declare their own
miniature registries.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class ServingRoot:
    """One serving loop/handler + its declared escape contract.

    ``path`` is a repo-relative file suffix; ``qualname`` the dotted
    function path inside it. ``raises`` lists exception type names that
    MAY propagate out of the root — subclasses are covered by their
    bases, so ``("MemgraphTpuError",)`` admits the whole typed
    taxonomy. An empty contract means the root must be total: every
    exception is handled inside the loop (the supervised-daemon shape).
    """

    root_id: str
    path: str
    qualname: str
    raises: tuple = ()
    why: str = ""


@dataclass(frozen=True)
class WireSide:
    """Where one side of a typed-outcome protocol lives and how to read
    its vocabulary out of the source (directives interpreted by
    tools/mgflow/protocol.py):

      ("dict_value", K)    constants under key K in dict literals
      ("dict_keys", N)     constant keys of the module-level dict N
      ("tuple_const", N)   members of the module-level tuple N
      ("send_tuple0", F)   constant first elements of tuple literals
                           passed to calls of F (wire envelopes)
      ("return_tuple0","") constant first elements of returned tuples
      ("compare", V)       constants compared against variable V
                           ("[0]" matches any x[0] subscript)
    """

    path: str
    scope: tuple = ()        # qualname prefixes; () = whole file
    extract: tuple = ()


@dataclass(frozen=True)
class Wire:
    """One server↔client typed-outcome protocol. ``declared`` names a
    module-level tuple that is the canonical vocabulary (falls back to
    the emitted set); ``handled_inline`` lists values consumed
    structurally rather than by literal comparison (e.g. the success
    value behind an ``if reply["ok"]`` check)."""

    wire_id: str
    server: tuple = ()       # WireSide(s)
    client: tuple = ()       # WireSide(s)
    declared: tuple | None = None    # (path, symbol)
    handled_inline: tuple = ()


#: Serving roots and their escape contracts. Keep ``why`` honest: it is
#: printed by ``python -m tools.mgflow list`` and is the reviewer-facing
#: justification for every non-empty contract.
SERVING_ROOTS = (
    ServingRoot(
        root_id="bolt.session",
        path="server/bolt.py",
        qualname="BoltSession.run",
        raises=(),
        why="a Bolt session must die clean: protocol errors map to "
            "FAILURE records, transport errors end the session, and "
            "the terminal catch-all logs anything else",
    ),
    ServingRoot(
        root_id="kernel.dispatch",
        path="server/kernel_server.py",
        qualname="KernelServer._serve_conn",
        raises=(),
        why="the kernel daemon's per-connection loop replies a typed "
            "outcome for every failure; an escape here kills the "
            "connection thread with the client still waiting",
    ),
    ServingRoot(
        root_id="ppr.plane",
        path="server/kernel_server.py",
        qualname="PprServingPlane._run",
        raises=(),
        why="the coalescing batcher thread serves every rider; it must "
            "survive any single batch failing (riders get typed "
            "replies, the thread lives on)",
    ),
    ServingRoot(
        root_id="mp.worker",
        path="server/mp_executor.py",
        qualname="MPReadExecutor._worker_loop",
        raises=(),
        why="the forked read worker ships every error back on the "
            "(err, type, message) envelope; an escape is a silent "
            "worker death the parent only sees as a broken pipe",
    ),
    ServingRoot(
        root_id="shard.worker",
        path="sharding/worker.py",
        qualname="shard_worker_main",
        raises=(),
        why="the shard worker's envelope loop ships errors back typed; "
            "an escape kills the shard until the plane respawns it",
    ),
    ServingRoot(
        root_id="twopc.prepare",
        path="sharding/router.py",
        qualname="ShardedClient._prepare_one",
        raises=("MemgraphTpuError",),
        why="prepare surfaces only the typed taxonomy: vote-no, bounce "
            "exhaustion and worker death all land in MemgraphTpuError "
            "subclasses the 2PC driver's presumed-abort path handles",
    ),
    ServingRoot(
        root_id="twopc.decide",
        path="sharding/router.py",
        qualname="ShardedClient._decide_one",
        raises=("MemgraphTpuError",),
        why="decide re-drives through the durable journal; what it "
            "raises (undeliverable decision, in-doubt loss) is typed "
            "so write_multi can account the abort",
    ),
    ServingRoot(
        root_id="replication.apply",
        path="replication/replica.py",
        qualname="ReplicaServer._serve_main",
        raises=(),
        why="the replica's apply loop must survive any frame: a "
            "corrupt or refused frame drops the connection (the main "
            "reconnects and catches up), it never kills the server",
    ),
    ServingRoot(
        root_id="raft.rpc",
        path="coordination/raft.py",
        qualname="RaftNode._handle",
        raises=(),
        why="a raft RPC handler that raises drops the peer's request "
            "on the floor mid-election; every path must answer",
    ),
    ServingRoot(
        root_id="stream.consumer",
        path="query/streams.py",
        qualname="Stream._loop",
        raises=(),
        why="the consumer loop owns exactly-once ingestion: poll "
            "errors reconnect, poison batches quarantine, stop is the "
            "typed _StreamStopped — nothing else may kill the thread",
    ),
    ServingRoot(
        root_id="http.monitoring",
        path="observability/http.py",
        qualname="start_monitoring_server.handle",
        raises=(),
        why="the monitoring endpoint is the thing operators check "
            "when everything else is broken; it answers or closes, "
            "it does not crash the event loop",
    ),
)


#: Typed-outcome wires (server-emitted ↔ client-decoded, both ways).
WIRES = (
    Wire(
        wire_id="kernel",
        server=(
            WireSide(path="server/kernel_server.py",
                     scope=("KernelServer", "PprServingPlane"),
                     extract=(("dict_value", "outcome"),)),
        ),
        client=(
            WireSide(path="server/kernel_server.py",
                     scope=("KernelClient", "SupervisedKernelClient",
                            "_raise_for_reply", "_OUTCOME_ERRORS"),
                     extract=(("dict_keys", "_OUTCOME_ERRORS"),
                              ("compare", "outcome"))),
        ),
        declared=("server/kernel_server.py", "DISPATCH_OUTCOMES"),
        # "completed" is the ok-path (header["ok"] is checked
        # structurally); "invalid" is the generic-KernelServerError
        # fall-through in _raise_for_reply, which carries the outcome
        handled_inline=("completed", "invalid"),
    ),
    Wire(
        wire_id="mp_executor",
        server=(
            WireSide(path="server/mp_executor.py",
                     scope=("MPReadExecutor._worker_loop",),
                     extract=(("send_tuple0", "_send"),)),
        ),
        client=(
            WireSide(path="server/mp_executor.py",
                     scope=("MPReadExecutor.execute",),
                     extract=(("compare", "[0]"),)),
        ),
        # "ok" is decoded structurally: everything that is not "err"
        # unpacks as (ok, columns, rows, spans)
        handled_inline=("ok",),
    ),
    Wire(
        wire_id="twopc",
        server=(
            WireSide(path="sharding/worker.py",
                     scope=("_handle", "shard_worker_main"),
                     extract=(("return_tuple0", ""),
                              ("send_tuple0", "_send"))),
        ),
        client=(
            WireSide(path="sharding/plane.py",
                     scope=("ShardPlane.request", "ShardPlane._direct"),
                     extract=(("compare", "status"),)),
            WireSide(path="sharding/router.py",
                     scope=("ShardedClient._decide_one",),
                     extract=(("compare", "status"),)),
        ),
        # "ok" falls through request() as the success status
        handled_inline=("ok",),
    ),
)


def flow_stats() -> dict:
    """The runtime-visible contract surface (GET /stats `flow` section):
    how many roots are under contract and how many escape types the
    contracts admit in total. Static by construction — these gauges
    move only when the registry itself changes, which is exactly what
    an operator diffing two deployments wants to see."""
    return {
        "contract_roots": len(SERVING_ROOTS),
        "escapes_total": sum(len(r.raises) for r in SERVING_ROOTS),
        "wires": [w.wire_id for w in WIRES],
        "roots": {r.root_id: list(r.raises) for r in SERVING_ROOTS},
    }
