"""Tenant profiles: named resource limits assignable to databases.

Counterpart of the reference's TenantProfiles
(/root/reference/src/dbms/tenant_profiles.cpp + the MemgraphCypher.g4
tenant-profile grammar): CREATE/ALTER/DROP TENANT PROFILE with a limit
list, SHOW, and SET ... ON DATABASE assignment, persisted in the root
kvstore so they survive restarts.

Enforced limit: `memory_limit` becomes the DEFAULT per-query memory cap
for every query running against an assigned database (an explicit
QUERY MEMORY LIMIT still wins); the reference additionally meters the
storage arena, which this build tracks globally, not per tenant.
"""

from __future__ import annotations

import json
import threading

from ..exceptions import QueryException

_KEY = "tenant_profiles"


class TenantProfiles:
    def __init__(self, kvstore=None) -> None:
        self._lock = threading.Lock()
        self._profiles: dict[str, dict] = {}
        self._assignments: dict[str, str] = {}   # database -> profile
        self._kv = kvstore
        if kvstore is not None:
            raw = kvstore.get_str(_KEY)
            if raw:
                data = json.loads(raw)
                self._profiles = data.get("profiles", {})
                self._assignments = data.get("assignments", {})

    def _save(self) -> None:
        if self._kv is not None:
            self._kv.put(_KEY, json.dumps(
                {"profiles": self._profiles,
                 "assignments": self._assignments}))

    # --- DDL -----------------------------------------------------------------

    def create(self, name: str, limits: dict) -> None:
        with self._lock:
            if name in self._profiles:
                raise QueryException(
                    f"tenant profile {name!r} already exists")
            self._profiles[name] = dict(limits)
            self._save()

    def alter(self, name: str, limits: dict) -> None:
        with self._lock:
            if name not in self._profiles:
                raise QueryException(
                    f"tenant profile {name!r} does not exist")
            self._profiles[name].update(limits)
            self._save()

    def drop(self, name: str) -> None:
        with self._lock:
            if name not in self._profiles:
                raise QueryException(
                    f"tenant profile {name!r} does not exist")
            del self._profiles[name]
            self._assignments = {db: p for db, p
                                 in self._assignments.items() if p != name}
            self._save()

    def assign(self, database: str, profile: str) -> None:
        with self._lock:
            if profile not in self._profiles:
                raise QueryException(
                    f"tenant profile {profile!r} does not exist")
            self._assignments[database] = profile
            self._save()

    def clear(self, database: str) -> None:
        with self._lock:
            self._assignments.pop(database, None)
            self._save()

    # --- reads ---------------------------------------------------------------

    def show(self, name: str | None = None) -> list[list]:
        with self._lock:
            items = (sorted(self._profiles.items()) if name is None
                     else [(name, self._profiles.get(name))])
            out = []
            for pname, limits in items:
                if limits is None:
                    raise QueryException(
                        f"tenant profile {pname!r} does not exist")
                dbs = sorted(db for db, p in self._assignments.items()
                             if p == pname)
                out.append([pname, dict(limits), dbs])
            return out

    def limit_for_database(self, database: str, key: str):
        with self._lock:
            profile = self._assignments.get(database)
            if profile is None:
                return None
            return self._profiles.get(profile, {}).get(key)
