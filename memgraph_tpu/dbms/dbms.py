"""Multi-tenancy: named databases with isolated storage.

Counterpart of the reference's DbmsHandler
(/root/reference/src/dbms/dbms_handler.hpp:134 — per-tenant Database with
isolated storage and memory arena; New_/Get/Delete at :916-991). Each
database owns its InMemoryStorage + InterpreterContext; sessions switch
with USE DATABASE. The default database always exists.
"""

from __future__ import annotations

import os
import threading
import time

from ..exceptions import QueryException
from ..utils.locks import tracked_lock
from ..storage import InMemoryStorage, StorageConfig

DEFAULT_DB = "memgraph"


class DbmsHandler:
    def __init__(self, root_config: StorageConfig | None = None,
                 interpreter_config: dict | None = None,
                 recover_on_startup: bool = True):
        from ..query.interpreter import InterpreterContext
        self._lock = tracked_lock("Dbms._lock")
        self._root_config = root_config or StorageConfig()
        self._interp_config = interpreter_config or {}
        self._recover = recover_on_startup
        self._databases: dict[str, "InterpreterContext"] = {}
        self._suspended: set[str] = set()
        self._suspending: set[str] = set()   # snapshot in flight
        self._make(DEFAULT_DB)
        # suspended tenants stay cold across restarts (their durable
        # shell is on disk; SUSPENDED markers record the state)
        root = self._root_config.durability_dir
        if root:
            dbdir = os.path.join(root, "databases")
            if os.path.isdir(dbdir):
                for entry in os.listdir(dbdir):
                    if os.path.exists(os.path.join(dbdir, entry,
                                                   "SUSPENDED")):
                        self._suspended.add(entry)
        from .tenant_profiles import TenantProfiles
        self.tenant_profiles = TenantProfiles(
            self._databases[DEFAULT_DB].kvstore
            if getattr(self._databases[DEFAULT_DB], "kvstore", None)
            is not None else None)

    def _db_config(self, name: str) -> StorageConfig:
        import dataclasses
        # copy EVERY field (replace, not field-by-field: a hand-copied
        # list silently drops newly added StorageConfig knobs — it
        # already lost automatic_*_index once); per-db durability_dir is
        # assigned below
        cfg = dataclasses.replace(self._root_config, durability_dir=None)
        if self._root_config.durability_dir:
            if name == DEFAULT_DB:
                # the default database lives at the root (single-tenant
                # layouts stay recoverable when multi-tenancy is enabled)
                cfg.durability_dir = self._root_config.durability_dir
            else:
                cfg.durability_dir = os.path.join(
                    self._root_config.durability_dir, "databases", name)
            os.makedirs(cfg.durability_dir, exist_ok=True)
            marker = os.path.join(cfg.durability_dir, "STORAGE_MODE")
            if os.path.exists(marker):
                from ..storage.common import StorageMode
                with open(marker, encoding="utf-8") as f:
                    cfg.storage_mode = StorageMode(f.read().strip())
        return cfg

    def _make(self, name: str, force_recover: bool = False):
        from ..query.interpreter import InterpreterContext
        from ..storage.common import StorageMode
        recover_now = self._recover or force_recover
        cfg = self._db_config(name)
        if cfg.storage_mode is StorageMode.ON_DISK_TRANSACTIONAL:
            # disk mode: sqlite owns persistence; snapshots/WAL unused
            # (ref: disk/storage.cpp — RocksDB owns durability)
            from ..storage.disk_storage import DiskStorage
            if not cfg.durability_dir:
                cfg.durability_dir = os.path.join(
                    os.getcwd(), "mg_disk_data", name)
                os.makedirs(cfg.durability_dir, exist_ok=True)
            storage = DiskStorage(cfg)
        else:
            storage = InMemoryStorage(cfg)
            if cfg.durability_dir:
                from ..storage.durability.recovery import (recover,
                                                           wire_durability)
                if recover_now:
                    if cfg.allow_recovery_failure:
                        try:
                            recover(storage)
                        except Exception as e:  # noqa: BLE001
                            import logging
                            logging.getLogger(__name__).error(
                                "recovery failed (continuing, "
                                "--storage-allow-recovery-failure): %s", e)
                    else:
                        recover(storage)
                if cfg.wal_enabled:
                    wire_durability(storage)
        ictx = InterpreterContext(storage, dict(self._interp_config))
        ictx.database_name = name
        # per-DB arena cap: the tenant profile's storage_limit is
        # enforced at write commits (storage._check_db_memory_limit)
        storage.memory_limit_fn = (
            lambda n=name: self.tenant_profiles.limit_for_database(
                n, "storage_limit"))
        ictx.dbms = self
        if cfg.durability_dir:
            from ..storage.kvstore import KVStore, Settings
            ictx.kvstore = KVStore(
                os.path.join(cfg.durability_dir, "kvstore.db"))
            ictx.settings = Settings(ictx.kvstore)
            if recover_now:
                self._restore_ddl(storage, ictx.kvstore)
                raw = ictx.kvstore.get("enums")
                if raw:
                    import json as _json
                    from ..storage.enums import enum_registry
                    enum_registry(storage).load(_json.loads(
                        raw.decode("utf-8")))
        self._databases[name] = ictx
        return ictx

    @staticmethod
    def _restore_ddl(storage, kvstore) -> None:
        """Make the kvstore the authoritative DDL set: re-create persisted
        indexes/constraints AND drop any that the snapshot restored but the
        kvstore no longer lists (a drop after the last snapshot must win)."""
        import json as _json
        index_keys = set()
        for key, _ in kvstore.items_with_prefix("ddl:index:"):
            index_keys.add(tuple(_json.loads(key[len("ddl:index:"):])[:1])
                           + (key,))
        # reconcile drops first (only when DDL persistence has ever run —
        # a directory predating the feature keeps its snapshot DDL)
        has_any = kvstore.get("ddl:enabled") is not None
        if has_any:
            lm, pm, tm = (storage.label_mapper, storage.property_mapper,
                          storage.edge_type_mapper)
            listed = {key[len("ddl:index:"):]
                      for key, _ in kvstore.items_with_prefix("ddl:index:")}
            for lid in list(storage.indices.label.labels()):
                if _json.dumps(["label", lm.id_to_name(lid)]) not in listed:
                    storage.indices.label.drop(lid)
            for (lid, pids) in list(storage.indices.label_property.keys()):
                spec = _json.dumps(["label_property", lm.id_to_name(lid),
                                    [pm.id_to_name(p) for p in pids]])
                if spec not in listed:
                    storage.indices.label_property.drop(lid, pids)
            for tid in list(storage.indices.edge_type.types()):
                if _json.dumps(["edge_type", tm.id_to_name(tid)])                         not in listed:
                    storage.indices.edge_type.drop(tid)
            listed_c = {key[len("ddl:constraint:"):]
                        for key, _ in
                        kvstore.items_with_prefix("ddl:constraint:")}
            for (lid, pid) in list(storage.constraints.existence.all()):
                spec = _json.dumps(["exists", lm.id_to_name(lid),
                                    [pm.id_to_name(pid)]])
                if spec not in listed_c:
                    storage.constraints.existence.drop(lid, pid)
            for (lid, pids) in list(storage.constraints.unique.all()):
                spec = _json.dumps(["unique", lm.id_to_name(lid),
                                    [pm.id_to_name(p) for p in pids]])
                if spec not in listed_c:
                    storage.constraints.unique.drop(lid, tuple(pids))
            for (lid, pid, tname) in list(storage.constraints.type.all()):
                spec = _json.dumps(["type", lm.id_to_name(lid),
                                    [pm.id_to_name(pid)]])
                if spec not in listed_c:
                    storage.constraints.type.drop(lid, pid)
        for key, _ in kvstore.items_with_prefix("ddl:index:"):
            spec = _json.loads(key[len("ddl:index:"):])
            if spec[0] == "label":
                storage.create_label_index(
                    storage.label_mapper.name_to_id(spec[1]))
            elif spec[0] == "label_property":
                storage.create_label_property_index(
                    storage.label_mapper.name_to_id(spec[1]),
                    tuple(storage.property_mapper.name_to_id(p)
                          for p in spec[2]))
            elif spec[0] == "edge_type":
                storage.create_edge_type_index(
                    storage.edge_type_mapper.name_to_id(spec[1]))
        for key, raw in kvstore.items_with_prefix("ddl:constraint:"):
            kind, label, props = _json.loads(key[len("ddl:constraint:"):])
            data_type = raw.decode("utf-8")
            lid = storage.label_mapper.name_to_id(label)
            pids = [storage.property_mapper.name_to_id(p) for p in props]
            try:
                if kind == "exists":
                    storage.create_existence_constraint(lid, pids[0])
                elif kind == "unique":
                    storage.create_unique_constraint(lid, tuple(pids))
                elif kind == "type":
                    storage.create_type_constraint(lid, pids[0], data_type)
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "constraint restore failed: %s", key)

    # --- API (reference: New_/Get/TryDelete) --------------------------------

    def create(self, name: str):
        if not name.replace("_", "").replace("-", "").isalnum():
            raise QueryException(f"invalid database name {name!r}")
        with self._lock:
            if name in self._databases or name in self._suspended:
                raise QueryException(f"database {name!r} already exists")
            return self._make(name)

    def get(self, name: str):
        with self._lock:
            ictx = self._databases.get(name)
            if ictx is None and name in self._suspended:
                raise QueryException(
                    f"database {name!r} is suspended; RESUME DATABASE "
                    f"{name} first")
        if ictx is None:
            raise QueryException(f"database {name!r} does not exist")
        return ictx

    def drop(self, name: str) -> None:
        if name == DEFAULT_DB:
            raise QueryException("cannot drop the default database")
        with self._lock:
            if name in self._suspended:
                self._suspended.discard(name)
                self._clear_suspend_marker(name)
            elif name in self._databases:
                del self._databases[name]
            else:
                raise QueryException(f"database {name!r} does not exist")
        # a recreated same-name database must not inherit the old limits
        profiles = getattr(self, "tenant_profiles", None)
        if profiles is not None:
            profiles.clear(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(set(self._databases) | self._suspended)

    # --- hot/cold (reference: specs/hot-cold-databases.md) ------------------

    def _suspend_marker(self, name: str) -> str:
        return os.path.join(self._db_config(name).durability_dir or "",
                            "SUSPENDED")

    def _clear_suspend_marker(self, name: str) -> None:
        try:
            os.remove(self._suspend_marker(name))
        except OSError:
            pass

    def suspend(self, name: str) -> None:
        """HOT -> COLD: persist a durable shell, drop the in-memory
        storage. Never loses data (spec §2); not queryable until
        resumed."""
        if name == DEFAULT_DB:
            raise QueryException(
                "the default database cannot be suspended")
        with self._lock:
            if name in self._suspended:
                return                  # idempotent (spec §4 SUSPEND|cold)
            ictx = self._databases.get(name)
            if ictx is None:
                raise QueryException(f"database {name!r} does not exist")
            cfg = ictx.storage.config
            if not getattr(cfg, "durability_dir", None):
                raise QueryException(
                    f"database {name!r} has no durability directory — "
                    f"suspending would lose its data")
            # make the db invisible first; the (possibly long) snapshot
            # runs OUTSIDE the handler lock so other tenants never stall
            del self._databases[name]
            self._suspended.add(name)
            self._suspending.add(name)
        # gate BEFORE snapshotting: sessions holding a USE DATABASE
        # reference can no longer open transactions, and in-flight ones
        # must drain — a commit racing the snapshot would be silently
        # lost on resume ("never loses data", spec §2)
        ictx.storage.suspended = True
        try:
            deadline = time.monotonic() + 30.0
            while getattr(ictx.storage, "_active_txns", None):
                if time.monotonic() > deadline:
                    raise QueryException(
                        f"cannot suspend {name!r}: transactions did not "
                        f"drain within 30s")
                time.sleep(0.01)
            from ..storage.durability.snapshot import create_snapshot
            ictx.storage._suspend_internal = True
            try:
                create_snapshot(ictx.storage)
            finally:
                ictx.storage._suspend_internal = False
        except Exception:
            with self._lock:            # undo: the db stays hot
                ictx.storage.suspended = False
                self._suspended.discard(name)
                self._suspending.discard(name)
                self._databases[name] = ictx
            raise
        with self._lock:
            self._suspending.discard(name)
            # a concurrent RESUME may have re-made the db while we
            # snapshotted; its fresh instance wins — no stale marker
            if name not in self._suspended:
                return
            with open(self._suspend_marker(name), "w") as f:
                f.write("cold\n")

    def resume(self, name: str) -> None:
        """COLD -> HOT: rebuild from the durable shell; blocks until the
        database is queryable again. Idempotent on hot databases."""
        # a concurrent SUSPEND may still be writing its snapshot: block
        # until the durable shell is complete, or resuming would recover
        # stale state (spec: RESUME blocks until the database is hot)
        deadline = time.monotonic() + 60.0
        while True:
            with self._lock:
                if name not in self._suspending:
                    break
            if time.monotonic() > deadline:
                raise QueryException(
                    f"database {name!r} is still being suspended")
            time.sleep(0.01)
        with self._lock:
            if name in self._databases:
                return
            if name not in self._suspended:
                raise QueryException(f"database {name!r} does not exist")
            self._suspended.discard(name)
            self._clear_suspend_marker(name)
            # recovery is NON-optional here even when the server skips it
            # at startup: resuming without it would bring up an empty db
            self._make(name, force_recover=True)

    def database_states(self) -> list[tuple[str, str]]:
        with self._lock:
            rows = [(n, "hot") for n in self._databases]
            rows += [(n, "suspended") for n in self._suspended]
        return sorted(rows)

    def default(self):
        return self.get(DEFAULT_DB)
