"""memgraph_tpu — a TPU-native graph database framework.

A ground-up re-design of the capabilities of the reference in-memory graph
database (openCypher engine, MVCC storage, Bolt serving, durability,
replication/HA, streaming, query modules) with the analytics layer executing
as JAX/XLA kernels on TPU.

Architecture (host vs device split, see SURVEY.md §7):
  - Host (Python/C++): Cypher parse/plan, MVCC point reads/writes, Bolt
    protocol, durability, replication control plane.
  - Device (JAX/XLA/pallas): whole-graph analytics (PageRank, Katz, label
    propagation, WCC, node2vec, kNN vector search) over immutable CSR
    snapshots, sharded across a `jax.sharding.Mesh` for multi-chip.

Package layout:
  utils/     — interning, temporal types, points, scheduler, settings
  storage/   — MVCC graph storage engine, indexes, constraints, durability
  query/     — openCypher frontend, planner, Volcano executor, functions
  ops/       — TPU kernels over CSR device graphs
  parallel/  — device meshes, edge partitioning, shard_map collectives
  models/    — embedding/GNN-style model families (node2vec, ...)
  server/    — Bolt wire protocol server
  dbms/      — multi-tenancy
  replication/ — WAL shipping control plane
"""

__version__ = "0.1.0"
