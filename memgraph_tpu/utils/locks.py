"""Runtime lock-order witness: the dynamic half of mglint's MG001.

``tracked_lock("Class.attr")`` is a drop-in replacement for
``threading.Lock()`` at lock *creation* sites. Unarmed (the default) it
returns a plain ``threading.Lock`` — zero overhead, byte-identical
behavior. Armed via ``MG_TRACK_LOCKS=1`` it returns a ``TrackedLock``
that records every "acquired B while holding A" edge into a global
digraph, with the acquiring file:line, and checks incrementally for
cycles: the first edge that closes a cycle is recorded as a violation
(and logged loudly) without blocking the program.

The test suite arms this (tests/conftest.py) and asserts at session end
that the witnessed graph is acyclic — so the static analysis (MG001,
which under-approximates: dynamic dispatch and unresolvable receivers
contribute no edges) and the dynamic witness (which only sees executed
interleavings) validate each other from opposite sides.

Lock names are class-scoped (``Storage._engine_lock``), not instance-
scoped: two instances of the same class count as ONE node, so nesting
two ``ReplicaClient._lock`` instances is reported as a self-edge. That
is deliberate — same-class instances locked in an unordered way are
exactly the two-thread deadlock the witness exists to catch (the fix is
an explicit tiebreak order, e.g. by gid, not an exemption).
"""

from __future__ import annotations

import logging
import os
import sys
import threading

from . import sanitize as _san

log = logging.getLogger(__name__)

ENV_VAR = "MG_TRACK_LOCKS"


def armed() -> bool:
    # MG_SAN=1 implies tracked locks: the race detector and the schedule
    # explorer both hook TrackedLock acquire/release, so arming the
    # sanitizer without the witness would blind them. An explicit
    # MG_TRACK_LOCKS=0 still wins (opt-out).
    v = os.environ.get(ENV_VAR)
    if v is not None:
        return v not in ("", "0")
    return _san.armed()


class LockOrderViolation(AssertionError):
    """Raised by assert_acyclic() when the witnessed graph has a cycle."""


# --- global witness state ---------------------------------------------------

# the witness's own mutex is a strict leaf: nothing is acquired under it
_W_LOCK = threading.Lock()
#: (from_name, to_name) -> first-seen site "file:line (thread)"
_EDGES: dict[tuple[str, str], str] = {}
#: recorded cycles: list of (cycle path tuple, closing site)
_VIOLATIONS: list[tuple[tuple[str, ...], str]] = []
_TLS = threading.local()


def _held_stack() -> list:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _caller_site(depth: int) -> str:
    """First stack frame OUTSIDE this module (the user's acquire site)."""
    try:
        frame = sys._getframe(depth)
        while frame is not None and frame.f_code.co_filename == __file__:
            frame = frame.f_back
        if frame is None:
            return "<unknown>"
        return (f"{frame.f_code.co_filename}:{frame.f_lineno} "
                f"({threading.current_thread().name})")
    except ValueError:
        return "<unknown>"


def _find_path(src: str, dst: str) -> list[str] | None:
    """DFS path src -> dst in the edge graph (caller holds _W_LOCK)."""
    succ: dict[str, list[str]] = {}
    for (frm, to) in _EDGES:
        succ.setdefault(frm, []).append(to)
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in succ.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquired(lock: "TrackedLock") -> None:
    stack = _held_stack()
    entry_ids = [e[0] for e in stack]
    if id(lock) in entry_ids:
        # reentrant re-acquire of the same instance: no new ordering
        stack.append((id(lock), lock.name, True))
        return
    held_names = [e[1] for e in stack]
    stack.append((id(lock), lock.name, False))
    if not held_names:
        return
    site = _caller_site(3)
    with _W_LOCK:
        for held in held_names:
            key = (held, lock.name)
            if key in _EDGES:
                continue
            # does the REVERSE direction already exist (possibly via a
            # longer path)? then this edge closes a cycle.
            back = _find_path(lock.name, held)
            _EDGES[key] = site
            if back is not None:
                cycle = tuple([held] + back)
                _VIOLATIONS.append((cycle, site))
                log.error(
                    "LOCK-ORDER VIOLATION: acquiring %s while holding "
                    "%s at %s closes the cycle %s (first-seen sites: "
                    "%s)", lock.name, held, site, " -> ".join(cycle),
                    "; ".join(f"{a}->{b} @ {_EDGES[(a, b)]}"
                              for a, b in zip(cycle, cycle[1:])
                              if (a, b) in _EDGES))


def _note_released(lock: "TrackedLock") -> None:
    stack = _held_stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] == id(lock):
            del stack[i]
            return


class TrackedLock:
    """Lock wrapper that witnesses acquisition order. Supports the
    ``with`` protocol plus acquire/release, like threading.Lock."""

    __slots__ = ("name", "_lock", "_reentrant")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched = _san.current_scheduler()
        if sched is not None and blocking:
            # cooperative acquisition: yield the schedule decision to the
            # explorer, then try-acquire in a blocked/retry loop so a
            # *paused* holder can never deadlock the harness
            sched.lock_acquire(self)
            ok = True
        else:
            ok = self._lock.acquire(blocking, timeout)
        if ok:
            _note_acquired(self)
            hook = _san._LOCK_ACQ_HOOK
            if hook is not None:
                hook(self)
        return ok

    def release(self) -> None:
        _note_released(self)
        hook = _san._LOCK_REL_HOOK
        if hook is not None:
            # BEFORE the real release: the lock's vector clock must carry
            # this thread's epoch before any other thread can acquire it
            hook(self)
        self._lock.release()
        sched = _san.current_scheduler()
        if sched is not None:
            sched.lock_released(self)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        if self._reentrant:
            raise AttributeError("RLock has no locked()")
        return self._lock.locked()

    def __repr__(self) -> str:
        return f"<TrackedLock {self.name!r}>"


# --- factories (the only API lock-creation sites use) -----------------------


def tracked_lock(name: str):
    """threading.Lock() unarmed; a named TrackedLock under
    MG_TRACK_LOCKS=1."""
    if armed():
        return TrackedLock(name)
    return threading.Lock()


def tracked_rlock(name: str):
    if armed():
        return TrackedLock(name, reentrant=True)
    return threading.RLock()


# --- inspection / assertion --------------------------------------------------


def edges() -> dict[tuple[str, str], str]:
    with _W_LOCK:
        return dict(_EDGES)


def violations() -> list[tuple[tuple[str, ...], str]]:
    with _W_LOCK:
        return list(_VIOLATIONS)


def reset() -> None:
    with _W_LOCK:
        _EDGES.clear()
        _VIOLATIONS.clear()


class isolated_witness:
    """Context manager for tests: run against a clean witness, then
    restore whatever the surrounding session had recorded so a test's
    deliberate cycle never fails the session-level assert."""

    def __enter__(self):
        with _W_LOCK:
            self._edges = dict(_EDGES)
            self._violations = list(_VIOLATIONS)
            _EDGES.clear()
            _VIOLATIONS.clear()
        return self

    def __exit__(self, *exc):
        with _W_LOCK:
            _EDGES.clear()
            _EDGES.update(self._edges)
            _VIOLATIONS[:] = self._violations


def witness_report() -> str:
    with _W_LOCK:
        lines = [f"lock-order witness: {len(_EDGES)} edge(s), "
                 f"{len(_VIOLATIONS)} violation(s)"]
        for (frm, to), site in sorted(_EDGES.items()):
            lines.append(f"  {frm} -> {to}   first seen {site}")
        for cycle, site in _VIOLATIONS:
            lines.append(f"  CYCLE {' -> '.join(cycle)} closed at {site}")
    return "\n".join(lines)


def assert_acyclic() -> None:
    """Raise LockOrderViolation if any witnessed cycle was recorded."""
    with _W_LOCK:
        if not _VIOLATIONS:
            return
        detail = "; ".join(
            f"{' -> '.join(cycle)} (closed at {site})"
            for cycle, site in _VIOLATIONS)
    raise LockOrderViolation(
        f"lock acquisition order has {len(_VIOLATIONS)} witnessed "
        f"cycle(s): {detail}")
