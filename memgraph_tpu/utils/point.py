"""Spatial point values (Cartesian and WGS-84, 2d/3d) with distance.

Capability parity with the reference's point type
(/root/reference/src/storage/v2/point.hpp) and `point.distance` semantics:
Euclidean distance for Cartesian CRS, haversine (meters) for WGS-84.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from enum import Enum

from ..exceptions import TypeException

WGS84_RADIUS_M = 6_371_009.0  # mean Earth radius


class CrsType(Enum):
    CARTESIAN_2D = 7203
    CARTESIAN_3D = 9157
    WGS84_2D = 4326
    WGS84_3D = 4979

    @property
    def is_wgs(self) -> bool:
        return self in (CrsType.WGS84_2D, CrsType.WGS84_3D)

    @property
    def dims(self) -> int:
        return 3 if self in (CrsType.CARTESIAN_3D, CrsType.WGS84_3D) else 2


@dataclass(frozen=True)
class Point:
    x: float
    y: float
    z: float | None
    crs: CrsType

    @classmethod
    def from_map(cls, m: dict) -> "Point":
        keys = {k.lower(): v for k, v in m.items()}
        crs_name = keys.get("crs")
        has_z = "z" in keys or "height" in keys
        is_wgs = ("longitude" in keys or "latitude" in keys
                  or (crs_name or "").lower().startswith("wgs"))
        if crs_name:
            table = {"cartesian": CrsType.CARTESIAN_2D,
                     "cartesian-3d": CrsType.CARTESIAN_3D,
                     "wgs-84": CrsType.WGS84_2D,
                     "wgs-84-3d": CrsType.WGS84_3D}
            crs = table.get(crs_name.lower())
            if crs is None:
                raise TypeException(f"Unknown CRS: {crs_name!r}")
        elif is_wgs:
            crs = CrsType.WGS84_3D if has_z else CrsType.WGS84_2D
        else:
            crs = CrsType.CARTESIAN_3D if has_z else CrsType.CARTESIAN_2D

        if crs.is_wgs:
            x = keys.get("longitude", keys.get("x"))
            y = keys.get("latitude", keys.get("y"))
            z = keys.get("height", keys.get("z")) if crs.dims == 3 else None
        else:
            x, y = keys.get("x"), keys.get("y")
            z = keys.get("z") if crs.dims == 3 else None
        if x is None or y is None or (crs.dims == 3 and z is None):
            raise TypeException("Missing point coordinate")
        x, y = float(x), float(y)
        z = float(z) if z is not None else None
        if crs.is_wgs and not (-180.0 <= x <= 180.0 and -90.0 <= y <= 90.0):
            raise TypeException("WGS-84 coordinates out of range")
        return cls(x, y, z, crs)

    @property
    def longitude(self) -> float:
        if not self.crs.is_wgs:
            raise TypeException("longitude on non-WGS point")
        return self.x

    @property
    def latitude(self) -> float:
        if not self.crs.is_wgs:
            raise TypeException("latitude on non-WGS point")
        return self.y

    @property
    def height(self) -> float:
        if not self.crs.is_wgs or self.z is None:
            raise TypeException("height on non-WGS-3d point")
        return self.z

    def to_map(self) -> dict:
        if self.crs.is_wgs:
            out = {"longitude": self.x, "latitude": self.y}
            if self.z is not None:
                out["height"] = self.z
            out["crs"] = "wgs-84-3d" if self.crs.dims == 3 else "wgs-84"
        else:
            out = {"x": self.x, "y": self.y}
            if self.z is not None:
                out["z"] = self.z
            out["crs"] = "cartesian-3d" if self.crs.dims == 3 else "cartesian"
        return out

    def distance(self, other: "Point") -> float:
        if self.crs != other.crs:
            raise TypeException("point.distance between different CRS")
        if self.crs.is_wgs:
            d = _haversine_m(self.y, self.x, other.y, other.x)
            if self.crs.dims == 3:
                dz = (self.z or 0.0) - (other.z or 0.0)
                return math.hypot(d, dz)
            return d
        dx, dy = self.x - other.x, self.y - other.y
        if self.crs.dims == 3:
            return math.sqrt(dx * dx + dy * dy
                             + ((self.z or 0.0) - (other.z or 0.0)) ** 2)
        return math.hypot(dx, dy)

    def __str__(self) -> str:
        return "point(" + ", ".join(f"{k}: {v}" for k, v in self.to_map().items()) + ")"


def _haversine_m(lat1, lon1, lat2, lon2) -> float:
    p1, p2 = math.radians(lat1), math.radians(lat2)
    dp = p2 - p1
    dl = math.radians(lon2 - lon1)
    a = math.sin(dp / 2) ** 2 + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2
    return 2 * WGS84_RADIUS_M * math.asin(math.sqrt(a))
