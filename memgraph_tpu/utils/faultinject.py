"""Deterministic fault injection for the durability / replication stack.

Named fault points are wired into the crash-critical call sites
(WAL writes, fsyncs, snapshot renames, replication sends/receives, Raft
RPCs, kvstore puts). Each point can be armed — programmatically via
``arm()`` or from the ``MEMGRAPH_TPU_FAULTS`` environment variable — to
fire one of a small set of failure actions at specific hit counts, so a
failing run replays byte-for-byte identically.

Env grammar (comma-separated specs)::

    MEMGRAPH_TPU_FAULTS="wal.write=kill@3,repl.send=raise@2,wal.write=torn:7+kill@5"

    <point>=<action>[:<arg>][+<then>]@<hit>[;<hit>...]

Actions:
    raise         raise FaultInjected (an OSError subclass — the network
                  call sites treat it exactly like a dropped connection)
    kill          os._exit(137): simulates kill -9 at that byte offset
    drop          the site silently skips the operation (fire() returns
                  "drop"; only honored by sites where skipping is
                  meaningful, e.g. raft.rpc loses the RPC)
    delay:<sec>   sleep, then continue normally
    torn:<n>      (write sites only) write the first n bytes of the
                  record, flush, then raise — or ``torn:<n>+kill`` to
                  exit(137) after the partial write ("torn write")

``@<hits>`` is a semicolon-separated list of 1-based hit numbers at
which the action fires; omitted means every hit. ``seeded_schedule()``
derives hit numbers from a seed, so randomized campaigns replay exactly.

The registry is process-global; an unarmed point costs one attribute
read (module flag) per call.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field

log = logging.getLogger(__name__)

ENV_VAR = "MEMGRAPH_TPU_FAULTS"
KILL_EXIT_CODE = 137  # the code a SIGKILLed process reports

#: the catalog of wired fault points (arming an unknown name is an error
#: so a typo in a test cannot silently arm nothing)
KNOWN_POINTS = (
    "wal.write",       # WalFile.sink, around the record write (torn-able)
    "wal.fsync",       # WalFile.sink, before os.fsync
    "snapshot.rename", # create_snapshot, before the tmp→final os.replace
    "repl.send",       # ReplicaClient frame/system/2PC sends
    "repl.recv",       # ReplicaServer, before handling a received frame
    "raft.rpc",        # RaftNode._call_peer ("drop" = RPC lost)
    "kvstore.put",     # KVStore.put, before the sqlite write
    "mgmt.rpc",        # coordination.mgmt_call ("drop" = mgmt RPC lost)
    # --- device fault family (utils/devicefault.py wraps these into
    # typed XLA-shaped errors at every device dispatch boundary) ---
    "device.call",     # dispatch raises XlaRuntimeError (compile/run fail)
    "device.oom",      # dispatch raises RESOURCE_EXHAUSTED (HBM OOM)
    "device.hang",     # arm with delay:<sec> — dispatch stalls past its
    #                    deadline (the wedge class supervision contains)
    "device.lost",     # backend gone: arm "raise" for an in-process
    #                    DeviceLostError, "kill" to take down the whole
    #                    process (the resident kernel-server daemon case)
    # --- streaming ingestion (query/streams.py consumer loop) ---
    "stream.poll",     # Stream._loop, before source.poll ("raise" =
    #                    broker/file unreachable; reconnect path)
    "stream.commit",   # Stream._loop, before source.commit — the window
    #                    the transactional offset record closes
    "stream.transform",# Stream._loop, around the user transform
)

#: device-plane nemesis ops (tools/mgchaos device schedules). Same
#: MG005-style contract as NEMESIS_OPS, but these arm the scalar
#: ``device.*`` fault points above instead of installing link rules:
#: every op here must map to a registered device point AND be exercised
#: by the seeded device sweep (tests/test_device_resilience.py).
DEVICE_NEMESIS_OPS = (
    "device_call",     # arms device.call  (raise)
    "device_oom",      # arms device.oom   (raise)
    "device_hang",     # arms device.hang  (delay)
    "device_lost",     # arms device.lost  (raise / kill)
)


def device_point_for_op(op: str) -> str:
    """Map a DEVICE_NEMESIS_OPS entry to its scalar fault point."""
    if op not in DEVICE_NEMESIS_OPS:
        raise ValueError(f"unknown device nemesis op {op!r}")
    return "device." + op[len("device_"):]

#: the catalog of nemesis operations tools/mgchaos schedules (the
#: MG005-style coverage contract: every op here must map to a live
#: ``net_*``/cluster hook AND be exercised by at least one test)
NEMESIS_OPS = (
    "partition",          # symmetric partition of a peer pair
    "partition_oneway",   # asymmetric: src->dst traffic lost, dst->src fine
    "partition_node",     # isolate one node from everybody (a "pause")
    "delay",              # fixed extra latency on a link
    "duplicate",          # every message on the link delivered twice
    # streaming ingestion plane (r17, mgstream; cluster-harness op: the
    # harness kills/restarts a stream consumer, not a net_* rule).
    # Position matters: the tuple order feeds the seeded schedule's op
    # draw, and the 10-seed sweep (tests/test_chaos.py) must exercise
    # every op — appending at the end starves partition_oneway.
    "stream_consumer_kill",  # kill a consumer mid-batch; heal restarts it
    "reorder",            # seeded jitter on the link (messages overtake)
    "kill_restart",       # node churn: hard-kill a node, later restart it
    # --- sharded OLTP plane (r18, mgshard; cluster-harness ops like
    # kill_restart — the harness drives ShardPlane, not a net_* rule) ---
    "shard_move",         # live-rebalance a shard to a fresh worker
    "shard_worker_kill",  # SIGKILL a shard owner; heal respawns it
)


class FaultInjected(OSError):
    """Raised at an armed fault point.

    Subclasses OSError deliberately: replication and Raft call sites
    already handle (ConnectionError, OSError) as "peer unreachable", so
    an injected network fault exercises exactly the production handling.
    """


@dataclass
class _FaultSpec:
    point: str
    action: str                      # raise | kill | drop | delay | torn
    arg: float | None = None         # delay seconds / torn byte count
    then: str = "raise"              # torn follow-up: raise | kill
    hits: frozenset[int] | None = None   # 1-based; None = every hit
    fired: int = field(default=0)

    def matches(self, hit: int) -> bool:
        return self.hits is None or hit in self.hits


_LOCK = threading.Lock()
_SPECS: dict[str, list[_FaultSpec]] = {}
_COUNTS: dict[str, int] = {}
_ARMED = False   # fast-path flag: unarmed fire() is one global read


def _parse_spec(text: str) -> _FaultSpec:
    text = text.strip()
    point, _, rest = text.partition("=")
    point = point.strip()
    if point not in KNOWN_POINTS:
        raise ValueError(f"unknown fault point {point!r} "
                         f"(known: {', '.join(KNOWN_POINTS)})")
    if not rest:
        raise ValueError(f"fault spec {text!r} has no action")
    action_part, _, hits_part = rest.partition("@")
    then = "raise"
    if "+" in action_part:
        action_part, _, then = action_part.partition("+")
        if then not in ("raise", "kill"):
            raise ValueError(f"bad torn follow-up {then!r}")
    action, _, arg_s = action_part.partition(":")
    action = action.strip()
    if action not in ("raise", "kill", "drop", "delay", "torn"):
        raise ValueError(f"unknown fault action {action!r}")
    arg: float | None = None
    if action == "delay":
        arg = float(arg_s or 0.05)
    elif action == "torn":
        arg = int(arg_s or 0)
    hits = None
    if hits_part:
        hits = frozenset(int(h) for h in hits_part.split(";") if h)
    return _FaultSpec(point, action, arg, then, hits)


def arm(point: str, action: str, *, arg: float | None = None,
        at: int | list[int] | None = None, then: str = "raise") -> None:
    """Arm one fault point programmatically (tests)."""
    if point not in KNOWN_POINTS:
        raise ValueError(f"unknown fault point {point!r}")
    hits = None
    if at is not None:
        hits = frozenset([at] if isinstance(at, int) else at)
    spec = _FaultSpec(point, action, arg, then, hits)
    global _ARMED
    with _LOCK:
        _SPECS.setdefault(point, []).append(spec)
        _ARMED = True


def arm_from_string(text: str) -> None:
    """Arm from the env-var grammar (also used by the env loader)."""
    global _ARMED
    for chunk in text.split(","):
        if not chunk.strip():
            continue
        spec = _parse_spec(chunk)
        with _LOCK:
            _SPECS.setdefault(spec.point, []).append(spec)
            _ARMED = True


def disarm(point: str) -> None:
    """Disarm one fault point (nemesis heal); hit counters are kept so
    later re-arming at a seeded hit number stays byte-replayable."""
    global _ARMED
    with _LOCK:
        _SPECS.pop(point, None)
        _ARMED = bool(_SPECS)


def reset(reload_env: bool = False) -> None:
    """Disarm everything (scalar faults AND the network model) and zero
    the hit counters."""
    global _ARMED, _NET_ARMED
    with _LOCK:
        _SPECS.clear()
        _COUNTS.clear()
        _ARMED = False
        _NET_RULES.clear()
        _NET_ARMED = False
    if reload_env:
        _load_env()


def hit_count(point: str) -> int:
    with _LOCK:
        return _COUNTS.get(point, 0)


def seeded_schedule(seed: int, points=KNOWN_POINTS,
                    max_hit: int = 16) -> dict[str, int]:
    """Deterministic {point: hit_number} schedule derived from a seed.

    The same seed always yields the same schedule (points are visited in
    sorted order), so a failure found by a randomized campaign replays
    exactly by re-arming with the same seed.
    """
    rng = random.Random(seed)
    return {p: rng.randint(1, max_hit) for p in sorted(points)}


def arm_seeded(seed: int, points=KNOWN_POINTS, action: str = "raise",
               max_hit: int = 16) -> dict[str, int]:
    schedule = seeded_schedule(seed, points, max_hit)
    for point, hit in schedule.items():
        arm(point, action, at=hit)
    return schedule


def _next_matching(point: str) -> _FaultSpec | None:
    """Count a hit on `point`; return the armed spec that fires on it."""
    with _LOCK:
        hit = _COUNTS.get(point, 0) + 1
        _COUNTS[point] = hit
        for spec in _SPECS.get(point, ()):
            if spec.matches(hit):
                spec.fired += 1
                return spec
    return None


def _execute(spec: _FaultSpec, hit: int) -> str | None:
    if spec.action == "delay":
        time.sleep(spec.arg or 0.05)
        return None
    if spec.action == "drop":
        log.warning("faultinject: dropping at %s (hit %d)", spec.point, hit)
        return "drop"
    if spec.action == "kill":
        log.error("faultinject: killing process at %s (hit %d)",
                  spec.point, hit)
        os._exit(KILL_EXIT_CODE)
    # raise (torn is handled by faulty_write; firing it via fire() is
    # equivalent to raise — there is no payload to tear here)
    raise FaultInjected(f"injected fault at {spec.point} (hit {hit})")


def fire(point: str) -> str | None:
    """Hook call site. Returns "drop" when the site should silently skip
    the operation, None to continue; raises FaultInjected or kills the
    process per the armed action."""
    if not _ARMED:
        return None
    spec = _next_matching(point)
    if spec is None:
        return None
    return _execute(spec, _COUNTS.get(point, 0))


def faulty_write(point: str, fileobj, data: bytes) -> None:
    """Write `data` to `fileobj`, honoring torn-write faults at `point`.

    A torn spec writes only the first n bytes, flushes them so they
    actually land in the file, then raises (or kills) — reproducing a
    crash mid-write at an exact byte offset.
    """
    if not _ARMED:
        fileobj.write(data)
        return
    spec = _next_matching(point)
    if spec is None:
        fileobj.write(data)
        return
    hit = _COUNTS.get(point, 0)
    if spec.action == "torn":
        n = int(spec.arg or 0)
        fileobj.write(data[:n])
        fileobj.flush()
        log.error("faultinject: torn write at %s — %d/%d bytes (hit %d)",
                  point, n, len(data), hit)
        if spec.then == "kill":
            os._exit(KILL_EXIT_CODE)
        raise FaultInjected(
            f"injected torn write at {point}: {n}/{len(data)} bytes")
    result = _execute(spec, hit)
    if result == "drop":
        return  # the write is silently lost
    fileobj.write(data)


# --- peer-aware network model (the mgchaos nemesis layer) --------------------
#
# Where the scalar points above fault ONE call site, the network model
# faults LINKS: rules are keyed on (src, dst) logical node names and
# evaluated by every cluster RPC site (raft._call_peer, replication
# send/ack, coordinator mgmt RPCs) in BOTH directions, so asymmetric
# one-way partitions behave like real ones — the request arrives and is
# executed, only the ack is lost. "*" matches any node. All state is
# process-global like the scalar registry: an in-process cluster shares
# one network.

NET_ACTIONS = ("drop", "delay", "duplicate", "reorder")


@dataclass
class _LinkRule:
    src: str                 # node name or "*"
    dst: str
    action: str              # one of NET_ACTIONS
    arg: float = 0.0         # delay seconds / reorder max jitter seconds

    def matches(self, src: str | None, dst: str | None) -> bool:
        # None = the caller did not declare a node identity (an admin /
        # harness connection); such traffic is nemesis-exempt
        if src is None or dst is None:
            return False
        return (self.src == "*" or self.src == src) and \
            (self.dst == "*" or self.dst == dst)


_NET_RULES: list[_LinkRule] = []
_NET_ARMED = False           # fast path: unarmed net_fire() is one read
_NET_RNG = random.Random(0)  # reorder jitter; reseed via net_seed()


def net_seed(seed: int) -> None:
    """Seed the jitter RNG so reorder delays replay deterministically."""
    global _NET_RNG
    with _LOCK:
        _NET_RNG = random.Random(seed)


def _net_add(src: str, dst: str, action: str, arg: float = 0.0) -> None:
    if action not in NET_ACTIONS:
        raise ValueError(f"unknown net action {action!r} "
                         f"(known: {', '.join(NET_ACTIONS)})")
    global _NET_ARMED
    with _LOCK:
        _NET_RULES.append(_LinkRule(src, dst, action, arg))
        _NET_ARMED = True


def net_partition(a: str, b: str, *, bidirectional: bool = True) -> None:
    """Partition a↔b (or only a→b with ``bidirectional=False``)."""
    _net_add(a, b, "drop")
    if bidirectional:
        _net_add(b, a, "drop")


def net_partition_node(node: str) -> None:
    """Isolate one node from everybody (both directions)."""
    _net_add(node, "*", "drop")
    _net_add("*", node, "drop")


def net_delay(a: str, b: str, seconds: float, *,
              bidirectional: bool = True) -> None:
    _net_add(a, b, "delay", seconds)
    if bidirectional:
        _net_add(b, a, "delay", seconds)


def net_duplicate(a: str, b: str, *, bidirectional: bool = True) -> None:
    _net_add(a, b, "duplicate")
    if bidirectional:
        _net_add(b, a, "duplicate")


def net_reorder(a: str, b: str, jitter: float = 0.05, *,
                bidirectional: bool = True) -> None:
    """Seeded random per-message jitter: messages overtake each other."""
    _net_add(a, b, "reorder", jitter)
    if bidirectional:
        _net_add(b, a, "reorder", jitter)


def net_heal(a: str | None = None, b: str | None = None) -> None:
    """Remove link rules. ``net_heal()`` heals everything;
    ``net_heal(a)`` heals every link touching a; ``net_heal(a, b)``
    heals both directions of that pair."""
    global _NET_ARMED
    with _LOCK:
        if a is None:
            _NET_RULES.clear()
        elif b is None:
            _NET_RULES[:] = [r for r in _NET_RULES
                             if a not in (r.src, r.dst)]
        else:
            _NET_RULES[:] = [r for r in _NET_RULES
                             if {r.src, r.dst} != {a, b}
                             and (r.src, r.dst) not in ((a, b), (b, a))]
        _NET_ARMED = bool(_NET_RULES)


def net_links() -> list[tuple[str, str, str]]:
    """Current (src, dst, action) rules — for SHOW-style introspection."""
    with _LOCK:
        return [(r.src, r.dst, r.action) for r in _NET_RULES]


def _net_execute(rules: list[_LinkRule]) -> str | None:
    """Apply matched rules: drop dominates, delays accumulate,
    duplicate is reported back to the caller (RPC sites re-send)."""
    result = None
    sleep_s = 0.0
    for rule in rules:
        if rule.action == "drop":
            return "drop"
        if rule.action == "delay":
            sleep_s += rule.arg
        elif rule.action == "reorder":
            with _LOCK:
                sleep_s += _NET_RNG.random() * rule.arg
        elif rule.action == "duplicate":
            result = "duplicate"
    if sleep_s > 0.0:
        time.sleep(sleep_s)
    return result


def net_fire(src: str | None, dst: str | None) -> str | None:
    """Link hook for one message direction src→dst. Returns "drop" when
    the message is lost, "duplicate" when the caller should deliver it
    twice, None to continue (delays/jitter already slept). ``None`` for
    src or dst marks nemesis-exempt traffic (admin/harness connections
    with no declared node identity) — it never matches a rule."""
    if not _NET_ARMED:
        return None
    if src is None or dst is None:
        return None
    with _LOCK:
        matched = [r for r in _NET_RULES if r.matches(src, dst)]
    if not matched:
        return None
    return _net_execute(matched)


def _load_env() -> None:
    text = os.environ.get(ENV_VAR, "")
    if text:
        try:
            arm_from_string(text)
        except ValueError:
            log.exception("faultinject: bad %s value %r", ENV_VAR, text)


_load_env()
