"""Persistent XLA compilation cache setup.

On the tunneled TPU platform a cold compile of the MXU pagerank kernel
costs ~20-30s; with the persistent cache enabled the same process-cold
call deserializes the executable in ~1-2s. The reference keeps exactly
this kind of prepared-state cache native-side (mg_utils.hpp snapshot
build); here the compiler artifact itself is the prepared state.

Called lazily from every kernel entry point (bench stages, GraphCache,
module procedures). Safe to call multiple times; must run before the
first jit compile to be effective for it.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

_done = False


def default_cache_dir() -> str:
    env = os.environ.get("MEMGRAPH_TPU_COMPILE_CACHE_DIR")
    if env:
        return env
    # repo-local when running from a checkout (bench/driver), else ~/.cache
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.path.isdir(os.path.join(repo, ".git")):
        return os.path.join(repo, ".jax_cache")
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "memgraph_tpu", "jax_cache")


def ensure_compile_cache() -> bool:
    """Enable jax's persistent compilation cache (idempotent).

    Returns True if the cache is (already) enabled. Disabled by setting
    MEMGRAPH_TPU_COMPILE_CACHE=0.
    """
    global _done
    if _done:
        return True
    if os.environ.get("MEMGRAPH_TPU_COMPILE_CACHE", "1") == "0":
        return False
    try:
        import jax
        path = default_cache_dir()
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything that takes meaningful time; entries are small
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        log.info("persistent compile cache unavailable: %s", e)
        return False
    _done = True
    return True
