"""Persistent XLA compilation cache setup.

On the tunneled TPU platform a cold compile of the MXU pagerank kernel
costs ~20-30s; with the persistent cache enabled the same process-cold
call deserializes the executable in ~1-2s. The reference keeps exactly
this kind of prepared-state cache native-side (mg_utils.hpp snapshot
build); here the compiler artifact itself is the prepared state.

Called lazily from every kernel entry point (bench stages, GraphCache,
module procedures). Safe to call multiple times; must run before the
first jit compile to be effective for it.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

_done = False
_compile_listener = False


def install_compile_counter() -> bool:
    """Runtime witness for the mgxla static compile budget: every XLA
    backend compile in this process bumps the ``jit.compile_total``
    counter (exported through SHOW METRICS INFO / ``GET /stats``), so a
    silent recompile storm — the exact hazard mglint MG008 and the
    lane-bucket contract check guard statically — shows up as a moving
    counter in production. Idempotent; riding ``jax.monitoring``'s
    backend-compile duration event keeps it zero-cost when nothing
    compiles."""
    global _compile_listener
    if _compile_listener:
        return True
    try:
        from jax import monitoring
    except Exception as e:  # noqa: BLE001 — the witness is optional
        log.info("jax.monitoring unavailable; jit.compile_total "
                 "disabled: %s", e)
        return False

    def _on_duration(event: str, duration: float = 0.0, **kw) -> None:
        if event == "/jax/core/compile/backend_compile_duration":
            from ..observability.metrics import global_metrics
            global_metrics.increment("jit.compile_total")

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception as e:  # noqa: BLE001 — the witness is optional
        log.info("could not register compile listener; "
                 "jit.compile_total disabled: %s", e)
        return False
    _compile_listener = True
    return True


def default_cache_dir() -> str:
    env = os.environ.get("MEMGRAPH_TPU_COMPILE_CACHE_DIR")
    if env:
        return env
    # repo-local when running from a checkout (bench/driver), else ~/.cache
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if os.path.isdir(os.path.join(repo, ".git")):
        return os.path.join(repo, ".jax_cache")
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "memgraph_tpu", "jax_cache")


def ensure_compile_cache() -> bool:
    """Enable jax's persistent compilation cache (idempotent).

    Returns True if the cache is (already) enabled. Disabled by setting
    MEMGRAPH_TPU_COMPILE_CACHE=0.
    """
    global _done
    # the compile-count witness installs even when the persistent cache
    # is opted out — budget observability must not depend on caching
    install_compile_counter()
    if _done:
        return True
    if os.environ.get("MEMGRAPH_TPU_COMPILE_CACHE", "1") == "0":
        return False
    try:
        import jax
        path = default_cache_dir()
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache everything that takes meaningful time; entries are small
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # noqa: BLE001 — cache is an optimization only
        log.info("persistent compile cache unavailable: %s", e)
        return False
    _done = True
    return True


def honor_jax_platforms_env() -> None:
    """Re-apply JAX_PLATFORMS after a site hook pre-initialized jax with
    a different backend (the axon .pth pins the TPU plugin regardless of
    env — a cpu-pinned process must not touch, or hang on, the tunnel).
    Shared by the composition root and the kernel-server daemon
    (bench.py stages do the same dance on their own BENCH_JAX_PLATFORM
    variable); failures are LOGGED, not swallowed, because silently
    running on the pinned backend is exactly the hang this prevents."""
    platform = os.environ.get("JAX_PLATFORMS")
    if not platform:
        return
    try:
        import jax
        jax.config.update("jax_platforms", platform)
    except Exception:  # noqa: BLE001 — diagnose, then proceed pinned
        log.exception("could not re-apply JAX_PLATFORMS=%s; this process "
                      "will use the pre-initialized backend", platform)
