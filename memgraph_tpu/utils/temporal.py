"""Cypher temporal types: Date, LocalTime, LocalDateTime, ZonedDateTime, Duration.

Capability parity with the reference's temporal values
(/root/reference/src/utils/temporal.hpp) — microsecond precision, ISO-8601
construction, component accessors, and +/- arithmetic with Duration — built on
Python's datetime rather than hand-rolled calendars.
"""

from __future__ import annotations

import datetime as _dt
import re
from dataclasses import dataclass
from functools import total_ordering

from ..exceptions import TypeException

MICROS_PER_SECOND = 1_000_000
MICROS_PER_MINUTE = 60 * MICROS_PER_SECOND
MICROS_PER_HOUR = 60 * MICROS_PER_MINUTE
MICROS_PER_DAY = 24 * MICROS_PER_HOUR


@total_ordering
@dataclass(frozen=True)
class Duration:
    """Signed duration with microsecond resolution, stored as total micros."""

    micros: int = 0

    @classmethod
    def from_parts(cls, *, days=0, hours=0, minutes=0, seconds=0,
                   milliseconds=0, microseconds=0) -> "Duration":
        total = (int(days) * MICROS_PER_DAY + int(hours) * MICROS_PER_HOUR
                 + int(minutes) * MICROS_PER_MINUTE)
        # fractional seconds are allowed in Cypher duration maps
        total += round(seconds * MICROS_PER_SECOND)
        total += round(milliseconds * 1000)
        total += round(microseconds)
        return cls(total)

    _ISO_RE = re.compile(
        r"^(?P<sign>-)?P(?!$)(?:(?P<days>\d+(?:\.\d+)?)D)?"
        r"(?:T(?!$)(?:(?P<hours>\d+(?:\.\d+)?)H)?(?:(?P<minutes>\d+(?:\.\d+)?)M)?"
        r"(?:(?P<seconds>\d+(?:\.\d+)?)S)?)?$")

    @classmethod
    def parse(cls, text: str) -> "Duration":
        m = cls._ISO_RE.match(text.strip())
        if not m:
            raise TypeException(f"Invalid duration string: {text!r}")
        g = {k: float(v) if v else 0.0 for k, v in m.groupdict(default="").items()
             if k != "sign"}
        d = cls.from_parts(days=0, hours=g["hours"], minutes=g["minutes"],
                           seconds=g["seconds"])
        d = Duration(d.micros + round(g["days"] * MICROS_PER_DAY))
        return Duration(-d.micros) if m.group("sign") else d

    # accessors (Cypher exposes day/hour/minute/second/... of normalized form)
    @property
    def days(self) -> int:
        return self.micros // MICROS_PER_DAY

    @property
    def hours(self) -> int:
        return (self.micros % MICROS_PER_DAY) // MICROS_PER_HOUR

    @property
    def minutes(self) -> int:
        return (self.micros % MICROS_PER_HOUR) // MICROS_PER_MINUTE

    @property
    def seconds(self) -> int:
        return (self.micros % MICROS_PER_MINUTE) // MICROS_PER_SECOND

    @property
    def microseconds(self) -> int:
        return self.micros % MICROS_PER_SECOND

    def to_timedelta(self) -> _dt.timedelta:
        return _dt.timedelta(microseconds=self.micros)

    def __add__(self, other):
        if isinstance(other, Duration):
            return Duration(self.micros + other.micros)
        return NotImplemented

    def __sub__(self, other):
        if isinstance(other, Duration):
            return Duration(self.micros - other.micros)
        return NotImplemented

    def __neg__(self):
        return Duration(-self.micros)

    def __lt__(self, other):
        if isinstance(other, Duration):
            return self.micros < other.micros
        return NotImplemented

    def __str__(self) -> str:
        m = abs(self.micros)
        sign = "-" if self.micros < 0 else ""
        days, m = divmod(m, MICROS_PER_DAY)
        hours, m = divmod(m, MICROS_PER_HOUR)
        minutes, m = divmod(m, MICROS_PER_MINUTE)
        seconds, micros = divmod(m, MICROS_PER_SECOND)
        frac = f".{micros:06d}".rstrip("0") if micros else ""
        return f"{sign}P{days}DT{hours}H{minutes}M{seconds}{frac}S"


@total_ordering
@dataclass(frozen=True)
class Date:
    d: _dt.date

    @classmethod
    def parse(cls, text: str) -> "Date":
        try:
            return cls(_dt.date.fromisoformat(text.strip()))
        except ValueError as e:
            raise TypeException(f"Invalid date string: {text!r}") from e

    @classmethod
    def from_parts(cls, year: int, month: int = 1, day: int = 1) -> "Date":
        try:
            return cls(_dt.date(year, month, day))
        except ValueError as e:
            raise TypeException(str(e)) from e

    @classmethod
    def today(cls) -> "Date":
        return cls(_dt.date.today())

    year = property(lambda self: self.d.year)
    month = property(lambda self: self.d.month)
    day = property(lambda self: self.d.day)

    def __add__(self, other):
        if isinstance(other, Duration):
            return Date((_dt.datetime.combine(self.d, _dt.time())
                         + other.to_timedelta()).date())
        return NotImplemented

    __radd__ = __add__

    def __sub__(self, other):
        if isinstance(other, Duration):
            return Date((_dt.datetime.combine(self.d, _dt.time())
                         - other.to_timedelta()).date())
        if isinstance(other, Date):
            delta = _dt.datetime.combine(self.d, _dt.time()) - \
                _dt.datetime.combine(other.d, _dt.time())
            return Duration(round(delta.total_seconds() * MICROS_PER_SECOND))
        return NotImplemented

    def __lt__(self, other):
        if isinstance(other, Date):
            return self.d < other.d
        return NotImplemented

    def __str__(self) -> str:
        return self.d.isoformat()


@total_ordering
@dataclass(frozen=True)
class LocalTime:
    t: _dt.time

    @classmethod
    def parse(cls, text: str) -> "LocalTime":
        try:
            return cls(_dt.time.fromisoformat(text.strip()))
        except ValueError as e:
            raise TypeException(f"Invalid local time string: {text!r}") from e

    @classmethod
    def from_parts(cls, hour=0, minute=0, second=0, millisecond=0,
                   microsecond=0) -> "LocalTime":
        try:
            return cls(_dt.time(hour, minute, second,
                                millisecond * 1000 + microsecond))
        except ValueError as e:
            raise TypeException(str(e)) from e

    hour = property(lambda self: self.t.hour)
    minute = property(lambda self: self.t.minute)
    second = property(lambda self: self.t.second)
    millisecond = property(lambda self: self.t.microsecond // 1000)
    microsecond = property(lambda self: self.t.microsecond % 1000)

    def _micros(self) -> int:
        return (self.t.hour * MICROS_PER_HOUR + self.t.minute * MICROS_PER_MINUTE
                + self.t.second * MICROS_PER_SECOND + self.t.microsecond)

    def __add__(self, other):
        if isinstance(other, Duration):
            m = (self._micros() + other.micros) % MICROS_PER_DAY
            return LocalTime(_micros_to_time(m))
        return NotImplemented

    __radd__ = __add__

    def __sub__(self, other):
        if isinstance(other, Duration):
            m = (self._micros() - other.micros) % MICROS_PER_DAY
            return LocalTime(_micros_to_time(m))
        if isinstance(other, LocalTime):
            return Duration(self._micros() - other._micros())
        return NotImplemented

    def __lt__(self, other):
        if isinstance(other, LocalTime):
            return self.t < other.t
        return NotImplemented

    def __str__(self) -> str:
        return self.t.isoformat()


def _micros_to_time(m: int) -> _dt.time:
    hours, m = divmod(m, MICROS_PER_HOUR)
    minutes, m = divmod(m, MICROS_PER_MINUTE)
    seconds, micros = divmod(m, MICROS_PER_SECOND)
    return _dt.time(hours, minutes, seconds, micros)


@total_ordering
@dataclass(frozen=True)
class LocalDateTime:
    dt: _dt.datetime  # naive

    @classmethod
    def parse(cls, text: str) -> "LocalDateTime":
        try:
            dt = _dt.datetime.fromisoformat(text.strip())
        except ValueError as e:
            raise TypeException(f"Invalid local datetime string: {text!r}") from e
        if dt.tzinfo is not None:
            raise TypeException("LocalDateTime must not carry a timezone")
        return cls(dt)

    @classmethod
    def from_parts(cls, year, month=1, day=1, hour=0, minute=0, second=0,
                   millisecond=0, microsecond=0) -> "LocalDateTime":
        try:
            return cls(_dt.datetime(year, month, day, hour, minute, second,
                                    millisecond * 1000 + microsecond))
        except ValueError as e:
            raise TypeException(str(e)) from e

    @classmethod
    def now(cls) -> "LocalDateTime":
        return cls(_dt.datetime.now())

    year = property(lambda self: self.dt.year)
    month = property(lambda self: self.dt.month)
    day = property(lambda self: self.dt.day)
    hour = property(lambda self: self.dt.hour)
    minute = property(lambda self: self.dt.minute)
    second = property(lambda self: self.dt.second)
    millisecond = property(lambda self: self.dt.microsecond // 1000)
    microsecond = property(lambda self: self.dt.microsecond % 1000)

    def date(self) -> Date:
        return Date(self.dt.date())

    def local_time(self) -> LocalTime:
        return LocalTime(self.dt.time())

    def timestamp_micros(self) -> int:
        epoch = _dt.datetime(1970, 1, 1)
        return round((self.dt - epoch).total_seconds() * MICROS_PER_SECOND)

    def __add__(self, other):
        if isinstance(other, Duration):
            return LocalDateTime(self.dt + other.to_timedelta())
        return NotImplemented

    __radd__ = __add__

    def __sub__(self, other):
        if isinstance(other, Duration):
            return LocalDateTime(self.dt - other.to_timedelta())
        if isinstance(other, LocalDateTime):
            delta = self.dt - other.dt
            return Duration(round(delta.total_seconds() * MICROS_PER_SECOND))
        return NotImplemented

    def __lt__(self, other):
        if isinstance(other, LocalDateTime):
            return self.dt < other.dt
        return NotImplemented

    def __str__(self) -> str:
        return self.dt.isoformat()


@total_ordering
@dataclass(frozen=True)
class ZonedDateTime:
    dt: _dt.datetime  # aware

    @classmethod
    def parse(cls, text: str) -> "ZonedDateTime":
        text = text.strip()
        # support trailing [Area/City] timezone names
        m = re.match(r"^(.*?)\[(.+)\]$", text)
        try:
            if m:
                from zoneinfo import ZoneInfo
                base = _dt.datetime.fromisoformat(m.group(1))
                tz = ZoneInfo(m.group(2))
                if base.tzinfo is None:
                    return cls(base.replace(tzinfo=tz))
                return cls(base.astimezone(tz))
            dt = _dt.datetime.fromisoformat(text)
        except Exception as e:
            raise TypeException(f"Invalid zoned datetime string: {text!r}") from e
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=_dt.timezone.utc)
        return cls(dt)

    @classmethod
    def now(cls) -> "ZonedDateTime":
        return cls(_dt.datetime.now(_dt.timezone.utc))

    year = property(lambda self: self.dt.year)
    month = property(lambda self: self.dt.month)
    day = property(lambda self: self.dt.day)
    hour = property(lambda self: self.dt.hour)
    minute = property(lambda self: self.dt.minute)
    second = property(lambda self: self.dt.second)

    def timestamp_micros(self) -> int:
        return round(self.dt.timestamp() * MICROS_PER_SECOND)

    def timezone_name(self) -> str:
        return str(self.dt.tzinfo)

    def __add__(self, other):
        if isinstance(other, Duration):
            return ZonedDateTime(self.dt + other.to_timedelta())
        return NotImplemented

    __radd__ = __add__

    def __sub__(self, other):
        if isinstance(other, Duration):
            return ZonedDateTime(self.dt - other.to_timedelta())
        if isinstance(other, ZonedDateTime):
            delta = self.dt - other.dt
            return Duration(round(delta.total_seconds() * MICROS_PER_SECOND))
        return NotImplemented

    def __lt__(self, other):
        if isinstance(other, ZonedDateTime):
            return self.dt < other.dt
        return NotImplemented

    def __str__(self) -> str:
        return self.dt.isoformat()
