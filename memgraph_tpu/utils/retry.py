"""Shared retry/backoff policy for cluster networking and device calls.

One policy object replaces the ad-hoc except-and-mark-invalid blocks
that used to be scattered across the replication client, the snapshot
download path, and reconnect loops: exponential backoff with a cap and
deterministic (seedable) jitter, plus a budget after which the caller
degrades instead of retrying forever.

Deadline semantics (r12): a policy can additionally carry

  * ``attempt_timeout`` — the per-attempt budget. Callers making socket
    or kernel-server calls use it as the per-call timeout instead of a
    scattered constant (``attempt_timeout_at`` clips it to whatever is
    left of the overall deadline, so the final attempt cannot overshoot).
  * ``deadline`` — the overall wall-clock budget across ALL attempts
    (including backoff sleeps). ``attempts()`` and ``call()`` stop
    retrying once the next backoff would cross it; the caller sees the
    last real exception, not a synthetic timeout.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator

#: Machine-checked retry classification (mglint MG013 `unsafe-retry`).
#:
#: Every RetryPolicy region (an ``attempts()`` loop or a ``.call(fn)``)
#: must be classified here, by the qualname of the operation it wraps
#: or encloses — "retryable" means the op is idempotent so blind
#: re-execution is safe; "unsafe" means it is not, and the region may
#: only swallow-and-retry exception classes that are themselves
#: registered "retryable" (pre-apply bounces). Exception-class entries
#: marked "unsafe" are outcomes that are deterministic against the
#: current state (oom/shed): retrying them is noise at best and a
#: retry storm at worst, so swallowing one inside ANY retry region is
#: a finding. An entry matched by nothing in the codebase is reported
#: unused — the registry can only shrink honestly.
IDEMPOTENCY = {
    # --- operations (function qualname suffixes) -------------------------
    # reads re-route freely: the worker bounces stale/fenced BEFORE
    # applying anything, and a crashed read left no state behind
    "ShardedClient.read": "retryable",
    "ShardedClient.scatter_read": "retryable",
    # schema DDL broadcast: CREATE INDEX / constraint DDL re-applies
    # convergently, so a bounced shard can simply be re-driven
    "ShardedClient.ddl": "retryable",
    # a single-shard WRITE is not idempotent: a worker that dies after
    # commit but before the ack leaves the outcome in doubt, and a
    # blind re-send double-applies. Only pre-apply bounce classes
    # (StaleShardEpoch) may be swallowed in its retry region.
    "ShardedClient.write": "unsafe",
    # 2PC prepare commits nothing (journal-before-vote); a fresh
    # prepare on a respawned worker is safe by construction
    "ShardedClient._prepare_one": "retryable",
    # 2PC decide is idempotent via the durable pending journal: the
    # whole point of the re-drive path
    "ShardedClient._decide_one": "retryable",
    # kernel requests are pure computations; the server's own
    # idempotent flag gates the fail-fast variant inside the region
    "SupervisedKernelClient._call_supervised": "retryable",
    # routed Bolt writes are DELIBERATELY at-least-once across
    # failovers (the chaos checker models duplicate acks); the mglint
    # baseline carries the justified MG013 entries for this region
    "RoutedClient.execute_write": "unsafe",
    # snapshot fetch for RECOVER is a pure download + atomic rename
    "recover_snapshot_from": "retryable",
    # --- exception classes ----------------------------------------------
    # pre-apply bounces: the owner refused BEFORE applying, so
    # re-sending is safe even under non-idempotent ops
    "StaleShardEpoch": "retryable",
    # transient device-plane outcomes: pure ops may re-dispatch
    "KernelDeviceError": "retryable",
    "KernelDeadlineExceeded": "retryable",
    # deterministic against this budget/graph — deliberately NOT
    # retried anywhere (the "oom/shed" rule, now machine-checked)
    "AdmissionRejected": "unsafe",
    "KernelOom": "unsafe",
}


class RetryPolicy:
    """Exponential backoff: base_delay * factor^n, capped, jittered.

    max_retries is the RETRY budget (total attempts = max_retries + 1).
    A seed makes the jitter sequence reproducible for deterministic
    fault-injection tests.
    """

    def __init__(self, base_delay: float = 0.05, factor: float = 2.0,
                 max_delay: float = 2.0, max_retries: int = 5,
                 jitter: float = 0.2, seed: int | None = None,
                 attempt_timeout: float | None = None,
                 deadline: float | None = None) -> None:
        self.base_delay = base_delay
        self.factor = factor
        self.max_delay = max_delay
        self.max_retries = max_retries
        self.jitter = jitter
        self.attempt_timeout = attempt_timeout
        self.deadline = deadline
        self._rng = random.Random(seed)

    def delay_for(self, attempt: int) -> float:
        """Backoff delay after the (attempt+1)-th failure (attempt >= 0)."""
        delay = min(self.max_delay,
                    self.base_delay * (self.factor ** attempt))
        if self.jitter:
            delay *= 1.0 + self.jitter * self._rng.random()
        return delay

    def delays(self) -> Iterator[float]:
        for attempt in range(self.max_retries):
            yield self.delay_for(attempt)

    def remaining(self, t0: float) -> float | None:
        """Seconds left of the overall deadline started at monotonic t0,
        or None when the policy has no deadline. Floors at 0."""
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - (time.monotonic() - t0))

    def attempt_timeout_at(self, t0: float) -> float | None:
        """Per-attempt timeout for an attempt starting now: the policy's
        attempt_timeout clipped to what the overall deadline (started at
        monotonic t0) still allows; None = unbounded."""
        left = self.remaining(t0)
        if left is None:
            return self.attempt_timeout
        if self.attempt_timeout is None:
            return left
        return min(self.attempt_timeout, left)

    def attempts(self) -> Iterator[int]:
        """Yield attempt numbers 0..max_retries, sleeping the backoff
        BETWEEN yields and honoring the overall deadline: iteration ends
        early (no sleep) once the next backoff would cross it. The
        caller's loop pattern::

            last = None
            for attempt in policy.attempts():
                try:
                    return op()
                except RetryableError as e:
                    last = e
            raise last   # budget or deadline exhausted
        """
        t0 = time.monotonic()
        for attempt in range(self.max_retries + 1):
            yield attempt
            if attempt >= self.max_retries:
                return
            delay = self.delay_for(attempt)
            left = self.remaining(t0)
            if left is not None and delay >= left:
                return
            time.sleep(delay)

    def call(self, fn: Callable, *, retry_on=(ConnectionError, OSError),
             on_retry: Callable | None = None):
        """Run fn(), retrying on `retry_on` with backoff; re-raises the
        last exception once the retry budget OR the overall deadline is
        exhausted."""
        t0 = time.monotonic()
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on as e:
                if attempt >= self.max_retries:
                    raise
                delay = self.delay_for(attempt)
                left = self.remaining(t0)
                if left is not None and delay >= left:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                time.sleep(delay)
                attempt += 1
