"""Shared retry/backoff policy for cluster networking.

One policy object replaces the ad-hoc except-and-mark-invalid blocks
that used to be scattered across the replication client, the snapshot
download path, and reconnect loops: exponential backoff with a cap and
deterministic (seedable) jitter, plus a budget after which the caller
degrades instead of retrying forever.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator


class RetryPolicy:
    """Exponential backoff: base_delay * factor^n, capped, jittered.

    max_retries is the RETRY budget (total attempts = max_retries + 1).
    A seed makes the jitter sequence reproducible for deterministic
    fault-injection tests.
    """

    def __init__(self, base_delay: float = 0.05, factor: float = 2.0,
                 max_delay: float = 2.0, max_retries: int = 5,
                 jitter: float = 0.2, seed: int | None = None) -> None:
        self.base_delay = base_delay
        self.factor = factor
        self.max_delay = max_delay
        self.max_retries = max_retries
        self.jitter = jitter
        self._rng = random.Random(seed)

    def delay_for(self, attempt: int) -> float:
        """Backoff delay after the (attempt+1)-th failure (attempt >= 0)."""
        delay = min(self.max_delay,
                    self.base_delay * (self.factor ** attempt))
        if self.jitter:
            delay *= 1.0 + self.jitter * self._rng.random()
        return delay

    def delays(self) -> Iterator[float]:
        for attempt in range(self.max_retries):
            yield self.delay_for(attempt)

    def call(self, fn: Callable, *, retry_on=(ConnectionError, OSError),
             on_retry: Callable | None = None):
        """Run fn(), retrying on `retry_on` with backoff; re-raises the
        last exception once the budget is exhausted."""
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on as e:
                if attempt >= self.max_retries:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                time.sleep(self.delay_for(attempt))
                attempt += 1
