"""mgsan runtime annotation shim: the product-side half of tools/mgsan.

The package annotates its hot cross-thread shared state with three tiny
calls that are **no-ops unless a sanitizer is armed** (one module-global
``is None`` check each):

``shared_field(owner, "a", "b")``
    Declares attributes of ``owner`` as shared across threads. This is
    simultaneously the *static* marker mglint's MG006/MG007 rules key on
    (they resolve ``X.a`` accesses against these declarations) and the
    *dynamic* registration point for the vector-clock race detector.

``shared_read(owner, "a")`` / ``shared_write(owner, "a")``
    Access annotations placed next to the real attribute access. Armed,
    they (1) give the cooperative schedule explorer a preemption point
    exactly where interleavings matter and (2) feed the FastTrack-style
    happens-before race detector.

``mvcc_event(kind, **fields)``
    Transaction life-cycle / read / write events for the MVCC isolation
    checker's history log (begin, read, write, commit, abort).

``yield_point(label)``
    Explicit scheduling point for multi-threaded tests running under the
    deterministic explorer (tools/mgsan/scheduler.py). Outside an
    explorer run it costs one global read.

tools/mgsan installs the hooks below when armed (``MG_SAN=1`` or
programmatically from tests); memgraph_tpu never imports tools/, so the
production import graph stays closed.
"""

from __future__ import annotations

import os

ENV_VAR = "MG_SAN"


def armed() -> bool:
    return os.environ.get(ENV_VAR, "") not in ("", "0")


# --- hook registry (written only by tools/mgsan) -----------------------------

#: callable(kind, owner, field) — kind is "r" or "w"
_ACCESS_HOOK = None
#: callable(owner, fields) — shared_field declarations
_DECLARE_HOOK = None
#: callable(event: dict) — MVCC history recorder
_MVCC_HOOK = None
#: callable(lock) / callable(lock) — TrackedLock acquired/about-to-release
_LOCK_ACQ_HOOK = None
_LOCK_REL_HOOK = None
#: callable() -> scheduler-or-None for the *current thread* (TLS-based)
_SCHED_RESOLVER = None


def install_hooks(*, access=None, declare=None, mvcc=None, lock_acq=None,
                  lock_rel=None, scheduler=None) -> None:
    """Install (or clear, with explicit None) sanitizer hooks. Only
    tools/mgsan calls this."""
    global _ACCESS_HOOK, _DECLARE_HOOK, _MVCC_HOOK
    global _LOCK_ACQ_HOOK, _LOCK_REL_HOOK, _SCHED_RESOLVER
    _ACCESS_HOOK = access
    _DECLARE_HOOK = declare
    _MVCC_HOOK = mvcc
    _LOCK_ACQ_HOOK = lock_acq
    _LOCK_REL_HOOK = lock_rel
    if scheduler is not None:
        _SCHED_RESOLVER = scheduler


def current_scheduler():
    """The cooperative scheduler driving the current thread, or None."""
    r = _SCHED_RESOLVER
    if r is None:
        return None
    return r()


# --- annotation API (the only calls product code makes) ----------------------


def shared_field(owner, *fields: str) -> None:
    """Declare attributes of ``owner`` as cross-thread shared state.

    Static: mglint MG006 (unguarded-shared-field) and MG007
    (check-then-act) resolve attribute accesses against these
    declarations. Dynamic: registers the fields with the armed race
    detector. Unarmed: a single global read.
    """
    h = _DECLARE_HOOK
    if h is not None:
        h(owner, fields)


def shared_read(owner, field: str) -> None:
    s = current_scheduler()
    if s is not None:
        s.yield_point(f"read:{type(owner).__name__}.{field}")
    h = _ACCESS_HOOK
    if h is not None:
        h("r", owner, field)


def shared_write(owner, field: str) -> None:
    s = current_scheduler()
    if s is not None:
        s.yield_point(f"write:{type(owner).__name__}.{field}")
    h = _ACCESS_HOOK
    if h is not None:
        h("w", owner, field)


def yield_point(label: str = "") -> None:
    s = current_scheduler()
    if s is not None:
        s.yield_point(label or "yield")


def mvcc_event(kind: str, **fields) -> None:
    """Record one MVCC history event (begin/read/write/commit/abort)."""
    h = _MVCC_HOOK
    if h is not None:
        ev = {"e": kind}
        ev.update(fields)
        h(ev)
