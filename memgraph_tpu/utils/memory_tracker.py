"""Per-query and process-wide memory accounting.

Python analog of the reference's allocation tracking
(/root/reference/src/utils/memory_tracker.cpp and
src/memory/query_memory_control.cpp): the reference hooks the allocator
per thread; here the Volcano operators account their MATERIALIZED state
(aggregation groups, sort buffers, DISTINCT sets, eager barriers,
collected lists, result accumulation) — the places where query memory
actually grows without bound — against a per-query limit, and every
query's usage also counts against an optional process-wide limit.

`QUERY MEMORY LIMIT 100 MB` (grammar: Cypher.g4:134-136) attaches a
per-query limit; the `--memory-limit` flag sets the global one.
"""

from __future__ import annotations

import sys
import threading

from ..exceptions import MemgraphTpuError


class MemoryLimitException(MemgraphTpuError):
    pass


def approx_size(value, _depth: int = 2) -> int:
    """Cheap recursive size estimate (caps recursion; containers sample
    the first 16 elements and extrapolate)."""
    try:
        size = sys.getsizeof(value)
    except TypeError:  # pragma: no cover - exotic objects
        return 64
    if _depth <= 0:
        return size
    if isinstance(value, (list, tuple, set, frozenset)):
        n = len(value)
        if n:
            sample = list(value)[:16]
            per = sum(approx_size(v, _depth - 1) for v in sample)
            size += per * n // len(sample)
        return size
    if isinstance(value, dict):
        n = len(value)
        if n:
            items = list(value.items())[:16]
            per = sum(approx_size(k, _depth - 1) + approx_size(v, _depth - 1)
                      for k, v in items)
            size += per * n // len(items)
        return size
    return size


class GlobalMemoryTracker:
    """Sum of all live query trackers vs an optional process limit."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.limit: int | None = None
        self.current = 0
        self.peak = 0

    def add(self, nbytes: int) -> None:
        with self._lock:
            if (self.limit is not None
                    and self.current + nbytes > self.limit):
                # never record the breaching chunk: callers treat a raise
                # as "nothing was added" (QueryMemoryTracker symmetry)
                raise MemoryLimitException(
                    f"global memory limit exceeded: tracked "
                    f"{self.current + nbytes} bytes > limit {self.limit} "
                    "(raise --memory-limit or add QUERY MEMORY LIMIT to "
                    "the offending queries)")
            self.current += nbytes
            if self.current > self.peak:
                self.peak = self.current

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.current -= nbytes
            if self.current < 0:
                self.current = 0


GLOBAL = GlobalMemoryTracker()


class QueryMemoryTracker:
    """One per query execution; released wholesale when the query ends."""

    __slots__ = ("limit", "current", "peak", "_global")

    def __init__(self, limit: int | None = None,
                 global_tracker: GlobalMemoryTracker = None) -> None:
        self.limit = limit
        self.current = 0
        self.peak = 0
        self._global = GLOBAL if global_tracker is None else global_tracker

    def add(self, nbytes: int) -> None:
        # order matters for symmetry with release_all(): self.current must
        # only ever count bytes that were also added to the global tracker,
        # so a raise here (per-query or global limit) records nothing
        if self.limit is not None and self.current + nbytes > self.limit:
            raise MemoryLimitException(
                f"query memory limit exceeded: tracked "
                f"{self.current + nbytes} bytes > limit {self.limit} "
                "(QUERY MEMORY LIMIT)")
        self._global.add(nbytes)
        self.current += nbytes
        if self.current > self.peak:
            self.peak = self.current

    def add_value(self, value) -> None:
        self.add(approx_size(value))

    def release_all(self) -> None:
        self._global.release(self.current)
        self.current = 0
