"""Enterprise license checking (reference: src/license/license.cpp,
license key settings `enterprise.license` / `organization.name` in
flags/run_time_configurable.cpp; surfaced by SHOW LICENSE INFO,
interpreter.cpp SystemInfoQuery::InfoType::LICENSE).

Key format (own design — the reference's `mglk-` scheme is not copied):

    mgtpu-<base64url(JSON payload)>.<sig>

payload = {"organization": str, "type": "enterprise"|"oem"|"ai-platform",
           "valid_until": unix epoch seconds (0 = perpetual),
           "memory_limit": bytes (0 = unlimited)}
sig     = first 16 hex chars of sha256(payload_b64 + "|" + organization)

The signature binds the key to the organization name, so a key only
validates when the `organization.name` setting matches — the same
operator contract as the reference. This is a checksum, not asymmetric
crypto: the goal is parity of behavior (key parsing, expiry, org match,
memory limit plumbing), not DRM.
"""

from __future__ import annotations

import base64
import hashlib
import json
import time

KEY_PREFIX = "mgtpu-"
LICENSE_SETTING = "enterprise.license"
ORGANIZATION_SETTING = "organization.name"

VALID_TYPES = ("enterprise", "oem", "ai-platform")


def _sign(payload_b64: str, organization: str) -> str:
    return hashlib.sha256(
        f"{payload_b64}|{organization}".encode()).hexdigest()[:16]


def generate_key(organization: str, license_type: str = "enterprise",
                 valid_until: int = 0, memory_limit: int = 0) -> str:
    """Mint a key (admin/test helper; the reference ships keys out of
    band, so there is no query surface for this)."""
    if license_type not in VALID_TYPES:
        raise ValueError(f"license type must be one of {VALID_TYPES}")
    payload = json.dumps({
        "organization": organization, "type": license_type,
        "valid_until": int(valid_until), "memory_limit": int(memory_limit),
    }, sort_keys=True).encode()
    blob = base64.urlsafe_b64encode(payload).decode().rstrip("=")
    return f"{KEY_PREFIX}{blob}.{_sign(blob, organization)}"


def _decode(key: str) -> dict:
    """Parse + checksum-verify a key; raises ValueError with the reason."""
    if not key.startswith(KEY_PREFIX):
        raise ValueError(f"license key must start with {KEY_PREFIX!r}")
    blob, _, sig = key[len(KEY_PREFIX):].partition(".")
    try:
        padded = blob + "=" * (-len(blob) % 4)
        payload = json.loads(base64.urlsafe_b64decode(padded))
    except Exception as e:
        raise ValueError(f"malformed license payload: {e}") from e
    org = payload.get("organization", "")
    if sig != _sign(blob, org):
        raise ValueError("license key checksum mismatch")
    if payload.get("type") not in VALID_TYPES:
        raise ValueError(f"unknown license type {payload.get('type')!r}")
    return payload


class LicenseChecker:
    """Validates the key in the runtime settings store on every call —
    `SET DATABASE SETTING 'enterprise.license' TO '...'` takes effect
    immediately, like the reference's observer-driven checker."""

    def __init__(self, settings) -> None:
        self._settings = settings

    def info(self) -> dict:
        key = self._settings.get(LICENSE_SETTING) or ""
        organization = self._settings.get(ORGANIZATION_SETTING) or ""
        result = {
            "organization_name": organization,
            "license_key": key,
            "is_valid": False,
            "license_type": "",
            "valid_until": "",
            "memory_limit": "unlimited",
            "status": "",
        }
        if not key:
            result["status"] = "no license key set"
            return result
        try:
            payload = _decode(key)
        except ValueError as e:
            result["status"] = str(e)
            return result
        if payload["organization"] != organization:
            result["status"] = (
                "license issued to a different organization "
                f"({payload['organization']!r}); set "
                f"'{ORGANIZATION_SETTING}' to match")
            return result
        until = payload.get("valid_until", 0)
        if until:
            result["valid_until"] = time.strftime(
                "%Y-%m-%d", time.gmtime(until))
            if time.time() > until:
                result["license_type"] = payload["type"]
                result["status"] = "license expired"
                return result
        else:
            result["valid_until"] = "forever"
        limit = payload.get("memory_limit", 0)
        if limit:
            result["memory_limit"] = f"{limit / (1024 ** 3):.2f}GiB"
        result["is_valid"] = True
        result["license_type"] = payload["type"]
        result["status"] = "valid"
        return result

    def is_valid(self) -> bool:
        return self.info()["is_valid"]

    def memory_limit(self) -> int:
        """Licensed memory cap in bytes (0 = unlimited / no license).
        Runs the FULL validation — an expired or org-mismatched license
        grants nothing."""
        if not self.is_valid():
            return 0
        return _decode(
            self._settings.get(LICENSE_SETTING))["memory_limit"]
