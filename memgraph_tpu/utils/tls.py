"""TLS contexts for every listener: Bolt, replication, Raft, mgmt RPC.

Reference analog: /root/reference/src/communication/context.cpp
(ServerContext/ClientContext wrapping OpenSSL) plus the intra-cluster TLS
init at memgraph.cpp:302-317, where one cert/key pair configured at startup
covers all cluster-internal channels. Same shape here: `set_cluster_tls`
installs a process-wide pair consulted by the replication and coordination
transports; Bolt takes its own pair (clients terminate TLS differently
than cluster peers).
"""

from __future__ import annotations

import os
import ssl
import threading
from dataclasses import dataclass
from typing import Optional


def server_context(cert_file: str, key_file: str,
                   ca_file: Optional[str] = None) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file)
    if ca_file:
        ctx.load_verify_locations(ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_context(ca_file: Optional[str] = None,
                   cert_file: Optional[str] = None,
                   key_file: Optional[str] = None,
                   verify_hostname: bool = True) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if ca_file:
        ctx.load_verify_locations(ca_file)
        # cluster peers dial by ip:port (verify_hostname=False); end-user
        # bolt+s clients verify the hostname against the CA-signed cert
        ctx.check_hostname = verify_hostname
    else:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    if cert_file and key_file:
        ctx.load_cert_chain(cert_file, key_file)
    return ctx


@dataclass
class ClusterTls:
    cert_file: str
    key_file: str
    ca_file: Optional[str] = None


_cluster: Optional[ClusterTls] = None
_cluster_server_ctx: Optional[ssl.SSLContext] = None
_cluster_client_ctx: Optional[ssl.SSLContext] = None
_lock = threading.Lock()


def set_cluster_tls(cert_file: str, key_file: str,
                    ca_file: Optional[str] = None) -> None:
    """Install intra-cluster TLS (replication + Raft + mgmt RPC). Contexts
    are built once here — Raft heartbeats wrap sockets many times a
    second, so per-connection context construction would hammer disk."""
    global _cluster, _cluster_server_ctx, _cluster_client_ctx
    with _lock:
        _cluster = ClusterTls(cert_file, key_file, ca_file)
        _cluster_server_ctx = server_context(cert_file, key_file, ca_file)
        _cluster_client_ctx = client_context(
            ca_file, cert_file, key_file, verify_hostname=False)


def clear_cluster_tls() -> None:
    global _cluster, _cluster_server_ctx, _cluster_client_ctx
    with _lock:
        _cluster = None
        _cluster_server_ctx = None
        _cluster_client_ctx = None


def cluster_server_context() -> Optional[ssl.SSLContext]:
    with _lock:
        return _cluster_server_ctx


def cluster_client_context() -> Optional[ssl.SSLContext]:
    with _lock:
        return _cluster_client_ctx


def wrap_cluster_server(sock, handshake_timeout: float = 5.0):
    """Wrap an accepted cluster-side connection if TLS is configured.

    A handshake deadline is mandatory: callers run this on per-connection
    threads, but without a timeout a silent peer would pin the thread (and
    a half-open scanner could exhaust them)."""
    ctx = cluster_server_context()
    if ctx is None:
        return sock
    old = sock.gettimeout()
    sock.settimeout(handshake_timeout)
    try:
        wrapped = ctx.wrap_socket(sock, server_side=True)
    finally:
        try:
            sock.settimeout(old)
        except OSError:
            pass
    wrapped.settimeout(old)
    return wrapped


def wrap_cluster_client(sock, server_hostname=None):
    ctx = cluster_client_context()
    if ctx is None:
        return sock
    return ctx.wrap_socket(sock, server_hostname=server_hostname)


def generate_self_signed(directory: str, common_name: str = "memgraph-tpu"
                         ) -> tuple[str, str]:
    """Create a self-signed cert + key (tests / quick start). Returns
    (cert_path, key_path)."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName("localhost"),
                 x509.IPAddress(__import__("ipaddress").ip_address(
                     "127.0.0.1"))]), critical=False)
            .sign(key, hashes.SHA256()))
    os.makedirs(directory, exist_ok=True)
    cert_path = os.path.join(directory, "cert.pem")
    key_path = os.path.join(directory, "key.pem")
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    return cert_path, key_path
