"""Name <-> id interning for labels, edge types, and property names.

The reference interns all label/property/edge-type strings to small integer
ids (NameIdMapper, /root/reference/src/storage/v2/name_id_mapper.hpp) so hot
paths compare ints. The TPU build needs the same ids as the bridge to device
arrays: label ids become rows of label one-hot/segment arrays, property ids
index columnar property exports.
"""

from __future__ import annotations

import threading


class NameIdMapper:
    """Thread-safe bidirectional string<->int interning map.

    Ids are dense, starting at 0, never reused. Safe for concurrent readers
    with occasional writers.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._name_to_id: dict[str, int] = {}
        self._id_to_name: list[str] = []

    def name_to_id(self, name: str) -> int:
        """Intern `name`, returning its id (allocating if unseen)."""
        existing = self._name_to_id.get(name)
        if existing is not None:
            return existing
        with self._lock:
            existing = self._name_to_id.get(name)
            if existing is not None:
                return existing
            new_id = len(self._id_to_name)
            self._id_to_name.append(name)
            self._name_to_id[name] = new_id
            return new_id

    def id_to_name(self, id_: int) -> str:
        return self._id_to_name[id_]

    def has_name(self, name: str) -> bool:
        return name in self._name_to_id

    def maybe_name_to_id(self, name: str) -> int | None:
        return self._name_to_id.get(name)

    def to_dict(self) -> dict[str, int]:
        """Snapshot for persistence (disk mode metadata)."""
        with self._lock:
            return dict(self._name_to_id)

    def load_dict(self, mapping: dict[str, int]) -> None:
        """Restore from a to_dict() snapshot (ids must be dense from 0)."""
        with self._lock:
            items = sorted(mapping.items(), key=lambda kv: kv[1])
            self._id_to_name = [name for name, _ in items]
            self._name_to_id = dict(mapping)

    def __len__(self) -> int:
        return len(self._id_to_name)

    def all_names(self) -> list[str]:
        return list(self._id_to_name)

    # --- durability ---------------------------------------------------------

    def to_list(self) -> list[str]:
        return list(self._id_to_name)

    @classmethod
    def from_list(cls, names: list[str]) -> "NameIdMapper":
        m = cls()
        m._id_to_name = list(names)
        m._name_to_id = {n: i for i, n in enumerate(names)}
        return m
