"""Device-plane fault boundary: typed errors, injection, classification.

Every accelerator dispatch the resilience plane supervises (kernel-server
requests, resumable mesh-analytics chunks, the bench/health device probe)
calls :func:`device_fault_point` first. Unarmed it costs one module-flag
read per point; armed (via ``utils/faultinject``) it turns into the four
canonical device failures:

    device.call   XlaRuntimeError — a dispatch/compile failure. Raised as
                  the REAL jaxlib ``XlaRuntimeError`` when jaxlib is
                  importable, so production handlers exercise exactly the
                  type they would see from a live device.
    device.oom    RESOURCE_EXHAUSTED — the HBM OOM the admission guard
                  exists to prevent; message carries the XLA status code
                  text so string-based classifiers treat it like the
                  real thing.
    device.hang   armed with ``delay:<sec>`` — the dispatch stalls past
                  its deadline (fire() sleeps; no exception). The wedge
                  class the kernel-server supervision loop contains.
    device.lost   the backend is gone. Armed ``raise`` it is an
                  in-process :class:`DeviceLostError` (resumable loops
                  re-place inputs and resume from their checkpoint);
                  armed ``kill`` it takes down the whole process — the
                  resident kernel-server daemon case, which the client
                  supervisor answers by restarting the server.

:func:`classify_device_error` is the shared taxonomy: it maps real AND
injected device exceptions onto {"oom", "device_lost", "device_error"}
so the kernel server, the checkpoint runner, and bench's probe all
report the same typed outcome for the same failure.
"""

from __future__ import annotations

import logging

from . import faultinject as FI

log = logging.getLogger(__name__)


class DeviceFaultError(RuntimeError):
    """Base for injected device-plane failures (in-process stand-ins for
    the XLA runtime errors a real device raises)."""


class DeviceLostError(DeviceFaultError):
    """The backend for this process is gone (chip reset, tunnel died).

    Unlike a per-call failure, resident device buffers and compiled
    executables must be assumed invalid: recovery means re-placing
    inputs and resuming from host-side checkpoint state.
    """


class DeviceOomError(DeviceFaultError):
    """Device memory exhausted (RESOURCE_EXHAUSTED)."""


def _xla_error_type():
    """The real XlaRuntimeError when jaxlib is importable, else None."""
    try:
        from jaxlib.xla_extension import XlaRuntimeError
        return XlaRuntimeError
    except Exception as e:  # noqa: BLE001 — jaxlib layout varies
        log.debug("no importable XlaRuntimeError (%s); falling back to "
                  "DeviceFaultError", e)
        return None


def make_device_call_error(detail: str) -> Exception:
    """An injected dispatch failure, as the real XlaRuntimeError type
    when available so handlers catch exactly the production class."""
    xla_err = _xla_error_type()
    msg = f"INTERNAL: injected device failure: {detail}"
    if xla_err is not None:
        try:
            return xla_err(msg)
        except Exception as e:  # noqa: BLE001 — not constructible here
            log.debug("XlaRuntimeError not constructible (%s); using "
                      "DeviceFaultError", e)
    return DeviceFaultError(msg)


def device_fault_point() -> None:
    """The device dispatch hook. Fires the whole ``device.*`` family in
    canonical order (hang → lost → oom → call) so one call site covers
    every armed device fault; each point keeps its own hit counter, so
    seeded schedules address the N-th dispatch of a specific kind."""
    FI.fire("device.hang")          # delay specs sleep here, then continue
    try:
        FI.fire("device.lost")
    except FI.FaultInjected as e:   # (the "kill" action never returns)
        raise DeviceLostError(
            f"UNAVAILABLE: device backend lost: {e}") from e
    try:
        FI.fire("device.oom")
    except FI.FaultInjected as e:
        raise DeviceOomError(
            "RESOURCE_EXHAUSTED: injected out-of-memory allocating "
            f"device buffer: {e}") from e
    try:
        FI.fire("device.call")
    except FI.FaultInjected as e:
        raise make_device_call_error(str(e)) from e


#: substrings XLA status messages carry for each failure class (the
#: jaxlib error type is one opaque XlaRuntimeError; the status code
#: prefix in the message is the only discriminator the runtime gives us)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "OOM")
_LOST_MARKERS = ("UNAVAILABLE", "device lost", "DATA_LOSS",
                 "backend lost", "failed to connect")


def classify_device_error(exc: BaseException) -> str | None:
    """Map an exception to a typed device outcome, or None when it is
    not a device-plane failure (caller re-raises those unchanged).

    Returns one of ``"oom"``, ``"device_lost"``, ``"device_error"``.
    """
    if isinstance(exc, DeviceOomError):
        return "oom"
    if isinstance(exc, DeviceLostError):
        return "device_lost"
    if isinstance(exc, DeviceFaultError):
        return "device_error"
    xla_err = _xla_error_type()
    is_xla = xla_err is not None and isinstance(exc, xla_err)
    # jax raises XlaRuntimeError for every device-side failure; the
    # status code rides the message text
    if is_xla:
        text = str(exc)
        if any(m in text for m in _OOM_MARKERS):
            return "oom"
        if any(m in text for m in _LOST_MARKERS):
            return "device_lost"
        return "device_error"
    return None
