"""Checkpoint/resume layer for long-running mesh analytics.

The partition-centric kernels in ``parallel/distributed.py`` compile to
ONE device program per chunk of up to ``k`` power iterations (the chunk
carry is the loop state: rank/label vector, convergence partials,
iteration counter). This module drives those chunks from the host:

  * every completed chunk's carry is copied to HOST memory as a
    :class:`Checkpoint` (k iterations of work is the most a device fault
    can destroy),
  * a device fault (``utils/devicefault.classify_device_error``) is
    answered by re-placing the carry from the last checkpoint — after a
    ``device_lost`` additionally rebuilding the device-resident inputs
    via the caller's ``rebuild`` hook — and resuming, NOT restarting,
  * resumption is bit-exact: a chunk is a pure function of its carry, so
    re-running from checkpoint ``c`` replays iterations ``c..c+k``
    identically to an unfaulted run (asserted by
    tests/test_device_resilience.py),
  * ``checkpoint_every=0`` (the default for callers that opt out) runs
    one full-budget chunk — byte-identical device programs and no host
    round-trips, so the non-resumable fast path IS the k=∞ degeneracy of
    the resumable one, not a separate implementation.

Every fault, resume, checkpoint, and slow chunk is counted through
``observability.metrics.global_metrics``.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field

import numpy as np

from ..observability import trace as mgtrace
from ..observability.metrics import global_metrics
from ..utils import devicefault
from ..utils.locks import tracked_lock
from ..utils.retry import RetryPolicy


@dataclass(frozen=True)
class Checkpoint:
    """Host-memory snapshot of one algorithm's loop state."""
    algo: str
    iteration: int
    payload: tuple            # host (numpy / python scalar) carry copy


class CheckpointStore:
    """Host-memory checkpoint store keyed by job id.

    Deliberately process-local: the checkpoint protects against DEVICE
    faults (the HBM state vanishing), not host crashes — durability of
    source data is the WAL's job. A bounded LRU keeps long-lived servers
    from accumulating dead jobs.
    """

    MAX_JOBS = 64

    def __init__(self) -> None:
        self._lock = tracked_lock("CheckpointStore._lock")
        self._ckpts: dict[str, Checkpoint] = {}

    def put(self, job: str, ckpt: Checkpoint) -> None:
        with self._lock:
            self._ckpts.pop(job, None)        # re-insert: LRU refresh
            self._ckpts[job] = ckpt
            while len(self._ckpts) > self.MAX_JOBS:
                self._ckpts.pop(next(iter(self._ckpts)))
        global_metrics.increment("analytics.checkpoint.saved_total")

    def get(self, job: str) -> Checkpoint | None:
        with self._lock:
            return self._ckpts.get(job)

    def drop(self, job: str) -> None:
        with self._lock:
            self._ckpts.pop(job, None)

    def jobs(self) -> list[str]:
        with self._lock:
            return list(self._ckpts)


_default_store = CheckpointStore()


def default_store() -> CheckpointStore:
    """The process-wide store the analytics entry points default to."""
    return _default_store


@dataclass
class RunReport:
    """What the resumable runner observed — filled in place so entry
    points keep their (values, err, iters) return contract."""
    algo: str = ""
    iterations: int = 0          # final iteration count
    chunks: int = 0              # successful chunk dispatches
    checkpoints: int = 0         # host checkpoints written
    resumes: int = 0             # device-fault recoveries
    faults: list = field(default_factory=list)   # typed outcome per fault
    lost_spans: list = field(default_factory=list)  # iters redone/resume
    slow_chunks: int = 0         # chunks exceeding chunk_deadline_s
    rebuilds: int = 0            # device_lost input re-placements

    @property
    def redone_iterations(self) -> int:
        return int(sum(self.lost_spans))


def run_resumable(*, algo: str, chunk, carry, carry_to_host,
                  carry_from_host, iter_of, max_iterations: int,
                  checkpoint_every: int = 0, job: str | None = None,
                  store: CheckpointStore | None = None,
                  retry: RetryPolicy | None = None, rebuild=None,
                  chunk_deadline_s: float | None = None,
                  report: RunReport | None = None):
    """Drive a chunked device loop to completion, surviving device faults.

    ``chunk(carry, it_stop)`` runs the compiled kernel until convergence
    or iteration ``it_stop`` and returns the new carry; ``iter_of``
    reads the (host-synced) iteration counter — the sync point where
    device errors surface. ``carry_to_host``/``carry_from_host`` convert
    the carry to/from host arrays for checkpointing. ``rebuild()`` is
    called after a ``device_lost`` to re-place device-resident inputs
    (and may return a replacement ``chunk`` callable). Returns the final
    carry.
    """
    report = report if report is not None else RunReport()
    report.algo = algo
    store = store or default_store()
    retry = retry or RetryPolicy(base_delay=0.05, max_delay=1.0,
                                 max_retries=3)
    k = checkpoint_every if checkpoint_every and checkpoint_every > 0 \
        else max_iterations
    ephemeral = job is None
    if ephemeral:
        job = f"{algo}:{uuid.uuid4().hex}"

    it = int(iter_of(carry))
    prior = store.get(job)
    if prior is not None and prior.algo == algo \
            and prior.iteration > it:
        carry = carry_from_host(prior.payload)
        it = prior.iteration
        global_metrics.increment("analytics.checkpoint.restored_total")
    # iteration-0 checkpoint: a fault during the FIRST chunk must also
    # resume (from the start) instead of poisoning the run
    store.put(job, Checkpoint(algo, it, carry_to_host(carry)))
    report.checkpoints += 1

    faults_in_a_row = 0
    t_run = time.monotonic()
    try:
        while True:
            it_stop = min(max_iterations, it + k)
            t0 = time.monotonic()
            try:
                # one compiled device chunk = one span; the FIRST chunk
                # folds XLA compilation in (its duration vs later chunks
                # is the compile cost), a faulted chunk records as error
                with mgtrace.span("device.chunk") as sp:
                    devicefault.device_fault_point()
                    new_carry = chunk(carry, it_stop)
                    new_it = int(iter_of(new_carry))   # host sync: device
                    #                                    errors surface here
                    if sp:
                        sp.set(algo=algo, chunk=report.chunks,
                               it_from=it, it_to=new_it)
            except Exception as e:  # noqa: BLE001 — classified below
                kind = devicefault.classify_device_error(e)
                if kind is None:
                    raise
                report.faults.append(kind)
                global_metrics.increment(
                    f"analytics.device_fault.{kind}_total")
                faults_in_a_row += 1
                if faults_in_a_row > retry.max_retries:
                    raise
                time.sleep(retry.delay_for(faults_in_a_row - 1))
                if kind == "device_lost" and rebuild is not None:
                    replacement = rebuild()
                    if replacement is not None:
                        chunk = replacement
                    report.rebuilds += 1
                ckpt = store.get(job)
                carry = carry_from_host(ckpt.payload)
                it = ckpt.iteration
                report.resumes += 1
                # the failed chunk's partial progress is discarded; at
                # most it_stop - checkpoint iterations (≤ k) are redone
                report.lost_spans.append(it_stop - it)
                global_metrics.increment("analytics.resume_total")
                continue
            faults_in_a_row = 0
            elapsed = time.monotonic() - t0
            # mgstat device attribution: the FIRST completed chunk folds
            # XLA compilation (same convention as the device.chunk span),
            # later chunks are pure iteration time
            from ..observability import stats as mgstats
            mgstats.record_stage(
                "device_compile" if report.chunks == 0
                else "device_iterate", elapsed)
            if chunk_deadline_s is not None and elapsed > chunk_deadline_s:
                # the chunk COMPLETED, late — the analytics-plane analog
                # of the kernel server's deadline_exceeded outcome
                report.slow_chunks += 1
                global_metrics.increment(
                    "analytics.chunk_deadline_exceeded_total")
            carry = new_carry
            report.chunks += 1
            if new_it >= max_iterations or new_it < it_stop \
                    or new_it == it:
                # budget spent, or the kernel's own convergence check
                # stopped the loop before the chunk cap
                it = new_it
                break
            it = new_it
            store.put(job, Checkpoint(algo, it, carry_to_host(carry)))
            report.checkpoints += 1
    finally:
        if ephemeral:
            store.drop(job)
        global_metrics.observe("analytics.resumable_run_seconds",
                               time.monotonic() - t_run)
    report.iterations = it
    if not ephemeral:
        store.drop(job)   # completed: the job's checkpoint is obsolete
    return carry
