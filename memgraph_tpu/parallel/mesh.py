"""Device mesh + sharding layer: built once, shared by every sharded kernel.

This is the single place the analytics stack learns about devices. It
provides:

  * `resolve_shard_map()` — the version-gated `shard_map` resolution. On
    jax >= 0.5 the public `jax.shard_map` (with replication checking) is
    used; on the 0.4 line the experimental one is wrapped with
    `check_rep=False` (0.4 has no replication rule for `while_loop`) and
    a WARNING is logged ONCE per process instead of silently taking the
    fallback.
  * `MeshContext` — a mesh plus its canonical `NamedSharding`s
    (replicated / edge-blocked / vertex-blocked), built once per
    (device-count, axis) and cached, so kernels never re-derive
    PartitionSpecs ad hoc. The single-device case is a mesh-of-1
    context, NOT a separate code path: `psum` over a 1-device axis is a
    no-op copy and every sharded kernel degenerates correctly.
  * `analytics_mesh()` — the process-wide default mesh the `ops/`
    algorithms route through, controlled by MEMGRAPH_TPU_MESH_DEVICES
    ("all", or an integer; unset → no mesh routing, the classic
    single-chip kernels run).

SNIPPETS [2]/[3] are the exemplars: canonical PartitionSpecs live in one
frozen layout object; call sites ask for shardings by meaning
("replicated", "edge blocks"), never by axis string.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field

import numpy as np

logger = logging.getLogger(__name__)

_EDGE_AXIS = "shard"


# --------------------------------------------------------------------------
# shard_map resolution (version-gated; warn once on the 0.4 fallback)
# --------------------------------------------------------------------------

_shard_map_cache = None
_fallback_warned = False
_resolve_lock = threading.Lock()


def resolve_shard_map():
    """Return (shard_map_fn, is_fallback).

    jax >= 0.5 exports `jax.shard_map` with a `while_loop` replication
    rule; there the public API is used unchanged. The jax-0.4 line only
    has `jax.experimental.shard_map` and cannot replication-check
    `while_loop` bodies, so it is wrapped with `check_rep=False` — and
    that downgrade is WARNING-logged once per process, because it also
    disables the rewrite that lets XLA fold replicated outputs without
    an all-gather (the silent slow path BENCH_r05 paid).
    """
    global _shard_map_cache, _fallback_warned
    if _shard_map_cache is not None:
        return _shard_map_cache
    with _resolve_lock:
        if _shard_map_cache is not None:
            return _shard_map_cache
        try:
            from jax import shard_map  # jax >= 0.5
            _shard_map_cache = (shard_map, False)
        except ImportError:
            import functools
            from jax.experimental.shard_map import shard_map as _sm
            import jax
            if not _fallback_warned:
                _fallback_warned = True
                logger.warning(
                    "jax %s has no public jax.shard_map; using "
                    "jax.experimental.shard_map with check_rep=False "
                    "(no replication rule for while_loop on the 0.4 "
                    "line). Correctness is unaffected; replicated "
                    "outputs lose the check that they stay "
                    "collective-free.", jax.__version__)
            _shard_map_cache = (functools.partial(_sm, check_rep=False),
                                True)
    return _shard_map_cache


def shard_map_fn():
    """The resolved shard_map callable (most call sites only want this)."""
    return resolve_shard_map()[0]


# --------------------------------------------------------------------------
# MeshContext
# --------------------------------------------------------------------------


def device_count() -> int:
    import jax
    return len(jax.devices())


def streaming_device():
    """The device the out-of-core streamed tier targets: the first
    visible accelerator. The streamed path is deliberately
    single-device — its bottleneck is the host→HBM link, so spreading
    blocks over a mesh would multiply transfer, not hide it; multi-chip
    streaming belongs to a future vertex-sharded tier."""
    import jax
    return jax.devices()[0]


@dataclass(frozen=True)
class MeshContext:
    """A mesh plus its canonical shardings, built once and cached.

    Axis layout: one named axis (default "shard") over which EDGE blocks
    are partitioned; O(n) vertex vectors are either replicated
    (`replicated`) or blocked over the same axis (`vertex_blocks`, the
    1.5D layout). 2D (edges x model) meshes for embedding training keep
    using `make_mesh_2d` below.
    """
    mesh: object                 # jax.sharding.Mesh
    axis: str
    n_shards: int
    replicated: object = field(repr=False)       # NamedSharding, P()
    edge_blocks: object = field(repr=False)      # P(axis, None): (P, per)
    vertex_blocks: object = field(repr=False)    # P(axis): 1D blocked

    def put_edge_blocks(self, arr):
        """Place a (n_shards, per) host array one row per device."""
        import jax
        return jax.device_put(arr, self.edge_blocks)

    def put_replicated(self, arr):
        import jax
        return jax.device_put(arr, self.replicated)

    @property
    def cache_key(self):
        """Stable identity for per-graph plan caches."""
        return (self.axis, self.n_shards,
                tuple(d.id for d in self.mesh.devices.flat))


_ctx_cache: dict = {}
_ctx_lock = threading.Lock()


def get_mesh_context(n_devices: int | None = None,
                     axis: str = _EDGE_AXIS) -> MeshContext:
    """Build (or fetch the cached) MeshContext over the first n devices.

    `n_devices=1` is the mesh-of-1 degeneracy: all sharded kernels run
    unchanged with no cross-device collectives in the compiled program.
    """
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if not 1 <= n_devices <= len(devs):
        raise ValueError(
            f"requested {n_devices} devices; {len(devs)} available")
    key = (n_devices, axis, tuple(d.id for d in devs[:n_devices]))
    with _ctx_lock:
        ctx = _ctx_cache.get(key)
        if ctx is None:
            mesh = Mesh(np.array(devs[:n_devices]), (axis,))
            ctx = MeshContext(
                mesh=mesh, axis=axis, n_shards=n_devices,
                replicated=NamedSharding(mesh, P()),
                edge_blocks=NamedSharding(mesh, P(axis, None)),
                vertex_blocks=NamedSharding(mesh, P(axis)))
            _ctx_cache[key] = ctx
    return ctx


def analytics_mesh() -> MeshContext | None:
    """Process-default mesh for `ops/` analytics, or None (single-chip).

    MEMGRAPH_TPU_MESH_DEVICES = "all" | "<int>" opts the whole analytics
    layer into mesh execution; unset keeps the classic single-chip
    kernels as the default (they are the measured bench path).
    """
    spec = os.environ.get("MEMGRAPH_TPU_MESH_DEVICES", "").strip()
    if not spec:
        return None
    if spec.lower() == "all":
        return get_mesh_context()
    try:
        n = int(spec)
    except ValueError:
        logger.warning("MEMGRAPH_TPU_MESH_DEVICES=%r is not an int or "
                       "'all'; ignoring", spec)
        return None
    return get_mesh_context(min(max(n, 1), device_count()))


def resolve_mesh(mesh=None) -> MeshContext | None:
    """Normalize an algorithm's `mesh=` argument to a MeshContext.

    Accepts None (→ the env-driven `analytics_mesh()` default, usually
    None), an int device count, a `jax.sharding.Mesh` (first axis is the
    edge axis), or a ready MeshContext.
    """
    if mesh is None:
        return analytics_mesh()
    if isinstance(mesh, MeshContext):
        return mesh
    if isinstance(mesh, int):
        return get_mesh_context(mesh)
    # a raw jax Mesh: wrap its first axis
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    if isinstance(mesh, Mesh):
        axis = mesh.axis_names[0]
        if len(mesh.axis_names) != 1:
            raise ValueError(
                "analytics meshes are 1D over the edge axis; got "
                f"axes {mesh.axis_names}")
        return MeshContext(
            mesh=mesh, axis=axis, n_shards=int(mesh.shape[axis]),
            replicated=NamedSharding(mesh, P()),
            edge_blocks=NamedSharding(mesh, P(axis, None)),
            vertex_blocks=NamedSharding(mesh, P(axis)))
    raise TypeError(f"mesh must be None, int, Mesh or MeshContext; "
                    f"got {type(mesh).__name__}")


# --------------------------------------------------------------------------
# legacy constructors (kept: __graft_entry__ / tests / node2vec use them)
# --------------------------------------------------------------------------


def make_mesh(n_devices: int | None = None, axis_name: str = "edges"):
    """1D mesh over the first n_devices devices (edge-partition axis)."""
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def make_mesh_2d(data: int, model: int,
                 axis_names: tuple[str, str] = ("data", "model")):
    """2D mesh (data x model) for embedding-training workloads."""
    import jax
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:data * model]).reshape(data, model)
    return Mesh(devs, axis_names)
