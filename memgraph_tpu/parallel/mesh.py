"""Device mesh construction helpers."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def device_count() -> int:
    return len(jax.devices())


def make_mesh(n_devices: int | None = None,
              axis_name: str = "edges") -> Mesh:
    """1D mesh over the first n_devices devices (edge-partition axis)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def make_mesh_2d(data: int, model: int,
                 axis_names: tuple[str, str] = ("data", "model")) -> Mesh:
    """2D mesh (data x model) for embedding-training workloads."""
    devs = np.array(jax.devices()[:data * model]).reshape(data, model)
    return Mesh(devs, axis_names)
