"""Algorithm-level mesh entry points: DeviceGraph in, results out.

The seam between `ops/` (single-chip algorithms over DeviceGraph
snapshots) and `parallel/distributed.py` (partition-centric kernels over
ShardedCSR). Each `*_mesh` function:

  1. blocks the snapshot's edges partition-centrically for the given
     MeshContext (cached on the immutable DeviceGraph, so repeated CALLs
     pay the blocking + device transfer once),
  2. runs the sharded kernel (one collective per iteration), and
  3. returns exactly the same (values[:n_nodes], ...) shape as the
     single-chip entry point it mirrors.

The mesh-of-1 context runs the SAME code path — `psum`/`psum_scatter`
over a 1-device axis compiles to a copy — so single-device is a
degeneracy of the sharded story, not a separate implementation.
`ops/pagerank.py` (and katz/labelprop/components) route here whenever a
mesh is requested (explicit `mesh=` argument or the
MEMGRAPH_TPU_MESH_DEVICES env default; see `parallel/mesh.py`).
"""

from __future__ import annotations

import numpy as np

from .mesh import MeshContext
from ..ops.csr import DeviceGraph, shard_csr


def pagerank_mesh(graph: DeviceGraph, ctx: MeshContext,
                  damping: float = 0.85, max_iterations: int = 100,
                  tol: float = 1e-6):
    """Sharded PageRank; same contract as ops.pagerank.pagerank."""
    from .distributed import pagerank_partition_centric
    scsr = shard_csr(graph, ctx, by="src")
    return pagerank_partition_centric(scsr, ctx, damping=damping,
                                      max_iterations=max_iterations,
                                      tol=tol)


def katz_mesh(graph: DeviceGraph, ctx: MeshContext, alpha: float = 0.2,
              beta: float = 1.0, max_iterations: int = 100,
              tol: float = 1e-6, normalized: bool = False):
    """Sharded Katz centrality; same contract as ops.katz.katz_centrality."""
    from .distributed import katz_partition_centric
    scsr = shard_csr(graph, ctx, by="src")
    return katz_partition_centric(scsr, ctx, alpha=alpha, beta=beta,
                                  max_iterations=max_iterations, tol=tol,
                                  normalized=normalized)


def label_propagation_mesh(graph: DeviceGraph, ctx: MeshContext,
                           max_iterations: int = 30,
                           self_weight: float = 0.0,
                           directed: bool = False):
    """Sharded label propagation; same contract as
    ops.labelprop.label_propagation."""
    from .distributed import labelprop_partition_centric
    scsr = shard_csr(graph, ctx, by="dst", doubled=not directed)
    labels, iters = labelprop_partition_centric(
        scsr, ctx, max_iterations=max_iterations,
        self_weight=self_weight)
    return labels, iters


def components_mesh(graph: DeviceGraph, ctx: MeshContext,
                    max_iterations: int = 200):
    """Sharded WCC; same contract as
    ops.components.weakly_connected_components."""
    from .distributed import wcc_partition_centric
    scsr = shard_csr(graph, ctx, by="src")
    return wcc_partition_centric(scsr, ctx,
                                 max_iterations=max_iterations)


def sssp_mesh(graph: DeviceGraph, ctx: MeshContext, source: int,
              max_iterations: int = 10_000):
    """Sharded Bellman-Ford over the context's mesh (weighted,
    directed); same result contract as ops.traversal.sssp's weighted
    directed mode. Rides the edge-partition ShardedGraph layout."""
    from .distributed import shard_graph, sssp_sharded
    sg = shard_graph(graph, ctx.mesh, axis=ctx.axis)
    dist, iters = sssp_sharded(sg, source, max_iterations=max_iterations)
    return np.asarray(dist), iters
