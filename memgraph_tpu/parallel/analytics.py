"""Algorithm-level mesh entry points: DeviceGraph in, results out.

The seam between `ops/` (single-chip algorithms over DeviceGraph
snapshots) and `parallel/distributed.py` (partition-centric kernels over
ShardedCSR). Each `*_mesh` function:

  1. blocks the snapshot's edges partition-centrically for the given
     MeshContext (cached on the immutable DeviceGraph, so repeated CALLs
     pay the blocking + device transfer once),
  2. runs the sharded kernel (one collective per iteration), and
  3. returns exactly the same (values[:n_nodes], ...) shape as the
     single-chip entry point it mirrors.

The mesh-of-1 context runs the SAME code path — `psum`/`psum_scatter`
over a 1-device axis compiles to a copy — so single-device is a
degeneracy of the sharded story, not a separate implementation.
`ops/pagerank.py` (and katz/labelprop/components) route here whenever a
mesh is requested (explicit `mesh=` argument or the
MEMGRAPH_TPU_MESH_DEVICES env default; see `parallel/mesh.py`).

Resilience (r12): every iterative entry point accepts
``checkpoint_every=k`` (plus ``job``/``store``/``report``) and routes
through `parallel/checkpoint.run_resumable` — the loop carry is copied
to host memory every k iterations and a device fault resumes from the
last checkpoint, bit-exact, instead of restarting. The
MEMGRAPH_TPU_CHECKPOINT_EVERY env var sets the default k for callers
that do not pass one (0 = single full-budget chunk, no host round
trips); the kernel server and bench.py pass it explicitly.
"""

from __future__ import annotations

import os

import numpy as np

from .mesh import MeshContext
from ..observability import trace as mgtrace
from ..ops.csr import DeviceGraph, shard_csr


def _shard_traced(graph: DeviceGraph, ctx: MeshContext, by: str = "src",
                  doubled: bool = False):
    """shard_csr under a ``device.transfer`` span: the partition-centric
    blocking + device placement stage of the trace (cache hits show as
    ~zero-duration spans, which is itself useful signal). The same
    extent attributes to the active mgstat stage accumulator, so a
    PROFILE-d query sees transfer seconds even with tracing disarmed."""
    import time as _time
    from ..observability import stats as mgstats
    t0 = _time.perf_counter()
    with mgtrace.span("device.transfer") as sp:
        scsr = shard_csr(graph, ctx, by=by, doubled=doubled)
        if sp:
            sp.set(n_shards=ctx.n_shards, by=by,
                   n_nodes=int(graph.n_nodes))
    mgstats.record_stage("device_transfer", _time.perf_counter() - t0)
    return scsr


def default_checkpoint_every() -> int:
    """Process-default checkpoint interval for mesh analytics (env
    MEMGRAPH_TPU_CHECKPOINT_EVERY; 0 disables intermediate
    checkpoints — one full-budget chunk, the classic fast path)."""
    try:
        return max(0, int(os.environ.get(
            "MEMGRAPH_TPU_CHECKPOINT_EVERY", "0")))
    except ValueError:
        return 0


def _resume_kw(checkpoint_every, job, store, report, retry):
    if checkpoint_every is None:
        checkpoint_every = default_checkpoint_every()
    return {"checkpoint_every": checkpoint_every, "job": job,
            "store": store, "report": report, "retry": retry}


def pagerank_mesh(graph: DeviceGraph, ctx: MeshContext,
                  damping: float = 0.85, max_iterations: int = 100,
                  tol: float = 1e-6, *, precision: str = "f32",
                  x0=None,
                  checkpoint_every: int | None = None,
                  job: str | None = None, store=None, report=None,
                  retry=None):
    """Sharded PageRank; same contract as ops.pagerank.pagerank.
    ``x0`` warm-starts from a previous solution (ops/delta.py)."""
    from .distributed import pagerank_partition_centric
    scsr = _shard_traced(graph, ctx, by="src")
    return pagerank_partition_centric(
        scsr, ctx, damping=damping, max_iterations=max_iterations,
        tol=tol, precision=precision, x0=x0,
        **_resume_kw(checkpoint_every, job, store, report, retry))


def katz_mesh(graph: DeviceGraph, ctx: MeshContext, alpha: float = 0.2,
              beta: float = 1.0, max_iterations: int = 100,
              tol: float = 1e-6, normalized: bool = False, *,
              precision: str = "f32", x0=None,
              checkpoint_every: int | None = None, job: str | None = None,
              store=None, report=None, retry=None):
    """Sharded Katz centrality; same contract as ops.katz.katz_centrality.
    ``x0`` warm-starts from a previous solution (ops/delta.py)."""
    from .distributed import katz_partition_centric
    scsr = _shard_traced(graph, ctx, by="src")
    return katz_partition_centric(
        scsr, ctx, alpha=alpha, beta=beta,
        max_iterations=max_iterations, tol=tol, normalized=normalized,
        precision=precision, x0=x0,
        **_resume_kw(checkpoint_every, job, store, report, retry))


def label_propagation_mesh(graph: DeviceGraph, ctx: MeshContext,
                           max_iterations: int = 30,
                           self_weight: float = 0.0,
                           directed: bool = False, *,
                           labels0=None,
                           checkpoint_every: int | None = None,
                           job: str | None = None, store=None,
                           report=None, retry=None):
    """Sharded label propagation; same contract as
    ops.labelprop.label_propagation. ``labels0`` warm-starts the
    election (adds-only deltas only — ops/delta.py monotone gate)."""
    from .distributed import labelprop_partition_centric
    scsr = _shard_traced(graph, ctx, by="dst", doubled=not directed)
    labels, iters = labelprop_partition_centric(
        scsr, ctx, max_iterations=max_iterations,
        self_weight=self_weight, labels0=labels0,
        **_resume_kw(checkpoint_every, job, store, report, retry))
    return labels, iters


def components_mesh(graph: DeviceGraph, ctx: MeshContext,
                    max_iterations: int = 200, *,
                    comp0=None,
                    checkpoint_every: int | None = None,
                    job: str | None = None, store=None, report=None,
                    retry=None):
    """Sharded WCC; same contract as
    ops.components.weakly_connected_components. ``comp0`` warm-starts
    the min-label propagation (adds-only deltas only — ops/delta.py
    monotone gate)."""
    from .distributed import wcc_partition_centric
    scsr = _shard_traced(graph, ctx, by="src")
    return wcc_partition_centric(
        scsr, ctx, max_iterations=max_iterations, comp0=comp0,
        **_resume_kw(checkpoint_every, job, store, report, retry))


def sssp_mesh(graph: DeviceGraph, ctx: MeshContext, source: int,
              max_iterations: int = 10_000):
    """Sharded Bellman-Ford over the context's mesh (weighted,
    directed); same result contract as ops.traversal.sssp's weighted
    directed mode. Rides the edge-partition ShardedGraph layout."""
    from .distributed import shard_graph, sssp_sharded
    sg = shard_graph(graph, ctx.mesh, axis=ctx.axis)
    dist, iters = sssp_sharded(sg, source, max_iterations=max_iterations)
    return np.asarray(dist), iters


def bfs_mesh(graph: DeviceGraph, ctx: MeshContext, source: int,
             max_iterations: int = 10_000, *, precision: str = "f32",
             checkpoint_every: int | None = None, job: str | None = None,
             store=None, report=None, retry=None):
    """BFS levels over the mesh via the GENERIC semiring kernel — the
    ~40-line new-algorithm story: a (min_plus, x0, relax-epilogue)
    triple riding semiring_partition_centric (one pmin per level,
    checkpoint-resumable).  Returns (levels[:n_nodes] int32 with -1 for
    unreachable, iterations); same result contract as
    ops.traversal.bfs_levels (directed)."""
    import jax.numpy as jnp
    from .distributed import (_minplus_relax_epilogue,
                              semiring_partition_centric)
    scsr = _shard_traced(graph, ctx, by="src")
    inf = np.float32(3.4e38)
    # unit hop weights; padding edges (dst = sink row n_nodes) stay inert
    unit_w = jnp.where(scsr.dst == scsr.n_nodes, inf,
                       jnp.float32(1.0)).astype(jnp.float32)
    hop_scsr = scsr.__class__(
        src=scsr.src, dst=scsr.dst, weights=unit_w,
        block_ptr=scsr.block_ptr, n_nodes=scsr.n_nodes,
        n_edges=scsr.n_edges, n_shards=scsr.n_shards, block=scsr.block,
        n_pad2=scsr.n_pad2, per=scsr.per, by=scsr.by)
    x0 = np.full(scsr.n_pad2, inf, dtype=np.float32)
    x0[source] = 0.0
    dist, _, iters = semiring_partition_centric(
        hop_scsr, ctx, "min_plus", x0, _minplus_relax_epilogue,
        max_iterations=max_iterations, metric="changed",
        precision=precision, algo="bfs",
        **_resume_kw(checkpoint_every, job, store, report, retry))
    dist = np.asarray(dist)
    levels = np.where(dist >= inf / 2, -1, dist.astype(np.int64))
    return levels.astype(np.int32), iters
