"""Multi-chip execution: device meshes, edge partitioning, sharded kernels.

The reference scales out by replication only (SURVEY.md §2.4.8 — no graph
sharding). The TPU build goes further: whole-graph analytics shard across a
`jax.sharding.Mesh`, with 1D *edge partitioning* (each device owns a
contiguous edge block; the vertex state vector is replicated) and XLA
collectives (`psum`) combining per-shard segment reductions over ICI. This
is the graph analog of data parallelism: the "sequence" axis is the edge
axis (SURVEY.md §5 long-context mapping).
"""

from .mesh import make_mesh, device_count
from .distributed import (shard_graph, ShardedGraph, pagerank_sharded,
                          sssp_sharded, wcc_sharded)

__all__ = ["make_mesh", "device_count", "shard_graph", "ShardedGraph",
           "pagerank_sharded", "sssp_sharded", "wcc_sharded"]
