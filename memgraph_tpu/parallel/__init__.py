"""Multi-chip execution: device meshes, edge partitioning, sharded kernels.

The reference scales out by replication only (SURVEY.md §2.4.8 — no graph
sharding). The TPU build goes further: whole-graph analytics shard across a
`jax.sharding.Mesh`, with 1D *edge partitioning* (each device owns a
contiguous edge block; the vertex state vector is replicated) and XLA
collectives (`psum`) combining per-shard segment reductions over ICI. This
is the graph analog of data parallelism: the "sequence" axis is the edge
axis (SURVEY.md §5 long-context mapping).
"""

from .mesh import (make_mesh, device_count, MeshContext, get_mesh_context,
                   analytics_mesh, resolve_mesh, resolve_shard_map)
from .distributed import (shard_graph, ShardedGraph, pagerank_sharded,
                          sssp_sharded, wcc_sharded,
                          pagerank_partition_centric,
                          katz_partition_centric,
                          labelprop_partition_centric,
                          wcc_partition_centric)
from .analytics import (pagerank_mesh, katz_mesh, label_propagation_mesh,
                        components_mesh, sssp_mesh)

__all__ = ["make_mesh", "device_count", "MeshContext", "get_mesh_context",
           "analytics_mesh", "resolve_mesh", "resolve_shard_map",
           "shard_graph", "ShardedGraph",
           "pagerank_sharded", "sssp_sharded", "wcc_sharded",
           "pagerank_partition_centric", "katz_partition_centric",
           "labelprop_partition_centric", "wcc_partition_centric",
           "pagerank_mesh", "katz_mesh", "label_propagation_mesh",
           "components_mesh", "sssp_mesh"]
