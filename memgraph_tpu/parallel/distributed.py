"""Sharded whole-graph kernels: edge-partitioned, psum-combined.

Scheme (the scaling-book recipe applied to graphs): pad the edge list to a
multiple of the mesh size, give each device a contiguous edge block
(src/dst/weight shards), replicate the O(n) vertex vectors. Each round every
device computes its local segment reduction into a full-size vertex vector,
then one `psum`/`pmin` over the mesh axis combines them — the collective
rides ICI. Vertex vectors are replicated (fine to ~100M nodes in f32);
2D vertex-sharding is the next scaling step.

Reference contrast: the reference's distributed story is replication +
point-to-point RPC (/root/reference/src/rpc, SURVEY.md §2.4); there is no
data-plane collective to mirror — this layer is designed TPU-first.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import MeshContext, shard_map_fn, streaming_device
from ..observability import stats as mgstats
from ..observability.metrics import global_metrics
from ..ops import tier as mgtier
from ..ops.csr import DeviceGraph, ShardedCSR
from ..ops.semiring import (backend_extent, edge_combine, edge_reduce,
                            pagerank_update, resolve_semiring)

# version-gated central resolution (parallel/mesh.py): jax >= 0.5 uses the
# public jax.shard_map; the 0.4 line gets the experimental one with
# check_rep=False and a WARNING logged once — never a silent fallback
shard_map = shard_map_fn()


def _cast_contrib(contrib, precision: str):
    """Reduced-precision streaming on the mesh backend: round each
    per-edge contribution to bf16 before the f32 segment accumulation
    (same contract as the segment backend's bf16 path; int8 streaming
    is a segment-backend feature — the collective lanes stay f32)."""
    if precision == "bf16":
        return contrib.astype(jnp.bfloat16).astype(jnp.float32)
    if precision != "f32":
        raise ValueError(
            f"mesh kernels route f32/bf16 only, got {precision!r}")
    return contrib


@dataclass(frozen=True)
class ShardedGraph:
    """Edge-sharded COO graph on a mesh. Vertex state is replicated."""
    src: object      # (e_pad,) sharded over mesh axis
    dst: object      # (e_pad,)
    weights: object  # (e_pad,)
    n_nodes: int
    n_edges: int     # true edge count; positions >= n_edges are padding
    n_pad: int
    e_pad: int
    mesh: Mesh
    axis: str


def shard_graph(graph: DeviceGraph, mesh: Mesh,
                axis: str | None = None) -> ShardedGraph:
    """Place edge arrays sharded over the mesh; pads edges to a multiple of
    the mesh size (padding edges are inert: weight 0 into the sink row)."""
    axis = axis or mesh.axis_names[0]
    n_shards = mesh.shape[axis]
    e_pad = graph.e_pad
    if e_pad % n_shards:
        new_e = ((e_pad + n_shards - 1) // n_shards) * n_shards
    else:
        new_e = e_pad
    sink = graph.n_nodes

    def pad_to(arr, fill):
        arr = np.asarray(arr)
        if len(arr) < new_e:
            arr = np.concatenate(
                [arr, np.full(new_e - len(arr), fill, dtype=arr.dtype)])
        return arr

    # CSC ((dst, src)-sorted) order: per-shard contiguous blocks stay
    # dst-sorted, so local segment reductions take the fast sorted lowering
    src = pad_to(graph.csc_src, sink)
    dst = pad_to(graph.csc_dst, sink)
    w = pad_to(graph.csc_weights, 0.0)

    sharding = NamedSharding(mesh, P(axis))
    return ShardedGraph(
        src=jax.device_put(src, sharding),
        dst=jax.device_put(dst, sharding),
        weights=jax.device_put(w, sharding),
        n_nodes=graph.n_nodes, n_edges=graph.n_edges,
        n_pad=graph.n_pad, e_pad=new_e,
        mesh=mesh, axis=axis)


def _pagerank_sharded_fn(mesh: Mesh, axis: str, n_pad: int,
                         max_iterations: int):
    """Build the shard_mapped pagerank step for a given mesh/shapes."""

    def step(src_blk, dst_blk, w_blk, n_nodes, damping, tol):
        n_f = n_nodes.astype(jnp.float32)
        valid_f = (jnp.arange(n_pad, dtype=jnp.int32) < n_nodes
                   ).astype(jnp.float32)
        # per-source outgoing weight: local partial + psum = global
        wsum_local = jax.ops.segment_sum(w_blk, src_blk, num_segments=n_pad)
        wsum = jax.lax.psum(wsum_local, axis)
        inv_wsum = jnp.where(wsum > 0, 1.0 / jnp.maximum(wsum, 1e-30), 0.0)
        dangling_f = valid_f * (wsum <= 0)

        rank0 = valid_f / n_f

        edge_mult = w_blk * inv_wsum[src_blk]  # hoisted per-edge multiplier

        def body(carry):
            rank, _, it = carry
            contrib = rank[src_blk] * edge_mult
            acc_local = jax.ops.segment_sum(contrib, dst_blk,
                                            num_segments=n_pad,
                                            indices_are_sorted=True)
            acc = jax.lax.psum(acc_local, axis)          # ← ICI collective
            dangling_mass = jnp.sum(rank * dangling_f)
            new_rank = valid_f * ((1.0 - damping) / n_f
                                  + damping * (acc + dangling_mass / n_f))
            err = jnp.sum(jnp.abs(new_rank - rank))
            return new_rank, err, it + 1

        def cond(carry):
            _, err, it = carry
            return (err > tol) & (it < max_iterations)

        rank, err, iters = jax.lax.while_loop(
            cond, body, (rank0, jnp.float32(jnp.inf), jnp.int32(0)))
        return rank, err, iters

    return shard_map(
        step, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(), P(), P()),
        out_specs=(P(), P(), P()))


#: compiled legacy sharded kernels keyed by (kind, devices, shapes) —
#: re-jitting the builder closure per call silently retraced + recompiled
#: on EVERY invocation (mglint MG008 recompile-hazard; the partition-
#: centric kernels already cache through _pc_cached)
_SHARDED_JIT_CACHE: dict = {}


def _sharded_jit(kind: str, builder_fn, mesh: Mesh, axis: str,
                 *shape_key, donate: tuple = ()):
    key = (kind, tuple(d.id for d in mesh.devices.flat), axis,
           shape_key, donate)
    fn = _SHARDED_JIT_CACHE.get(key)
    if fn is None:
        fn = _SHARDED_JIT_CACHE[key] = jax.jit(
            builder_fn(mesh, axis, *shape_key), donate_argnums=donate)
    return fn


def pagerank_sharded(sg: ShardedGraph, damping: float = 0.85,
                     max_iterations: int = 100, tol: float = 1e-6):
    """Distributed PageRank over the mesh. Returns (ranks[:n], err, iters)."""
    fn = _sharded_jit("pagerank", _pagerank_sharded_fn, sg.mesh, sg.axis,
                      sg.n_pad, max_iterations)
    rank, err, iters = fn(sg.src, sg.dst, sg.weights,
                          jnp.int32(sg.n_nodes), jnp.float32(damping),
                          jnp.float32(tol))
    return rank[:sg.n_nodes], float(err), int(iters)


def shard_graph_by_src(graph: DeviceGraph, mesh: Mesh,
                       axis: str | None = None) -> ShardedGraph:
    """Partition edges by SOURCE shard (edge e goes to the device owning
    src block floor(src / (n_pad / n_shards))) — the layout the 1.5D
    pagerank needs: every gather rank[src] is then device-local.

    Within each device block edges stay (dst-sorted) for the sorted
    segment reduction.
    """
    import numpy as np
    axis = axis or mesh.axis_names[0]
    n_shards = mesh.shape[axis]
    if graph.n_pad % n_shards:
        raise ValueError("n_pad must divide the mesh size")
    block = graph.n_pad // n_shards
    src = np.asarray(graph.csc_src)[:graph.n_edges]
    dst = np.asarray(graph.csc_dst)[:graph.n_edges]
    w = np.asarray(graph.csc_weights)[:graph.n_edges]
    owner = src // block
    # bucket edges per owner, keep dst order within the bucket (stable)
    order = np.argsort(owner, kind="stable")
    src, dst, w, owner = src[order], dst[order], w[order], owner[order]
    counts = np.bincount(owner, minlength=n_shards)
    per = int(counts.max()) if len(counts) else 1
    per = max(per, 1)
    sink = graph.n_nodes
    e_pad = per * n_shards
    src_full = np.full(e_pad, sink, dtype=np.int32)
    dst_full = np.full(e_pad, sink, dtype=np.int32)
    w_full = np.zeros(e_pad, dtype=np.float32)
    offsets = np.concatenate([[0], np.cumsum(counts)])
    for s in range(n_shards):
        lo, hi = offsets[s], offsets[s + 1]
        src_full[s * per:s * per + (hi - lo)] = src[lo:hi]
        dst_full[s * per:s * per + (hi - lo)] = dst[lo:hi]
        w_full[s * per:s * per + (hi - lo)] = w[lo:hi]
    sharding = NamedSharding(mesh, P(axis))
    return ShardedGraph(
        src=jax.device_put(src_full, sharding),
        dst=jax.device_put(dst_full, sharding),
        weights=jax.device_put(w_full, sharding),
        n_nodes=graph.n_nodes, n_edges=graph.n_edges,
        n_pad=graph.n_pad, e_pad=e_pad, mesh=mesh, axis=axis)


def _pagerank_15d_fn(mesh: Mesh, axis: str, n_pad: int, n_shards: int,
                     max_iterations: int):
    """1.5D pagerank: rank is SHARDED over the mesh (each device holds
    n_pad/n_shards entries); edges are src-sharded so the per-edge rank
    gather is device-local, and partial destination sums combine with ONE
    reduce_scatter per iteration — O(n/p) memory and lower ICI volume than
    the replicated psum scheme (the scaling-book recipe)."""
    block = n_pad // n_shards

    def step(src_blk, dst_blk, w_blk, n_nodes, damping, tol):
        shard_id = jax.lax.axis_index(axis)
        base = shard_id * block
        n_f = n_nodes.astype(jnp.float32)
        local_ids = base + jnp.arange(block, dtype=jnp.int32)
        valid_f = (local_ids < n_nodes).astype(jnp.float32)

        local_src = jnp.clip(src_blk - base, 0, block - 1)
        src_mine = (src_blk >= base) & (src_blk < base + block)
        w_eff = jnp.where(src_mine, w_blk, 0.0)

        # local out-weight per owned node (edges are src-sharded: complete)
        wsum = jax.ops.segment_sum(w_eff, local_src, num_segments=block)
        inv_wsum = jnp.where(wsum > 0, 1.0 / jnp.maximum(wsum, 1e-30), 0.0)
        dangling_f = valid_f * (wsum <= 0)

        rank0 = valid_f / n_f  # local shard of the rank vector

        def body(carry):
            rank, _, it = carry
            contrib = rank[local_src] * w_eff * inv_wsum[local_src]
            # partial sums over ALL destinations, then scatter to owners
            acc_full = jax.ops.segment_sum(contrib, dst_blk,
                                           num_segments=n_pad,
                                           indices_are_sorted=True)
            acc = jax.lax.psum_scatter(
                acc_full.reshape(n_shards, block), axis,
                scatter_dimension=0, tiled=False)
            dangling_mass = jax.lax.psum(jnp.sum(rank * dangling_f), axis)
            new_rank = valid_f * ((1.0 - damping) / n_f
                                  + damping * (acc + dangling_mass / n_f))
            err = jax.lax.psum(jnp.sum(jnp.abs(new_rank - rank)), axis)
            return new_rank, err, it + 1

        def cond(carry):
            _, err, it = carry
            return (err > tol) & (it < max_iterations)

        rank, err, iters = jax.lax.while_loop(
            cond, body, (rank0, jnp.float32(jnp.inf), jnp.int32(0)))
        return rank, err, iters

    return shard_map(
        step, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(), P(), P()),
        out_specs=(P(axis), P(), P()))


def pagerank_sharded_15d(sg: ShardedGraph, damping: float = 0.85,
                         max_iterations: int = 100, tol: float = 1e-6):
    """Memory-scalable distributed PageRank (use shard_graph_by_src)."""
    n_shards = sg.mesh.shape[sg.axis]
    fn = _sharded_jit("pagerank_15d", _pagerank_15d_fn, sg.mesh, sg.axis,
                      sg.n_pad, n_shards, max_iterations)
    rank, err, iters = fn(sg.src, sg.dst, sg.weights,
                          jnp.int32(sg.n_nodes), jnp.float32(damping),
                          jnp.float32(tol))
    return rank[:sg.n_nodes], float(err), int(iters)


def _min_propagate_sharded_fn(mesh: Mesh, axis: str, n_pad: int,
                              max_iterations: int, undirected: bool,
                              pointer_jump: bool):
    def step(src_blk, dst_blk, w_blk, init):
        def body(carry):
            val, _, it = carry
            # dst_blk is per-block sorted (CSC shards) → sorted lowering;
            # the backward reduction keys on src which is unsorted under CSC
            cand_local = jax.ops.segment_min(val[src_blk] + w_blk, dst_blk,
                                             num_segments=n_pad,
                                             indices_are_sorted=True)
            if undirected:
                back = jax.ops.segment_min(val[dst_blk] + w_blk, src_blk,
                                           num_segments=n_pad)
                cand_local = jnp.minimum(cand_local, back)
            cand = jax.lax.pmin(cand_local, axis)
            new = jnp.minimum(val, cand)
            if pointer_jump:
                new = new[new.astype(jnp.int32)].astype(new.dtype)
            return new, jnp.any(new < val), it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < max_iterations)

        val, _, iters = jax.lax.while_loop(
            cond, body, (init, jnp.bool_(True), jnp.int32(0)))
        return val, iters

    return shard_map(
        step, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P()),
        out_specs=(P(), P()))


_INF = jnp.float32(3.4e38)


def sssp_sharded(sg: ShardedGraph, source: int,
                 max_iterations: int = 10_000):
    """Distributed Bellman-Ford (weighted, directed)."""
    init = jnp.full((sg.n_pad,), _INF, dtype=jnp.float32).at[source].set(0.0)
    # inert padding: padding edges must not relax through the sink
    real = jnp.arange(sg.e_pad) < sg.n_edges
    w = jnp.where(real, sg.weights, _INF)
    w = jax.device_put(w, NamedSharding(sg.mesh, P(sg.axis)))
    # init is freshly built per call: donate it back to the iterate
    fn = _sharded_jit("min_propagate", _min_propagate_sharded_fn,
                      sg.mesh, sg.axis, sg.n_pad, max_iterations,
                      False, False, donate=(3,))
    dist, iters = fn(sg.src, sg.dst, w, init)
    out = dist[:sg.n_nodes]
    return jnp.where(out >= _INF / 2, jnp.inf, out), int(iters)


def _wcc_sharded_fn(mesh: Mesh, axis: str, n_pad: int, max_iterations: int):
    """Integer min-label propagation + pointer jumping (separate from the
    float path: float32 cannot represent node indices >= 2^24)."""

    def step(src_blk, dst_blk, init):
        def body(carry):
            comp, _, it = carry
            fwd = jax.ops.segment_min(comp[src_blk], dst_blk,
                                      num_segments=n_pad,
                                      indices_are_sorted=True)
            bwd = jax.ops.segment_min(comp[dst_blk], src_blk,
                                      num_segments=n_pad)
            cand = jax.lax.pmin(jnp.minimum(fwd, bwd), axis)
            new = jnp.minimum(comp, cand)
            new = new[new]  # pointer jump
            return new, jnp.any(new < comp), it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < max_iterations)

        comp, _, iters = jax.lax.while_loop(
            cond, body, (init, jnp.bool_(True), jnp.int32(0)))
        return comp, iters

    return shard_map(
        step, mesh=mesh,
        in_specs=(P(axis), P(axis), P()),
        out_specs=(P(), P()))


def wcc_sharded(sg: ShardedGraph, max_iterations: int = 200):
    """Distributed weakly-connected components (min-label + pointer jump)."""
    init = jnp.arange(sg.n_pad, dtype=jnp.int32)
    fn = _sharded_jit("wcc", _wcc_sharded_fn, sg.mesh, sg.axis,
                      sg.n_pad, max_iterations, donate=(2,))
    comp, iters = fn(sg.src, sg.dst, init)
    return comp[:sg.n_nodes], int(iters)


# ==========================================================================
# Partition-centric kernels over ShardedCSR (the pjit/NamedSharding story)
# ==========================================================================
#
# Inputs are placed ONCE under the MeshContext's NamedShardings
# (ShardedCSR.to_device); the kernels below are shard_mapped over the
# context's edge axis and keep the ONE-collective-per-iteration invariant:
#
#   pagerank  — rank SHARDED over vertex blocks; per-iteration partials
#               land in the (dst-shard, local) partition-centric layout
#               and ONE fused psum_scatter both scatters them to their
#               owners AND rides the dangling-mass / convergence-error
#               partial sums in two extra lanes (so neither needs its
#               own psum — the 3-collective 1.5D scheme collapses to 1).
#   katz      — x replicated, partial A^T x psum-combined: one psum.
#   labelprop — edges owned by DST shard, labels replicated; each round
#               a device elects labels for its own block only and one
#               psum concatenates the disjoint blocks.
#   wcc       — comp replicated, one pmin per round + pointer jumping.
#
# Convergence checks that need a global reduction are carried one
# iteration behind (the error partial rides the NEXT iteration's
# collective), so tol-based runs execute at most one extra iteration —
# never an extra collective.
#
# Resumability (r12): every kernel is a CHUNK — it takes the loop carry
# (state vector(s), convergence partials, iteration counter) plus an
# `it_stop` bound and runs `while cond & (it < it_stop)`. The entry
# points drive chunks through parallel/checkpoint.run_resumable, which
# copies the carry to host every k iterations and resumes from the last
# checkpoint after a device fault. `checkpoint_every=0` runs ONE chunk
# covering the whole budget: identical device program, no host round
# trips — the fast path is the k=∞ degeneracy, not a separate kernel.

_PC_EXTRA = 2          # piggyback lanes: [dangling_mass, prev_local_err]


def _pc_pagerank_build(ctx: MeshContext, block: int, n_shards: int,
                       precision: str = "f32"):
    axis = ctx.axis
    n_pad2 = n_shards * block

    def step(src_blk, dst_blk, w_blk, n_nodes, damping, tol,
             rank, local_err_v, g_err_prev, it, it_stop):
        src_blk, dst_blk, w_blk = src_blk[0], dst_blk[0], w_blk[0]
        # local_err is a genuinely per-shard partial (it rides the next
        # iteration's collective), so it crosses chunk boundaries as a
        # P(axis)-sharded (n_shards,) vector: one lane per device
        local_err = local_err_v[0]
        shard_id = jax.lax.axis_index(axis)
        base = shard_id * block
        n_f = n_nodes.astype(jnp.float32)
        local_ids = base + jnp.arange(block, dtype=jnp.int32)
        valid_f = (local_ids < n_nodes).astype(jnp.float32)

        # edges are src-owned: every out-edge of an owned vertex is
        # local, so the out-weight sum needs no collective
        local_src = src_blk - base
        wsum = jax.ops.segment_sum(w_blk, local_src, num_segments=block)
        inv_wsum = jnp.where(wsum > 0, 1.0 / jnp.maximum(wsum, 1e-30), 0.0)
        dangling_f = valid_f * (wsum <= 0)
        edge_mult = w_blk * inv_wsum[local_src]

        def body(carry):
            rank, local_err, _, it = carry
            contrib = _cast_contrib(rank[local_src] * edge_mult,
                                    precision)
            # the (dst, src) sort within the shard means this sorted
            # segment-sum fills the (dst-shard, local-dst) blocks of the
            # partition-centric layout contiguously
            acc = edge_reduce("sum", contrib, dst_blk, n_pad2,
                              sorted=True).reshape(n_shards, block)
            dm_local = jnp.sum(rank * dangling_f)
            extras = jnp.broadcast_to(
                jnp.stack([dm_local, local_err]), (n_shards, _PC_EXTRA))
            payload = jnp.concatenate([acc, extras], axis=1)
            # THE collective: row q of the payload sum lands on device q
            got = jax.lax.psum_scatter(payload, axis,
                                       scatter_dimension=0, tiled=False)
            acc_own = got[:block]
            dm = got[block]
            g_err_prev = got[block + 1]
            new_rank = pagerank_update(acc_own, dm, valid_f, n_f, damping)
            new_local_err = jnp.sum(jnp.abs(new_rank - rank))
            return new_rank, new_local_err, g_err_prev, it + 1

        def cond(carry):
            _, _, g_err_prev, it = carry
            return (g_err_prev > tol) & (it < it_stop)

        rank, local_err, g_err, iters = jax.lax.while_loop(
            cond, body, (rank, local_err, g_err_prev, it))
        return rank, local_err.reshape(1), g_err, iters

    Pr = P()
    Pe = P(axis, None)
    Pv = P(axis)
    # the chunk carry (rank, local-err lanes, trailing error, iteration
    # counter) is donated: each chunk consumes the previous chunk's
    # output, so donation halves the iterate's HBM residency and the
    # checkpoint layer's host copies are taken from OUTPUTS, never from
    # donated inputs (parallel/checkpoint.run_resumable)
    return jax.jit(shard_map(
        step, mesh=ctx.mesh,
        in_specs=(Pe, Pe, Pe, Pr, Pr, Pr, Pv, Pv, Pr, Pr, Pr),
        out_specs=(Pv, Pv, Pr, Pr)), donate_argnums=(6, 7, 8, 9))


_PC_KERNEL_CACHE: dict = {}


def _pc_cached(kind: str, builder, ctx: MeshContext, *shape_key):
    key = (kind, ctx.cache_key, shape_key)
    fn = _PC_KERNEL_CACHE.get(key)
    if fn is None:
        fn = _PC_KERNEL_CACHE[key] = builder(ctx, *shape_key)
    return fn


def _run_pc_resumable(*, algo, scsr, ctx, chunk_of, carry0, iter_index,
                      max_iterations, checkpoint_every=0, job=None,
                      store=None, retry=None, chunk_deadline_s=None,
                      report=None):
    """Shared driver: wire a partition-centric chunk kernel into the
    checkpoint layer. `chunk_of(scsr)` binds the (possibly re-placed)
    ShardedCSR into a `chunk(carry, it_stop)` callable; after a
    device_lost the rebuild hook re-places the edge rows and re-binds."""
    from .checkpoint import run_resumable
    holder = {"scsr": scsr}

    def rebuild():
        holder["scsr"] = holder["scsr"].refresh(ctx)
        return chunk_of(holder["scsr"])

    carry = run_resumable(
        algo=algo, chunk=chunk_of(scsr), carry=carry0,
        carry_to_host=lambda c: tuple(np.asarray(x) for x in c),
        carry_from_host=lambda p: p,
        iter_of=lambda c: int(c[iter_index]),
        max_iterations=max_iterations,
        checkpoint_every=checkpoint_every, job=job, store=store,
        retry=retry, rebuild=rebuild, chunk_deadline_s=chunk_deadline_s,
        report=report)
    return carry


def _warm_vertex_vector(x0, scsr: ShardedCSR, dtype, pad_value=None):
    """Pad a warm-start (n_nodes,) solution to the mesh's n_pad2 vertex
    space. ``pad_value=None`` fills padding rows with their own index
    (the label-algorithm convention); a scalar fills directly. The
    returned buffer is FRESH — safe to donate into the chunk carry."""
    if pad_value is None:
        v = np.arange(scsr.n_pad2, dtype=dtype)
    else:
        v = np.full(scsr.n_pad2, pad_value, dtype=dtype)
    x0 = np.asarray(x0)
    n = min(len(x0), scsr.n_nodes)
    v[:n] = x0[:n].astype(dtype, copy=False)
    return v


def pagerank_partition_centric(scsr: ShardedCSR, ctx: MeshContext,
                               damping: float = 0.85,
                               max_iterations: int = 100,
                               tol: float = 1e-6, *,
                               precision: str = "f32",
                               x0=None,
                               checkpoint_every: int = 0,
                               job: str | None = None, store=None,
                               retry=None, chunk_deadline_s=None,
                               report=None):
    """PageRank over a partition-centric ShardedCSR: rank sharded over
    vertex blocks, exactly one collective (a fused psum_scatter) per
    power iteration. Returns (ranks[:n_nodes], err, iters).

    The convergence check trails by one iteration (its global reduction
    rides the next iteration's collective), so tol-based runs may do one
    extra iteration; fixed-iteration runs (tol=0) are unchanged.

    `precision="bf16"` rounds per-edge contributions to bfloat16 before
    the f32 accumulation (semiring.PRECISION_BOUNDS documents the error
    budget); the collective payload stays f32.

    `x0` (optional, (n_nodes,) f32) warm-starts the power iteration from
    a previous solution (ops/delta.py commit-then-CALL): PageRank is a
    contraction with a unique fixpoint, so any seed converges to the
    same answer at the same tol — the seed only changes the iteration
    count. The seed is renormalized to unit mass and rides the SAME
    compiled chunk kernel (x0 is data, not structure: no recompile, the
    carry donation covers it).

    `checkpoint_every=k` (> 0) checkpoints the loop carry to host memory
    every k iterations and resumes from the last checkpoint after a
    device fault — re-executing at most k iterations, bit-exact to an
    unfaulted run (parallel/checkpoint.py). `job` keys the checkpoint in
    `store` so a caller that died mid-run can also resume.
    """
    if scsr.by != "src":
        raise ValueError("pagerank needs a src-owned ShardedCSR")
    fn = _pc_cached("pagerank", _pc_pagerank_build, ctx,
                    scsr.block, scsr.n_shards, precision)
    if x0 is None:
        ids = np.arange(scsr.n_pad2, dtype=np.int64)
        rank0 = (ids < scsr.n_nodes).astype(np.float32) \
            / np.float32(scsr.n_nodes)
    else:
        rank0 = _warm_vertex_vector(x0, scsr, np.float32, pad_value=0.0)
        total = float(rank0.sum())
        if not np.isfinite(total) or total <= 0.0:
            ids = np.arange(scsr.n_pad2, dtype=np.int64)
            rank0 = (ids < scsr.n_nodes).astype(np.float32) \
                / np.float32(scsr.n_nodes)
        else:
            rank0 /= np.float32(total)
    carry0 = (rank0,
              np.full((scsr.n_shards,), np.inf, dtype=np.float32),
              np.float32(np.inf), np.int32(0))

    def chunk_of(s):
        def chunk(carry, it_stop):
            return fn(s.src, s.dst, s.weights, jnp.int32(s.n_nodes),
                      jnp.float32(damping), jnp.float32(tol),
                      *carry, jnp.int32(it_stop))
        return chunk

    rank, _, err, iters = _run_pc_resumable(
        algo="pagerank", scsr=scsr, ctx=ctx, chunk_of=chunk_of,
        carry0=carry0, iter_index=3, max_iterations=max_iterations,
        checkpoint_every=checkpoint_every, job=job, store=store,
        retry=retry, chunk_deadline_s=chunk_deadline_s, report=report)
    return rank[:scsr.n_nodes], float(err), int(iters)


def _pc_katz_build(ctx: MeshContext, block: int, n_shards: int,
                   precision: str = "f32"):
    axis = ctx.axis
    n_pad2 = n_shards * block

    def step(src_blk, dst_blk, w_blk, n_nodes, alpha, beta, tol,
             x, err, it, it_stop):
        src_blk, dst_blk, w_blk = src_blk[0], dst_blk[0], w_blk[0]
        valid_f = (jnp.arange(n_pad2, dtype=jnp.int32) < n_nodes
                   ).astype(jnp.float32)

        def body(carry):
            x, _, it = carry
            contrib = _cast_contrib(x[src_blk] * w_blk, precision)
            acc_local = edge_reduce("sum", contrib, dst_blk, n_pad2,
                                    sorted=True)
            acc = jax.lax.psum(acc_local, axis)    # the one collective
            new_x = valid_f * (alpha * acc + beta)
            # x is replicated: every device computes the same error —
            # no collective needed for the convergence check
            err = jnp.max(jnp.abs(new_x - x))
            return new_x, err, it + 1

        def cond(carry):
            _, err, it = carry
            return (err > tol) & (it < it_stop)

        x, err, iters = jax.lax.while_loop(cond, body, (x, err, it))
        return x, err, iters

    Pr = P()
    Pe = P(axis, None)
    # carry (x, err, it) donated — see _pc_pagerank_build
    return jax.jit(shard_map(
        step, mesh=ctx.mesh,
        in_specs=(Pe, Pe, Pe, Pr, Pr, Pr, Pr, Pr, Pr, Pr, Pr),
        out_specs=(Pr, Pr, Pr)), donate_argnums=(7, 8, 9))


def _katz_normalize(x):
    """Final L2 normalization, applied once AFTER the outer chunk loop
    (inside the loop it would have to re-run per chunk and break the
    chunked ≡ monolithic equivalence)."""
    x = jnp.asarray(x)
    norm = jnp.sqrt(jnp.sum(x * x))
    return x / jnp.maximum(norm, 1e-30)


def katz_partition_centric(scsr: ShardedCSR, ctx: MeshContext,
                           alpha: float = 0.2, beta: float = 1.0,
                           max_iterations: int = 100, tol: float = 1e-6,
                           normalized: bool = False, *,
                           precision: str = "f32", x0=None,
                           checkpoint_every: int = 0,
                           job: str | None = None, store=None,
                           retry=None, chunk_deadline_s=None,
                           report=None):
    """Katz centrality over the mesh: x replicated, one psum/iteration.
    `x0` warm-starts from a previous (UN-normalized) solution — the
    Katz iteration is a contraction for alpha < 1/λ_max, so any seed
    reaches the same fixpoint at the same tol (ops/delta.py contract).
    Checkpoint/resume semantics as in `pagerank_partition_centric`."""
    fn = _pc_cached("katz", _pc_katz_build, ctx,
                    scsr.block, scsr.n_shards, precision)
    start = (np.zeros(scsr.n_pad2, dtype=np.float32) if x0 is None
             else _warm_vertex_vector(x0, scsr, np.float32,
                                      pad_value=0.0))
    carry0 = (start,
              np.float32(np.inf), np.int32(0))

    def chunk_of(s):
        def chunk(carry, it_stop):
            return fn(s.src, s.dst, s.weights, jnp.int32(s.n_nodes),
                      jnp.float32(alpha), jnp.float32(beta),
                      jnp.float32(tol), *carry, jnp.int32(it_stop))
        return chunk

    x, err, iters = _run_pc_resumable(
        algo="katz", scsr=scsr, ctx=ctx, chunk_of=chunk_of,
        carry0=carry0, iter_index=2, max_iterations=max_iterations,
        checkpoint_every=checkpoint_every, job=job, store=store,
        retry=retry, chunk_deadline_s=chunk_deadline_s, report=report)
    if normalized:
        x = _katz_normalize(x)
    return x[:scsr.n_nodes], float(err), int(iters)


def _pc_labelprop_build(ctx: MeshContext, block: int, n_shards: int,
                        per: int):
    axis = ctx.axis
    n_pad2 = n_shards * block

    def step(src_blk, dst_blk, w_blk, self_weight,
             labels_in, changed_in, it, it_stop):
        src_blk, dst_blk, w_blk = src_blk[0], dst_blk[0], w_blk[0]
        shard_id = jax.lax.axis_index(axis)
        base = shard_id * block

        def one_round(labels):
            # edges are DST-owned: every incident edge of an owned
            # vertex is local, so run reduction + election are local
            lab_e = labels[src_blk]
            d_s, l_s, w_s = jax.lax.sort((dst_blk, lab_e, w_blk),
                                         num_keys=2)
            first = jnp.concatenate([
                jnp.ones((1,), dtype=jnp.bool_),
                (d_s[1:] != d_s[:-1]) | (l_s[1:] != l_s[:-1])])
            run_id = jnp.cumsum(first.astype(jnp.int32)) - 1
            run_w = jax.ops.segment_sum(w_s, run_id, num_segments=per)
            idx = jnp.arange(per, dtype=jnp.int32)
            first_idx = jax.ops.segment_min(
                jnp.where(first, idx, per), run_id, num_segments=per)
            first_idx = jnp.minimum(first_idx, per - 1)
            run_dst_local = d_s[first_idx] - base
            run_lab = l_s[first_idx]
            valid_run = idx <= run_id[-1]
            # padding edges carry weight 0 into the sink row; runs that
            # fall outside the local block clip to an ignored slot
            in_block = (run_dst_local >= 0) & (run_dst_local < block)
            run_dst_local = jnp.clip(run_dst_local, 0, block - 1)
            run_w = jnp.where(valid_run & in_block, run_w, 0.0)
            best_w = jax.ops.segment_max(run_w, run_dst_local,
                                         num_segments=block)
            is_best = run_w >= best_w[run_dst_local] - 1e-12
            cand = jnp.where(valid_run & in_block & is_best, run_lab,
                             jnp.int32(n_pad2))
            best_lab = jax.ops.segment_min(cand, run_dst_local,
                                           num_segments=block)
            has_nb = best_lab < n_pad2
            own = jax.lax.dynamic_slice(labels, (base,), (block,))
            own_wins = (~has_nb) | (self_weight >= best_w) | \
                       (jnp.isclose(self_weight, best_w)
                        & (own <= best_lab))
            new_local = jnp.where(own_wins, own, best_lab)
            # disjoint block election: one psum concatenates the blocks
            contrib = jax.lax.dynamic_update_slice(
                jnp.zeros(n_pad2, dtype=jnp.int32), new_local, (base,))
            return jax.lax.psum(contrib, axis)

        def body(carry):
            labels, _, it = carry
            new = one_round(labels)
            return new, jnp.any(new != labels), it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < it_stop)

        labels, changed, iters = jax.lax.while_loop(
            cond, body, (labels_in, changed_in, it))
        return labels, changed, iters

    Pr = P()
    Pe = P(axis, None)
    # carry (labels, changed, it) donated — see _pc_pagerank_build
    return jax.jit(shard_map(
        step, mesh=ctx.mesh,
        in_specs=(Pe, Pe, Pe, Pr, Pr, Pr, Pr, Pr),
        out_specs=(Pr, Pr, Pr)), donate_argnums=(4, 5, 6))


def labelprop_partition_centric(scsr: ShardedCSR, ctx: MeshContext,
                                max_iterations: int = 30,
                                self_weight: float = 0.0, *,
                                labels0=None,
                                checkpoint_every: int = 0,
                                job: str | None = None, store=None,
                                retry=None, chunk_deadline_s=None,
                                report=None):
    """Synchronous label propagation over the mesh (dst-owned edges,
    labels replicated, one int psum per round). `scsr` must be built
    with by="dst" (both edge directions already concatenated for the
    undirected variant). Returns (labels[:n_nodes], iters).

    `labels0` warm-starts the election from a previous labeling —
    ONLY valid when the delta since that labeling added edges (the
    monotone gate in ops/delta.py): the election re-runs over a
    superset of neighbors and re-converges; removals must cold-start
    LOUDLY because a community held together by a removed edge would
    never re-elect. Checkpoint/resume semantics as in
    `pagerank_partition_centric`."""
    if scsr.by != "dst":
        raise ValueError("labelprop needs a dst-owned ShardedCSR")
    fn = _pc_cached("labelprop", _pc_labelprop_build, ctx,
                    scsr.block, scsr.n_shards, scsr.per)
    start = (np.arange(scsr.n_pad2, dtype=np.int32) if labels0 is None
             else _warm_vertex_vector(labels0, scsr, np.int32))
    carry0 = (start,
              np.bool_(True), np.int32(0))

    def chunk_of(s):
        def chunk(carry, it_stop):
            return fn(s.src, s.dst, s.weights, jnp.float32(self_weight),
                      *carry, jnp.int32(it_stop))
        return chunk

    labels, _, iters = _run_pc_resumable(
        algo="labelprop", scsr=scsr, ctx=ctx, chunk_of=chunk_of,
        carry0=carry0, iter_index=2, max_iterations=max_iterations,
        checkpoint_every=checkpoint_every, job=job, store=store,
        retry=retry, chunk_deadline_s=chunk_deadline_s, report=report)
    return labels[:scsr.n_nodes], int(iters)


def _pc_wcc_build(ctx: MeshContext, block: int, n_shards: int):
    axis = ctx.axis
    n_pad2 = n_shards * block

    def step(src_blk, dst_blk, comp_in, changed_in, it, it_stop):
        src_blk, dst_blk = src_blk[0], dst_blk[0]

        def body(carry):
            comp, _, it = carry
            fwd = jax.ops.segment_min(comp[src_blk], dst_blk,
                                      num_segments=n_pad2,
                                      indices_are_sorted=True)
            bwd = jax.ops.segment_min(comp[dst_blk], src_blk,
                                      num_segments=n_pad2)
            cand = jax.lax.pmin(jnp.minimum(fwd, bwd), axis)  # the one
            new = jnp.minimum(comp, cand)
            new = new[new]                     # pointer jump, replicated
            return new, jnp.any(new != comp), it + 1

        def cond(carry):
            _, changed, it = carry
            return changed & (it < it_stop)

        comp, changed, iters = jax.lax.while_loop(
            cond, body, (comp_in, changed_in, it))
        return comp, changed, iters

    Pr = P()
    Pe = P(axis, None)
    # carry (comp, changed, it) donated — see _pc_pagerank_build
    return jax.jit(shard_map(
        step, mesh=ctx.mesh,
        in_specs=(Pe, Pe, Pr, Pr, Pr, Pr),
        out_specs=(Pr, Pr, Pr)), donate_argnums=(2, 3, 4))


def wcc_partition_centric(scsr: ShardedCSR, ctx: MeshContext,
                          max_iterations: int = 200, *,
                          comp0=None,
                          checkpoint_every: int = 0,
                          job: str | None = None, store=None,
                          retry=None, chunk_deadline_s=None,
                          report=None):
    """Weakly-connected components over the mesh: comp replicated, one
    pmin per round + pointer jumping. Returns (comp[:n_nodes], iters).

    `comp0` warm-starts from a previous min-label assignment — ONLY
    valid when the delta since it added edges (the monotone gate in
    ops/delta.py): min-label propagation can merge components but never
    split them, so a removal-carrying delta must cold-start LOUDLY.
    Checkpoint/resume semantics as in `pagerank_partition_centric`."""
    fn = _pc_cached("wcc", _pc_wcc_build, ctx,
                    scsr.block, scsr.n_shards)
    start = (np.arange(scsr.n_pad2, dtype=np.int32) if comp0 is None
             else _warm_vertex_vector(comp0, scsr, np.int32))
    carry0 = (start,
              np.bool_(True), np.int32(0))

    def chunk_of(s):
        def chunk(carry, it_stop):
            return fn(s.src, s.dst, *carry, jnp.int32(it_stop))
        return chunk

    comp, _, iters = _run_pc_resumable(
        algo="wcc", scsr=scsr, ctx=ctx, chunk_of=chunk_of,
        carry0=carry0, iter_index=2, max_iterations=max_iterations,
        checkpoint_every=checkpoint_every, job=job, store=store,
        retry=retry, chunk_deadline_s=chunk_deadline_s, report=report)
    return comp[:scsr.n_nodes], int(iters)


# ==========================================================================
# Generic semiring kernel (ops/semiring.py's mesh backend)
# ==========================================================================
#
# A NEW algorithm's mesh story is now a (semiring, x0, epilogue) triple:
# x replicated, per-shard ⊗-combine + local ⊕-reduce, ONE ⊕-matched
# collective per iteration (psum / pmin / pmax), the fused epilogue
# applied replicated — same invariants as the tuned kernels above, and
# checkpoint-resumable through the same r12 chunk machinery.

_PC_COLLECTIVE = {"sum": jax.lax.psum, "min": jax.lax.pmin,
                  "max": jax.lax.pmax, "or": jax.lax.pmax}


def _pc_semiring_build(ctx: MeshContext, block: int, n_shards: int,
                       sr_name: str, epilogue, metric: str,
                       precision: str):
    sr = resolve_semiring(sr_name)
    axis = ctx.axis
    n_pad2 = n_shards * block
    collective = _PC_COLLECTIVE[sr.add]

    def step(src_blk, dst_blk, w_blk, params, x, m, it, it_stop):
        src_blk, dst_blk, w_blk = src_blk[0], dst_blk[0], w_blk[0]

        def body(carry):
            x, _, it = carry
            vals = edge_combine(sr, x[src_blk],
                                None if sr.mul == "first" else w_blk)
            if jnp.issubdtype(vals.dtype, jnp.floating):
                vals = _cast_contrib(vals, precision)
            acc_local = edge_reduce(sr.add, vals, dst_blk, n_pad2,
                                    sorted=True)
            acc = collective(acc_local, axis)      # the one collective
            new_x, new_m = epilogue(x, acc, {}, params)
            return new_x, new_m, it + 1

        if metric == "changed":
            def cond(carry):
                _, m, it = carry
                return m & (it < it_stop)
        else:
            def cond(carry):
                _, m, it = carry
                return (m > params["tol"]) & (it < it_stop)

        return jax.lax.while_loop(cond, body, (x, m, it))

    Pr = P()
    Pe = P(axis, None)
    # carry (x, m, it) donated — see _pc_pagerank_build
    return jax.jit(shard_map(
        step, mesh=ctx.mesh,
        in_specs=(Pe, Pe, Pe, Pr, Pr, Pr, Pr, Pr),
        out_specs=(Pr, Pr, Pr)), donate_argnums=(4, 5, 6))


def semiring_partition_centric(scsr: ShardedCSR, ctx: MeshContext,
                               semiring, x0, epilogue, params=None,
                               max_iterations: int = 100,
                               metric: str = "changed",
                               precision: str = "f32", *,
                               algo: str = "semiring",
                               checkpoint_every: int = 0,
                               job: str | None = None, store=None,
                               retry=None, chunk_deadline_s=None,
                               report=None):
    """Run a (semiring, x0, epilogue) fixpoint over the mesh: exactly
    one collective per iteration, checkpoint-resumable. Returns
    (x[:n_nodes], metric, iters)."""
    sr = resolve_semiring(semiring)
    params = params or {}
    fn = _pc_cached(f"semiring:{sr.name}", _pc_semiring_build, ctx,
                    scsr.block, scsr.n_shards, sr.name, epilogue,
                    metric, precision)
    m0 = np.bool_(True) if metric == "changed" \
        else np.float32(np.inf)
    carry0 = (np.asarray(x0), m0, np.int32(0))

    def chunk_of(s):
        def chunk(carry, it_stop):
            return fn(s.src, s.dst, s.weights, params, *carry,
                      jnp.int32(it_stop))
        return chunk

    x, m, iters = _run_pc_resumable(
        algo=algo, scsr=scsr, ctx=ctx, chunk_of=chunk_of,
        carry0=carry0, iter_index=2, max_iterations=max_iterations,
        checkpoint_every=checkpoint_every, job=job, store=store,
        retry=retry, chunk_deadline_s=chunk_deadline_s, report=report)
    return x[:scsr.n_nodes], m, int(iters)


def _minplus_relax_epilogue(x, acc, env, P):
    """min-plus relaxation epilogue (BFS / SSSP over the mesh)."""
    new = jnp.minimum(x, acc)
    return new, jnp.any(new < x)


# ==========================================================================
# mgtier execution plane: streamed out-of-core fixpoints
# ==========================================================================
#
# The data plane (ops/tier.py) pins the ShardedCSR rows host-side as
# compressed wire blocks; this is the loop that runs a fixpoint over
# them without ever holding the edge set on the device:
#
#   per iteration (one sweep over all P blocks):
#     dispatch device_put(block 0)                      # H2D, async
#     for k in 0..P-1:
#       dispatch device_put(block k+1)                  # next buffer
#       acc = fold(acc, block k)                        # SpMV on k
#     x, metric = epilogue(x, acc)                      # O(n), on-device
#
# JAX's async dispatch turns the two in-flight buffers into the classic
# double-buffer schedule (the pallas-guide DMA pattern applied at the
# host→HBM boundary): block k+1's transfer runs while block k's segment
# reduction executes, so steady-state cost is max(transfer, compute)
# per block instead of the sum. The O(n) iterate/accumulator/env
# vectors stay device-resident across the whole run.
#
# Honest measurement: the FIRST streamed iteration runs the schedule
# serially (put → wait → fold → wait, per block) to price transfer and
# compute separately; later iterations run overlapped and the per-
# iteration wall clock yields `hidden = (T_xfer + T_comp - T_iter) /
# T_xfer` — the fraction of transfer the overlap actually hid (≈0 on a
# CPU host where "transfer" is a memcpy; the perf gate tags that
# degraded rather than asserting a fantasy).
#
# The resident comparator (`resident=True`) pre-places every block and
# runs the IDENTICAL kernels in the identical order — the FLOP schedule
# is shared, only the transfer schedule differs, which is what makes
# the streamed-vs-resident f32 bit-exactness test meaningful.

_TIER_KERNEL_CACHE: dict = {}


def _tier_cached(kind: str, builder, *shape_key):
    key = (kind,) + shape_key
    fn = _TIER_KERNEL_CACHE.get(key)
    if fn is None:
        fn = _TIER_KERNEL_CACHE[key] = builder(*shape_key)
    return fn


def _tier_decode(blk, block: int, per: int, precision: str, u16: bool,
                 need_w: bool = True):
    """Traced half of the ops/tier.py codec: rebuild (src, dst, w) from
    a wire block INSIDE the jitted sweep, so only compressed bytes cross
    the host→device boundary. Index decode is exact (uint16 offsets +
    shard bases); weights dequantize per the tier's precision with f32
    accumulation downstream."""
    if u16:
        src = blk["src_off"].astype(jnp.int32) + blk["base"]
        q = jnp.searchsorted(
            blk["bounds"][1:], jnp.arange(per, dtype=jnp.int32),
            side="right").astype(jnp.int32)
        dst = blk["dst_off"].astype(jnp.int32) + q * block
    else:
        src, dst = blk["src"], blk["dst"]
    if not need_w:
        return src, dst, None
    w = blk["w"]
    if precision == "bf16":
        w = w.astype(jnp.float32)
    elif precision == "int8":
        w = w.astype(jnp.float32) * blk["scale"]
    return src, dst, w


def _tier_wsum_build(block, per, n_pad2, precision, u16):
    def step(acc, blk):
        src, _dst, w = _tier_decode(blk, block, per, precision, u16)
        return acc + jax.ops.segment_sum(w, src, num_segments=n_pad2)
    return jax.jit(step, donate_argnums=(0,))


def _tier_pagerank_sweep_build(block, per, n_pad2, precision, u16):
    def step(acc, x, inv_wsum, blk):
        src, dst, w = _tier_decode(blk, block, per, precision, u16)
        contrib = x[src] * (w * inv_wsum[src])
        contrib = _cast_contrib(contrib,
                                "bf16" if precision == "bf16" else "f32")
        return acc + jax.ops.segment_sum(contrib, dst,
                                         num_segments=n_pad2,
                                         indices_are_sorted=True)
    return jax.jit(step, donate_argnums=(0,))


def _tier_pagerank_epilogue_build(n_pad2):
    def fin(x, acc, dangling_f, valid_f, n_f, damping):
        dm = jnp.sum(x * dangling_f)
        new = pagerank_update(acc, dm, valid_f, n_f, damping)
        err = jnp.sum(jnp.abs(new - x))
        return new, err
    # only ONE O(n) output exists to alias — donating both x and acc
    # makes XLA silently COPY the second (a UserWarning at compile, a
    # full extra iterate on a production device). tools/mgmem gates
    # dropped donations; declare exactly the donation that lands.
    return jax.jit(fin, donate_argnums=(0,))


def _tier_katz_sweep_build(block, per, n_pad2, precision, u16):
    def step(acc, x, blk):
        src, dst, w = _tier_decode(blk, block, per, precision, u16)
        contrib = _cast_contrib(
            x[src] * w, "bf16" if precision == "bf16" else "f32")
        return acc + jax.ops.segment_sum(contrib, dst,
                                         num_segments=n_pad2,
                                         indices_are_sorted=True)
    return jax.jit(step, donate_argnums=(0,))


def _tier_katz_epilogue_build(n_pad2):
    def fin(x, acc, valid_f, alpha, beta):
        new = valid_f * (alpha * acc + beta)
        err = jnp.max(jnp.abs(new - x))
        return new, err
    # one O(n) output slot: donate only the alias that lands (mgmem)
    return jax.jit(fin, donate_argnums=(0,))


def _tier_wcc_sweep_build(block, per, n_pad2, u16):
    def step(cand, comp, blk):
        src, dst, _ = _tier_decode(blk, block, per, "f32", u16,
                                   need_w=False)
        # padding edges carry a REAL local src (the shard base) toward
        # the sink row; weightless min-reductions must mask them or the
        # sink merges unrelated components on the backward pass
        real = jnp.arange(per, dtype=jnp.int32) < blk["rc"]
        ident = jnp.int32(n_pad2)
        fwd = jnp.where(real, comp[src], ident)
        bwd = jnp.where(real, comp[dst], ident)
        cand = jnp.minimum(cand, jax.ops.segment_min(
            fwd, dst, num_segments=n_pad2, indices_are_sorted=True))
        cand = jnp.minimum(cand, jax.ops.segment_min(
            bwd, src, num_segments=n_pad2))
        return cand
    return jax.jit(step, donate_argnums=(0,))


def _tier_wcc_epilogue_build(n_pad2):
    def fin(comp, cand):
        new = jnp.minimum(comp, cand)
        new = new[new]                        # pointer jump
        changed = jnp.any(new != comp)
        return new, changed
    # one O(n) output slot: donate only the alias that lands (mgmem)
    return jax.jit(fin, donate_argnums=(0,))


def _put_block(hb, device):
    return jax.device_put(hb.payload, device)


def _tier_sweep(tier, dev_blocks, fold, acc, device, measure=None):
    """One full pass over the edge blocks: ``acc = fold(acc, blk)``.

    ``dev_blocks`` set → resident comparator (pre-placed, same kernels,
    same order). ``measure`` set → serial timed schedule (prices
    transfer vs compute separately). Otherwise the double-buffered
    streaming schedule: block k+1's put is dispatched before block k's
    fold, so the H2D copy overlaps the segment reduction.
    """
    if dev_blocks is not None:
        for blk in dev_blocks:
            acc = fold(acc, blk)
        return acc
    blocks = tier.blocks
    if measure is not None:
        for hb in blocks:
            t0 = time.perf_counter()
            blk = jax.block_until_ready(_put_block(hb, device))  # mglint: disable=MG009 — the MEASURED serial iteration exists to price transfer vs compute separately; the sync IS the measurement, and it runs exactly once per run
            t1 = time.perf_counter()
            acc = jax.block_until_ready(fold(acc, blk))  # mglint: disable=MG009 — same measured-iteration contract: without the per-block sync the async dispatch would hide exactly the cost being priced
            t2 = time.perf_counter()
            measure["t_xfer"] += t1 - t0
            measure["t_comp"] += t2 - t1
            global_metrics.observe("tier.block_transfer_latency_sec",
                                   t1 - t0)
        return acc
    nxt = _put_block(blocks[0], device)
    for k in range(len(blocks)):
        cur, nxt = nxt, (_put_block(blocks[k + 1], device)
                         if k + 1 < len(blocks) else None)
        acc = fold(acc, cur)
    return acc


def _count_sweep(tier):
    global_metrics.increment("tier.blocks_streamed_total",
                             tier.n_blocks)
    global_metrics.increment("tier.bytes_streamed_total",
                             tier.raw_bytes_per_sweep)
    global_metrics.increment("tier.compressed_bytes_total",
                             tier.wire_bytes_per_sweep)


def _tier_fixpoint(*, algo, tier, env_of, iterate, x0, metric0,
                   keep_going, max_iterations, resident=False,
                   stats=None, checkpoint_every=0, job=None, store=None,
                   retry=None, chunk_deadline_s=None, report=None):
    """Shared streamed-fixpoint driver, wired into the checkpoint layer.

    ``env_of(device, sweep)`` builds the per-run device-resident
    environment (may itself sweep the blocks, e.g. pagerank's wsum
    pass); ``iterate(x, env, sweep)`` runs ONE iteration (sweep +
    epilogue) and returns ``(new_x, metric)`` with a device metric.
    Chunks checkpoint the (x, metric, it) carry to host; a device fault
    resumes from the last chunk boundary, a ``device_lost`` additionally
    drops the env/resident blocks so they re-place on the fresh client.
    """
    from .checkpoint import run_resumable
    device = streaming_device()
    # price the run through the admission estimator the server's
    # verdict used — every device materialization below (block H2D,
    # carry re-place, accumulator/env vectors in the drivers) lives
    # inside this modeled budget, which tools/mgmem machine-checks
    # against XLA's buffer assignment per phase (MG011 accounting root)
    global_metrics.set_gauge(
        "tier.modeled_request_bytes",
        float(mgtier.streamed_request_bytes(
            tier.n_nodes, tier.n_edges, tier.precision,
            algorithm=algo)))
    holder: dict = {}
    measured = {"serial": None, "iters": 0, "hidden_sum": 0.0,
                "overlap_iters": 0, "overlap_wall": 0.0}

    def dev_blocks():
        if not resident:
            return None
        db = holder.get("blocks")
        if db is None:
            db = holder["blocks"] = [_put_block(hb, device)
                                     for hb in tier.blocks]
        return db

    def sweep(fold, acc, measure=None):
        out = _tier_sweep(tier, dev_blocks(), fold, acc, device,
                          measure=measure)
        if not resident:
            _count_sweep(tier)
        return out

    def env():
        e = holder.get("env")
        if e is None:
            e = holder["env"] = env_of(device, sweep)
        return e

    def chunk(carry, it_stop):
        x, metric, it = carry
        x = jax.device_put(x, device)
        while it < it_stop and keep_going(metric):
            measure = None
            if not resident and measured["serial"] is None:
                measure = {"t_xfer": 0.0, "t_comp": 0.0}
            t0 = time.perf_counter()
            x, m_dev = iterate(x, env(),
                               lambda f, a: sweep(f, a, measure))
            metric = np.asarray(m_dev)  # mglint: disable=MG009 — the host drives the per-block streaming loop, so the per-ITERATION convergence read is the sync granularity by construction (the sweep inside the iteration is where overlap lives)
            wall = time.perf_counter() - t0
            if measure is not None:
                measured["serial"] = measure
                mgstats.record_stage("device_transfer",
                                     measure["t_xfer"])
            elif not resident and measured["serial"] is not None:
                s = measured["serial"]
                if s["t_xfer"] > 0:
                    hidden = (s["t_xfer"] + s["t_comp"] - wall) \
                        / s["t_xfer"]
                    hidden = min(max(hidden, 0.0), 1.0)
                    measured["hidden_sum"] += hidden
                    measured["overlap_iters"] += 1
                    measured["overlap_wall"] += wall
                    global_metrics.observe(
                        "tier.transfer_hidden_fraction", hidden)
            measured["iters"] += 1
            it += 1
        return x, metric, it

    def rebuild():
        holder.clear()                        # re-place env + blocks
        return None                           # chunk closure re-reads

    x, metric, iters = run_resumable(
        algo=algo, chunk=chunk, carry=(np.asarray(x0), metric0, 0),
        carry_to_host=lambda c: (np.asarray(c[0]), np.asarray(c[1]),
                                 int(c[2])),
        carry_from_host=lambda p: p, iter_of=lambda c: int(c[2]),
        max_iterations=max_iterations,
        checkpoint_every=checkpoint_every, job=job, store=store,
        retry=retry, rebuild=rebuild, chunk_deadline_s=chunk_deadline_s,
        report=report)

    if stats is not None:
        s = measured["serial"] or {"t_xfer": 0.0, "t_comp": 0.0}
        n_ov = measured["overlap_iters"]
        stats.update({
            "mode": "resident" if resident else "streamed",
            "precision": tier.precision,
            "n_blocks": tier.n_blocks,
            "iterations": int(iters),
            "wire_bytes_per_sweep": tier.wire_bytes_per_sweep,
            "raw_bytes_per_sweep": tier.raw_bytes_per_sweep,
            "serial_transfer_s": s["t_xfer"],
            "serial_compute_s": s["t_comp"],
            "overlap_iters": n_ov,
            "overlap_iter_s_mean": (measured["overlap_wall"] / n_ov)
            if n_ov else None,
            "transfer_hidden_fraction": (measured["hidden_sum"] / n_ov)
            if n_ov else None,
        })
    return x, metric, int(iters)


def pagerank_streamed(tier, damping: float = 0.85,
                      max_iterations: int = 100, tol: float = 1e-6, *,
                      x0=None, resident: bool = False, stats=None,
                      checkpoint_every: int = 0, job: str | None = None,
                      store=None, retry=None, chunk_deadline_s=None,
                      report=None):
    """PageRank over a host-pinned :class:`~..ops.tier.TierCSR` —
    out-of-core: only edge blocks stream, the rank vector stays
    device-resident. Returns ``(ranks[:n], err, iters)``."""
    scsr, n, n_pad2 = tier.scsr, tier.n_nodes, tier.n_pad2
    shape = (tier.block, tier.per, n_pad2, tier.precision, tier.u16)
    wsum_fn = _tier_cached("wsum", _tier_wsum_build, *shape)
    sweep_fn = _tier_cached("pr_sweep", _tier_pagerank_sweep_build,
                            *shape)
    epi_fn = _tier_cached("pr_epi", _tier_pagerank_epilogue_build,
                          n_pad2)
    n_f = np.float32(n)
    damping = np.float32(damping)

    if x0 is None:
        x0v = np.zeros(n_pad2, np.float32)
        x0v[:n] = 1.0 / n
    else:
        x0v = _warm_vertex_vector(x0, scsr, np.float32, pad_value=0.0)

    def env_of(device, sweep):
        valid = np.zeros(n_pad2, np.float32)
        valid[:n] = 1.0
        valid_f = jax.device_put(valid, device)
        wsum = sweep(wsum_fn, jnp.zeros(n_pad2, jnp.float32))
        dangling_f = valid_f * (wsum == 0.0)
        inv_wsum = jnp.where(wsum > 0.0, 1.0 / wsum, 0.0)
        return {"valid_f": valid_f, "dangling_f": dangling_f,
                "inv_wsum": inv_wsum}

    def iterate(x, env, sweep):
        acc = sweep(lambda a, blk: sweep_fn(a, x, env["inv_wsum"], blk),
                    jnp.zeros(n_pad2, jnp.float32))
        return epi_fn(x, acc, env["dangling_f"], env["valid_f"],
                      n_f, damping)

    with backend_extent("streamed"):
        x, err, iters = _tier_fixpoint(
            algo="pagerank", tier=tier, env_of=env_of, iterate=iterate,
            x0=x0v, metric0=np.float32(np.inf),
            keep_going=lambda m: float(m) > tol,
            max_iterations=max_iterations, resident=resident,
            stats=stats, checkpoint_every=checkpoint_every, job=job,
            store=store, retry=retry,
            chunk_deadline_s=chunk_deadline_s, report=report)
    return np.asarray(x)[:n], float(err), iters


def katz_streamed(tier, alpha: float = 0.1, beta: float = 1.0,
                  max_iterations: int = 100, tol: float = 1e-6, *,
                  normalized: bool = True, x0=None,
                  resident: bool = False, stats=None,
                  checkpoint_every: int = 0, job: str | None = None,
                  store=None, retry=None, chunk_deadline_s=None,
                  report=None):
    """Katz centrality over a host-pinned TierCSR. Returns
    ``(scores[:n], err, iters)``."""
    scsr, n, n_pad2 = tier.scsr, tier.n_nodes, tier.n_pad2
    shape = (tier.block, tier.per, n_pad2, tier.precision, tier.u16)
    sweep_fn = _tier_cached("katz_sweep", _tier_katz_sweep_build,
                            *shape)
    epi_fn = _tier_cached("katz_epi", _tier_katz_epilogue_build, n_pad2)
    alpha = np.float32(alpha)
    beta = np.float32(beta)
    x0v = (np.zeros(n_pad2, np.float32) if x0 is None
           else _warm_vertex_vector(x0, scsr, np.float32, pad_value=0.0))

    def env_of(device, sweep):
        valid = np.zeros(n_pad2, np.float32)
        valid[:n] = 1.0
        return {"valid_f": jax.device_put(valid, device)}

    def iterate(x, env, sweep):
        acc = sweep(lambda a, blk: sweep_fn(a, x, blk),
                    jnp.zeros(n_pad2, jnp.float32))
        return epi_fn(x, acc, env["valid_f"], alpha, beta)

    with backend_extent("streamed"):
        x, err, iters = _tier_fixpoint(
            algo="katz", tier=tier, env_of=env_of, iterate=iterate,
            x0=x0v, metric0=np.float32(np.inf),
            keep_going=lambda m: float(m) > tol,
            max_iterations=max_iterations, resident=resident,
            stats=stats, checkpoint_every=checkpoint_every, job=job,
            store=store, retry=retry,
            chunk_deadline_s=chunk_deadline_s, report=report)
    out = np.asarray(x)[:n]
    if normalized:
        nrm = float(np.linalg.norm(out))
        if nrm > 0:
            out = out / nrm
    return out, float(err), iters


def wcc_streamed(tier, max_iterations: int = 200, *, comp0=None,
                 resident: bool = False, stats=None,
                 checkpoint_every: int = 0, job: str | None = None,
                 store=None, retry=None, chunk_deadline_s=None,
                 report=None):
    """Weakly-connected components over a host-pinned TierCSR (min-
    label propagation + pointer jumping). Returns
    ``(labels[:n], changed, iters)``."""
    scsr, n, n_pad2 = tier.scsr, tier.n_nodes, tier.n_pad2
    shape = (tier.block, tier.per, n_pad2, tier.u16)
    sweep_fn = _tier_cached("wcc_sweep", _tier_wcc_sweep_build, *shape)
    epi_fn = _tier_cached("wcc_epi", _tier_wcc_epilogue_build, n_pad2)
    x0v = (np.arange(n_pad2, dtype=np.int32) if comp0 is None
           else _warm_vertex_vector(comp0, scsr, np.int32))

    def env_of(device, sweep):
        return {}

    def iterate(comp, env, sweep):
        cand = sweep(lambda a, blk: sweep_fn(a, comp, blk),
                     jnp.full(n_pad2, n_pad2, jnp.int32))
        return epi_fn(comp, cand)

    with backend_extent("streamed"):
        comp, changed, iters = _tier_fixpoint(
            algo="wcc", tier=tier, env_of=env_of, iterate=iterate,
            x0=x0v, metric0=np.bool_(True),
            keep_going=lambda m: bool(m),
            max_iterations=max_iterations, resident=resident,
            stats=stats, checkpoint_every=checkpoint_every, job=job,
            store=store, retry=retry,
            chunk_deadline_s=chunk_deadline_s, report=report)
    return np.asarray(comp)[:n], bool(changed), iters
