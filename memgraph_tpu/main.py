"""Process entry / composition root.

Counterpart of /root/reference/src/memgraph.cpp main(): wires config,
storage (with durability recovery), interpreter context, triggers, auth,
query-module directory, Bolt server, monitoring endpoint, and ordered
shutdown (snapshot-on-exit).

Run:  python -m memgraph_tpu.main --bolt-port 7687 --data-directory /tmp/mg
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys

from .auth.auth import Auth
from .query.interpreter import Interpreter, InterpreterContext
from .server.bolt import BoltServer
from .storage import InMemoryStorage, StorageConfig
from .storage.common import IsolationLevel, StorageMode


def build_config(argv=None) -> argparse.Namespace:
    """~Flag surface of the reference's src/flags/ (the subset that exists)."""
    p = argparse.ArgumentParser("memgraph_tpu")
    p.add_argument("--bolt-address", default="0.0.0.0")
    p.add_argument("--bolt-port", type=int, default=7687)
    p.add_argument("--bolt-advertised-address", default=None,
                   help="host:port other machines should dial for this "
                        "server (routing tables, cluster metadata); "
                        "defaults to localhost:<bolt-port>")
    p.add_argument("--memory-limit", type=int, default=0,
                   help="global tracked-memory limit in MiB (0 = off; "
                        "reference: --memory-limit)")
    p.add_argument("--bolt-cert-file", default=None,
                   help="TLS certificate for the Bolt listener (bolt+s)")
    p.add_argument("--bolt-key-file", default=None)
    p.add_argument("--cluster-cert-file", default=None,
                   help="intra-cluster TLS (replication, Raft, mgmt RPC); "
                        "reference analog memgraph.cpp:302-317")
    p.add_argument("--cluster-key-file", default=None)
    p.add_argument("--cluster-ca-file", default=None)
    p.add_argument("--data-directory", default=None,
                   help="durability directory (snapshots + WAL)")
    p.add_argument("--storage-mode", default="IN_MEMORY_TRANSACTIONAL",
                   choices=[m.value for m in StorageMode])
    p.add_argument("--isolation-level", default="SNAPSHOT_ISOLATION",
                   choices=[l.value for l in IsolationLevel])
    p.add_argument("--storage-wal-enabled",
                   action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--storage-wal-file-size-kib", type=int, default=65536,
                   help="WAL segment rotation size (KiB); old segments "
                        "are pruned once a snapshot covers them")
    p.add_argument("--storage-snapshot-on-exit",
                   action=argparse.BooleanOptionalAction, default=False)
    p.add_argument("--storage-recover-on-startup",
                   action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--query-modules-directory", default=None)
    p.add_argument("--auth-user-or-role-name-regex", default=".*")
    p.add_argument("--auth-module-mappings", default="",
                   help="external auth modules per Bolt scheme, e.g. "
                        "'saml:/path/to/module.py;oidc:/path/other.py' "
                        "(reference: src/auth/module.hpp)")
    p.add_argument("--monitoring-port", type=int, default=0,
                   help="websocket monitoring port: live log streaming + "
                        "metrics frames, as the reference's Lab channel "
                        "(communication/websocket/listener.cpp); "
                        "0 = disabled (reference default 7444)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="Prometheus/JSON metrics HTTP port "
                        "(0 = disabled; reference default 9091)")
    p.add_argument("--metrics-address", default=None,
                   help="bind address for the metrics HTTP endpoint")
    p.add_argument("--audit-enabled",
                   action=argparse.BooleanOptionalAction, default=False)
    p.add_argument("--storage-snapshot-interval-sec", type=int, default=0,
                   help="periodic snapshot interval (0 = disabled)")
    p.add_argument("--storage-gc-cycle-sec", type=int, default=30,
                   help="periodic delta-GC interval (0 = disabled)")
    p.add_argument("--log-level", default="INFO")
    p.add_argument("--init-file", default=None,
                   help="cypherl file executed on startup")
    p.add_argument("--init-data-file", default=None,
                   help="cypherl data file executed after --init-file "
                        "(reference: --init-data-file)")
    p.add_argument("--bolt-server-name-for-init", default=None,
                   help="server name sent in the Bolt HELLO response")
    p.add_argument("--log-failed-queries",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="log the text of failing queries at WARNING")
    p.add_argument("--debug-query-plans",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="log each prepared query's plan at DEBUG")
    p.add_argument("--monitoring-address", default=None,
                   help="bind address for the monitoring endpoint "
                        "(default: --bolt-address)")
    p.add_argument("--aws-access-key", default=None)
    p.add_argument("--aws-secret-key", default=None)
    p.add_argument("--aws-region", default=None)
    p.add_argument("--aws-endpoint-url", default=None,
                   help="S3-compatible endpoint for s3:// snapshot loads")
    p.add_argument("--storage-delta-on-identical-property-update",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="write a delta even when SET stores an identical "
                        "value (disable to skip no-op writes)")
    p.add_argument("--storage-automatic-label-index-creation-enabled",
                   action=argparse.BooleanOptionalAction, default=False)
    p.add_argument("--storage-automatic-edge-type-index-creation-enabled",
                   action=argparse.BooleanOptionalAction, default=False)
    p.add_argument("--storage-parallel-snapshot-creation",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="encode/decode snapshot chunks on a worker pool")
    p.add_argument("--replication-restore-state-on-startup",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="restore MAIN/REPLICA role and registered "
                        "replicas from the durable state")
    p.add_argument("--hops-limit-partial-results",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="USING HOPS LIMIT returns partial results when "
                        "the budget is spent (false: error)")
    p.add_argument("--execution-timeout-sec", type=float, default=600.0)
    # HA coordination (reference: --coordinator-id/--coordinator-port etc.)
    p.add_argument("--coordinator-id", default=None,
                   help="run as a coordinator with this raft node id")
    p.add_argument("--coordinator-port", type=int, default=0,
                   help="raft port for this coordinator")
    p.add_argument("--coordinator-peers", default="",
                   help="comma list of id=host:port raft peers")
    p.add_argument("--management-port", type=int, default=0,
                   help="data-instance management server port (HA)")
    # --- wider reference flag surface ------------------------------------
    p.add_argument("--storage-snapshot-retention-count", type=int,
                   default=3, help="how many snapshots to keep")
    p.add_argument("--storage-snapshot-thread-count", type=int, default=0,
                   help="snapshot encode/decode worker threads "
                        "(0 = cpu count)")
    p.add_argument("--storage-properties-on-edges",
                   action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--bolt-num-workers", type=int, default=0,
                   help="bolt worker threads (0 = auto)")
    p.add_argument("--query-execution-timeout-sec", type=float,
                   default=None,
                   help="reference-named alias of --execution-timeout-sec")
    p.add_argument("--log-file", default=None)
    p.add_argument("--telemetry-enabled", action="store_true",
                   help="send anonymous usage telemetry (object counts, "
                        "uptime; never query text or data) — reference: "
                        "--telemetry-enabled, src/telemetry/")
    p.add_argument("--telemetry-endpoint",
                   default="https://telemetry.invalid/v1/beat")
    p.add_argument("--also-log-to-stderr",
                   action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--allow-load-csv",
                   action=argparse.BooleanOptionalAction, default=True)
    p.add_argument("--memory-warning-threshold", type=int, default=1024,
                   help="log a warning when free system memory drops "
                        "below this many MB (0 disables)")
    p.add_argument("--kafka-bootstrap-servers", default="",
                   help="default brokers for CREATE KAFKA STREAM")
    p.add_argument("--pulsar-service-url", default="",
                   help="default service url for CREATE PULSAR STREAM")
    p.add_argument("--auth-password-strength-regex", default=".+",
                   help="regex newly set passwords must match")
    p.add_argument("--auth-password-permit-null",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="allow users without a password")
    # --- round-5 flag surface (reference: src/flags/*.cpp) ------------------
    p.add_argument("--storage-property-store-compression-enabled",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="zlib-compress large property blobs (reference: "
                        "storage/v2/property_store.hpp:38)")
    p.add_argument("--storage-property-store-compression-level",
                   choices=["low", "mid", "high"], default="mid",
                   help="zlib level: low=1 mid=6 high=9")
    p.add_argument("--license-key", default="",
                   help="enterprise license key (utils/license.py)")
    p.add_argument("--organization-name", default="",
                   help="organization the license key was issued for")
    p.add_argument("--data-recovery-on-startup", default=None,
                   action=argparse.BooleanOptionalAction,
                   help="recover snapshot+WAL on startup (newer alias of "
                        "--storage-recover-on-startup; wins when both set)")
    p.add_argument("--log-query-plan",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="log every prepared query's plan at INFO")
    p.add_argument("--log-min-duration-ms", type=int, default=0,
                   help="log queries slower than this (0 = off)")
    p.add_argument("--metrics-format", choices=["JSON", "PROMETHEUS"],
                   default="JSON",
                   help="default metrics HTTP payload format")
    p.add_argument("--schema-info-enabled",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="collect + serve SHOW SCHEMA INFO")
    p.add_argument("--storage-gc-aggressive",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="run GC after every commit, not just the timer")
    p.add_argument("--timezone", default=None,
                   help="IANA timezone for temporal functions "
                        "(sets TZ process-wide)")
    p.add_argument("--strict-flag-check",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="unknown flags abort startup (off: warn + ignore, "
                        "for config files shared across versions)")
    p.add_argument("--storage-enable-schema-metadata",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="alias of --schema-info-enabled (reference name)")
    p.add_argument("--storage-enable-edges-metadata",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="include per-edge-type counts in STORAGE INFO")
    p.add_argument("--storage-parallel-schema-recovery",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="decode snapshot chunks on the worker pool")
    p.add_argument("--storage-allow-recovery-failure",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="start with partial/empty data when durability "
                        "files are damaged instead of refusing to boot")
    p.add_argument("--storage-snapshot-interval", default=None,
                   help="snapshot cadence in seconds (reference also "
                        "accepts cron syntax; numeric-only here, alias "
                        "of --storage-snapshot-interval-sec)")
    p.add_argument("--coordinator-hostname", default=None,
                   help="hostname this coordinator advertises to peers "
                        "and in ROUTE responses")
    p.add_argument("--experimental-enabled", default="",
                   help="comma-separated experimental feature gates "
                        "(recorded in runtime settings; all features in "
                        "this build are stable, so gates are advisory)")
    p.add_argument("--experimental-config", default="",
                   help="JSON config for experimental features")
    p.add_argument("--query-callable-mappings-path", default=None,
                   help="JSON {alias: procedure} mapping file so "
                        "Neo4j-style CALL names resolve locally")
    if argv is None:
        import sys as _sys
        argv = _sys.argv[1:]
    known, unknown = p.parse_known_args(argv)
    if unknown:
        if known.strict_flag_check:
            p.error(f"unrecognized arguments: {' '.join(unknown)} "
                    "(use --no-strict-flag-check to ignore)")
        import logging as _logging
        _logging.getLogger(__name__).warning(
            "ignoring unknown flags (--no-strict-flag-check): %s", unknown)
    return known


def build_database(args) -> InterpreterContext:
    if args.timezone:
        # process-wide, as the reference's --timezone configures the
        # server-side zone used by temporal functions
        _os.environ["TZ"] = args.timezone
        import time as _time
        if hasattr(_time, "tzset"):
            _time.tzset()
    if args.storage_property_store_compression_enabled:
        from .storage.property_store import COMPRESSION
        COMPRESSION["enabled"] = True
        COMPRESSION["level"] = {"low": 1, "mid": 6, "high": 9}[
            args.storage_property_store_compression_level]
    if args.storage_snapshot_interval:
        try:
            args.storage_snapshot_interval_sec = int(
                args.storage_snapshot_interval)
        except ValueError:
            logging.warning("--storage-snapshot-interval: only numeric "
                            "seconds are supported; ignoring %r",
                            args.storage_snapshot_interval)
    recover_flag = args.storage_recover_on_startup
    if args.data_recovery_on_startup is not None:
        recover_flag = args.data_recovery_on_startup
    args.storage_recover_on_startup = recover_flag
    storage_config = StorageConfig(
        storage_mode=StorageMode(args.storage_mode),
        isolation_level=IsolationLevel(args.isolation_level),
        durability_dir=args.data_directory,
        wal_enabled=bool(args.storage_wal_enabled and args.data_directory),
        wal_segment_size=args.storage_wal_file_size_kib * 1024,
        snapshot_on_exit=args.storage_snapshot_on_exit,
        properties_on_edges=args.storage_properties_on_edges,
        snapshot_retention_count=args.storage_snapshot_retention_count,
        delta_on_identical_property_update=(
            args.storage_delta_on_identical_property_update),
        automatic_label_index=(
            args.storage_automatic_label_index_creation_enabled),
        automatic_edge_type_index=(
            args.storage_automatic_edge_type_index_creation_enabled),
        gc_aggressive=args.storage_gc_aggressive,
        allow_recovery_failure=args.storage_allow_recovery_failure,
    )
    if not args.storage_parallel_schema_recovery:
        from .storage.durability import snapshot as _snap_mod
        _snap_mod.POOL_WORKERS = 1
    if args.aws_access_key:
        _os.environ.setdefault("AWS_ACCESS_KEY_ID", args.aws_access_key)
    if args.aws_secret_key:
        _os.environ.setdefault("AWS_SECRET_ACCESS_KEY",
                               args.aws_secret_key)
    if args.aws_region:
        _os.environ.setdefault("AWS_DEFAULT_REGION", args.aws_region)
    if args.aws_endpoint_url:
        _os.environ.setdefault("AWS_ENDPOINT_URL", args.aws_endpoint_url)
    if not args.storage_parallel_snapshot_creation:
        from .storage.durability import snapshot as _snap
        _snap.POOL_WORKERS = 1
    timeout_sec = (args.query_execution_timeout_sec
                   if args.query_execution_timeout_sec is not None
                   else args.execution_timeout_sec)
    interp_config = {
        "execution_timeout_sec": timeout_sec,
        "allow_load_csv": args.allow_load_csv,
        "kafka_bootstrap_servers": args.kafka_bootstrap_servers,
        "pulsar_service_url": args.pulsar_service_url,
        "auth_password_strength_regex": args.auth_password_strength_regex,
        "auth_password_permit_null": args.auth_password_permit_null,
        "advertised_address": (args.bolt_advertised_address
                               or f"localhost:{args.bolt_port}"),
        "log_failed_queries": args.log_failed_queries,
        "debug_query_plans": args.debug_query_plans,
        "bolt_server_name": args.bolt_server_name_for_init,
        "hops_limit_partial_results": args.hops_limit_partial_results,
        "log_query_plan": args.log_query_plan,
        "log_min_duration_ms": args.log_min_duration_ms,
        "schema_info_enabled": (args.schema_info_enabled
                                and args.storage_enable_schema_metadata),
        "storage_enable_edges_metadata":
            args.storage_enable_edges_metadata,
        "metrics_format": args.metrics_format,
        "experimental_enabled": args.experimental_enabled,
        "experimental_config": args.experimental_config,
        "coordinator_hostname": args.coordinator_hostname,
    }
    # multi-tenancy: every server runs behind a DbmsHandler; the default
    # database recovers from (and persists to) the root data directory
    from .dbms.dbms import DbmsHandler
    dbms = DbmsHandler(storage_config, interp_config,
                       recover_on_startup=args.storage_recover_on_startup)
    ictx = dbms.default()
    storage = ictx.storage

    if args.memory_limit:
        from .utils.memory_tracker import GLOBAL
        GLOBAL.limit = args.memory_limit * 1024 * 1024

    # warm the native CSR builder at startup so the first analytics query
    # doesn't pay the compile
    from .ops.native import get_lib
    get_lib()

    if args.audit_enabled and args.data_directory:
        from .observability.audit import AuditLog
        import os
        ictx.audit = AuditLog(
            os.path.join(args.data_directory, "audit", "audit.log"),
            install_sigusr2=True)
        logging.info("audit log enabled")

    # background maintenance (reference: periodic snapshots memgraph.cpp:588,
    # GC cycle flags)
    import threading

    def _periodic(interval, fn, name):
        def loop():
            import time as _t
            while True:
                _t.sleep(interval)
                try:
                    fn()
                except Exception:
                    logging.exception("%s failed", name)
        t = threading.Thread(target=loop, daemon=True, name=name)
        t.start()

    if args.storage_snapshot_interval_sec and args.data_directory:
        from .storage.durability.snapshot import create_snapshot
        _periodic(args.storage_snapshot_interval_sec,
                  lambda: create_snapshot(storage), "periodic-snapshot")
        logging.info("periodic snapshots every %ds",
                     args.storage_snapshot_interval_sec)
    if args.memory_warning_threshold:
        def _warn_low_memory():
            try:
                with open("/proc/meminfo") as f:
                    for line in f:
                        if line.startswith("MemAvailable:"):
                            avail_mb = int(line.split()[1]) // 1024
                            if avail_mb < args.memory_warning_threshold:
                                logging.warning(
                                    "available system memory low: %d MB "
                                    "(threshold %d MB)", avail_mb,
                                    args.memory_warning_threshold)
                            break
            except OSError:
                pass
        _periodic(60, _warn_low_memory, "memory watcher")
    if args.storage_gc_cycle_sec:
        _periodic(args.storage_gc_cycle_sec, storage.collect_garbage,
                  "periodic-gc")

    # trigger store wiring (registers its commit hook)
    from .query.triggers import global_trigger_store
    global_trigger_store(ictx)

    if args.license_key or args.organization_name:
        from .utils.license import LICENSE_SETTING, ORGANIZATION_SETTING
        from .storage.kvstore import ensure_settings
        settings = ensure_settings(ictx)
        if args.license_key:
            settings.set(LICENSE_SETTING, args.license_key)
        if args.organization_name:
            settings.set(ORGANIZATION_SETTING, args.organization_name)
        logging.info("license configured from flags")

    if args.query_callable_mappings_path:
        from .query.procedures.registry import global_registry as _greg
        try:
            n_aliases = _greg.load_callable_mappings(
                args.query_callable_mappings_path)
            logging.info("loaded %d callable mappings", n_aliases)
        except (OSError, ValueError) as e:
            logging.error("callable mappings failed to load: %s", e)

    if args.query_modules_directory:
        from .query.procedures.registry import global_registry
        loaded = global_registry.load_directory(args.query_modules_directory)
        logging.info("loaded query modules: %s", loaded)

    if args.coordinator_id:
        from .coordination.coordinator import CoordinatorInstance
        peers = {}
        # peer format: id=host:raftport[@boltport] — the optional bolt
        # port lets every coordinator advertise ALL coordinators in the
        # ROUTE role, so drivers survive losing the one they bootstrapped
        # from (reference: coordinator_instance.cpp routing table)
        # own entry uses the DIALABLE advertised address, not the bind
        # address (0.0.0.0 would be served verbatim to remote drivers);
        # --coordinator-hostname overrides the host part (reference:
        # coordination flag of the same name)
        advertised = ictx.config["advertised_address"]
        if args.coordinator_hostname:
            advertised = (f"{args.coordinator_hostname}:"
                          f"{advertised.rsplit(':', 1)[-1]}")
        routers = [advertised]
        for part in filter(None, args.coordinator_peers.split(",")):
            pid, _, addr = part.partition("=")
            addr, _, bolt_port = addr.partition("@")
            host, _, port = addr.rpartition(":")
            peers[pid] = (host, int(port))
            if bolt_port:
                routers.append(f"{host}:{int(bolt_port)}")
        ictx.coordinator = CoordinatorInstance(
            args.coordinator_id, args.bolt_address, args.coordinator_port,
            peers, kvstore=getattr(ictx, "kvstore", None),
            routers=routers)
        ictx.coordinator.start()
        logging.info("coordinator %s on raft port %d (%d peers)",
                     args.coordinator_id, args.coordinator_port, len(peers))
    if args.management_port:
        from .coordination.data_instance import DataInstanceManagementServer
        ictx.mgmt_server = DataInstanceManagementServer(
            ictx, args.bolt_address, args.management_port)
        ictx.mgmt_server.start()
        logging.info("management server on port %d", args.management_port)

    # auth store wired BEFORE the init file runs (single source of truth)
    from .auth.module import parse_module_mappings
    auth_modules = parse_module_mappings(args.auth_module_mappings)
    if args.data_directory:
        import os as _os
        _os.makedirs(args.data_directory, exist_ok=True)
        ictx.auth_store = Auth(
            _os.path.join(args.data_directory, "auth.json"),
            module_mappings=auth_modules)
    elif auth_modules:
        # SSO works without durable auth too (module-managed identities)
        ictx.auth_store = Auth(module_mappings=auth_modules)

    for path in (args.init_file, args.init_data_file):
        if path:
            interp = Interpreter(ictx, system=True)
            with open(path) as f:
                for statement in split_statements(f.read()):
                    interp.execute(statement)

    if args.replication_restore_state_on_startup:
        from .replication.main_role import ReplicationState
        ictx.replication = ReplicationState(ictx.storage, ictx=ictx)
        ictx.replication.restore_state()
    return ictx


def split_statements(text: str) -> list[str]:
    """Split a cypherl stream on top-level ';' (string/comment-aware)."""
    from .query.frontend.lexer import tokenize
    out = []
    start = 0
    for tok in tokenize(text):
        if tok.type == ";":
            stmt = text[start:tok.pos].strip()
            if stmt:
                out.append(stmt)
            start = tok.pos + 1
    tail = text[start:].strip()
    if tail:
        out.append(tail)
    return out


async def serve(args, ictx) -> None:
    auth = getattr(ictx, "auth_store", None)
    if auth is None:
        auth = Auth(None)
        ictx.auth_store = auth

    ssl_ctx = None
    if args.bolt_cert_file and args.bolt_key_file:
        from .utils.tls import server_context
        ssl_ctx = server_context(args.bolt_cert_file, args.bolt_key_file)
    server = BoltServer(ictx, args.bolt_address, args.bolt_port, auth,
                        ssl_context=ssl_ctx,
                        workers=args.bolt_num_workers or None)
    await server.start()
    logging.info("Bolt server listening on %s:%d%s", args.bolt_address,
                 args.bolt_port, " (TLS)" if ssl_ctx else "")

    telemetry = None
    if args.telemetry_enabled:
        from .observability.telemetry import (Telemetry,
                                              attach_query_collectors,
                                              attach_storage_collectors)
        telemetry = Telemetry(args.telemetry_endpoint,
                              kvstore=getattr(ictx, "kvstore", None))
        attach_storage_collectors(telemetry, ictx)
        attach_query_collectors(telemetry)
        telemetry.start()
        logging.info("telemetry enabled -> %s", args.telemetry_endpoint)

    monitoring = None
    if args.metrics_port:
        from .observability.http import start_monitoring_server
        monitoring = await start_monitoring_server(
            args.metrics_address or args.monitoring_address
            or args.bolt_address, args.metrics_port, ictx)
        logging.info("metrics endpoint on :%d", args.metrics_port)

    ws_monitoring = None
    if args.monitoring_port:
        from .observability.metrics import global_metrics
        from .observability.monitoring_ws import MonitoringServer
        ws_monitoring = MonitoringServer(
            args.monitoring_address or args.bolt_address,
            args.monitoring_port, auth=auth, metrics=global_metrics)
        ws_monitoring.start()
        logging.info("websocket monitoring on :%d", args.monitoring_port)

    stop = asyncio.Event()

    def shutdown(*_):
        stop.set()

    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, shutdown)
    await stop.wait()

    logging.info("shutting down ...")
    if telemetry is not None:
        telemetry.stop()
    server.stop()
    if monitoring is not None:
        monitoring.close()
    if ws_monitoring is not None:
        ws_monitoring.stop()
    if args.storage_snapshot_on_exit and args.data_directory:
        from .storage.durability.snapshot import create_snapshot
        create_snapshot(ictx.storage)
        logging.info("exit snapshot written")


def main(argv=None) -> int:
    args = build_config(argv)
    handlers = None
    if args.log_file:
        handlers = [logging.FileHandler(args.log_file)]
        if args.also_log_to_stderr:
            handlers.append(logging.StreamHandler())
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        handlers=handlers)
    if args.storage_snapshot_thread_count:
        from .storage.durability import snapshot as _snap
        _snap.POOL_WORKERS = args.storage_snapshot_thread_count
    # honor JAX_PLATFORMS even when a site hook pre-initialized jax with a
    # different backend (e.g. the axon TPU plugin)
    from .utils.jax_cache import honor_jax_platforms_env
    honor_jax_platforms_env()
    if bool(args.bolt_cert_file) != bool(args.bolt_key_file):
        logging.error("--bolt-cert-file and --bolt-key-file must be "
                      "given together")
        return 1
    if bool(args.cluster_cert_file) != bool(args.cluster_key_file):
        logging.error("--cluster-cert-file and --cluster-key-file must be "
                      "given together")
        return 1
    if args.cluster_cert_file and args.cluster_key_file:
        from .utils.tls import set_cluster_tls
        set_cluster_tls(args.cluster_cert_file, args.cluster_key_file,
                        args.cluster_ca_file)
        logging.info("intra-cluster TLS enabled")
    ictx = build_database(args)
    try:
        asyncio.run(serve(args, ictx))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
