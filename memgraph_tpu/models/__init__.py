"""Model families built on the graph framework.

TPU-native counterparts of the reference's ML-flavored MAGE modules
(/root/reference/mage/python/): node2vec embeddings (node2vec.py), with
link-prediction / node-classification heads reusing the same embedding
machinery. Training is ordinary JAX: jitted steps, optax optimizers,
shardable over a (data, model) mesh.
"""

from .node2vec import Node2Vec, Node2VecConfig

__all__ = ["Node2Vec", "Node2VecConfig"]
