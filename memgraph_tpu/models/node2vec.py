"""node2vec: biased random walks + skip-gram with negative sampling.

Counterpart of /root/reference/mage/python/node2vec.py (gensim Word2Vec on
host walks) and node2vec_online — redesigned for TPU: walks are sampled on
device (ops/walks.py), and the skip-gram objective trains embedding tables
with a jitted optax step. The tables shard over a (data, model) mesh:
batch across `data`, embedding dimension across `model` — the layout
`dryrun_multichip` validates.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..ops.csr import DeviceGraph
from ..ops.walks import random_walks, walks_to_skipgram_pairs


@dataclass
class Node2VecConfig:
    embedding_dim: int = 128
    walk_length: int = 20
    walks_per_node: int = 4
    window: int = 5
    negatives: int = 5
    p: float = 1.0
    q: float = 1.0
    learning_rate: float = 0.01
    epochs: int = 3
    batch_size: int = 8192
    seed: int = 0


def init_params(n_nodes_pad: int, dim: int, key):
    k1, k2 = jax.random.split(key)
    scale = 1.0 / jnp.sqrt(dim)
    return {
        "in": jax.random.normal(k1, (n_nodes_pad, dim), jnp.float32) * scale,
        "out": jax.random.normal(k2, (n_nodes_pad, dim), jnp.float32) * scale,
    }


def sgns_loss(params, centers, contexts, negatives):
    """Skip-gram negative-sampling loss; -1 ids mask out padding pairs."""
    mask = ((centers >= 0) & (contexts >= 0)).astype(jnp.float32)
    c = jnp.maximum(centers, 0)
    t = jnp.maximum(contexts, 0)
    e_c = params["in"][c]                        # (B, D)
    e_t = params["out"][t]                       # (B, D)
    e_n = params["out"][negatives]               # (B, K, D)
    pos = jnp.sum(e_c * e_t, axis=-1)
    neg = jnp.einsum("bd,bkd->bk", e_c, e_n)
    pos_loss = jax.nn.softplus(-pos)
    neg_loss = jnp.sum(jax.nn.softplus(neg), axis=-1)
    return jnp.sum((pos_loss + neg_loss) * mask) / jnp.maximum(mask.sum(), 1.0)


@partial(jax.jit, static_argnames=("optimizer",))
def train_step(params, opt_state, centers, contexts, negatives, optimizer):
    loss, grads = jax.value_and_grad(sgns_loss)(params, centers, contexts,
                                                negatives)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


class Node2Vec:
    """End-to-end node2vec trainer over a DeviceGraph."""

    def __init__(self, config: Node2VecConfig | None = None):
        self.config = config or Node2VecConfig()

    def fit(self, graph: DeviceGraph, verbose: bool = False) -> np.ndarray:
        cfg = self.config
        key = jax.random.PRNGKey(cfg.seed)
        key, pk = jax.random.split(key)
        params = init_params(graph.n_pad, cfg.embedding_dim, pk)
        optimizer = optax.adam(cfg.learning_rate)
        opt_state = optimizer.init(params)

        starts = jnp.tile(jnp.arange(graph.n_nodes, dtype=jnp.int32),
                          cfg.walks_per_node)
        for epoch in range(cfg.epochs):
            key, wk, sk = jax.random.split(key, 3)
            walks = random_walks(graph, starts, cfg.walk_length, key=wk,
                                 p=cfg.p, q=cfg.q)
            pairs = walks_to_skipgram_pairs(walks, cfg.window)
            pairs = jax.random.permutation(sk, pairs, axis=0)
            n_pairs = pairs.shape[0]
            B = cfg.batch_size
            n_batches = max(n_pairs // B, 1)
            for b in range(n_batches):
                batch = pairs[b * B:(b + 1) * B]
                if batch.shape[0] < B:  # keep shapes static for jit
                    pad = jnp.full((B - batch.shape[0], 2), -1, batch.dtype)
                    batch = jnp.concatenate([batch, pad])
                key, nk = jax.random.split(key)
                negs = jax.random.randint(nk, (B, cfg.negatives), 0,
                                          graph.n_nodes)
                params, opt_state, loss = train_step(
                    params, opt_state, batch[:, 0], batch[:, 1], negs,
                    optimizer)
            if verbose:
                print(f"epoch {epoch}: loss={float(loss):.4f}")
        return np.asarray(params["in"][:graph.n_nodes])


def build_sharded_train_step(mesh, optimizer):
    """Jitted train step with explicit shardings for dryrun_multichip:
    embedding tables sharded over the `model` axis (tensor parallel),
    batch over `data` (data parallel)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    param_sharding = {"in": NamedSharding(mesh, P(None, "model")),
                      "out": NamedSharding(mesh, P(None, "model"))}
    batch_sharding = NamedSharding(mesh, P("data"))

    @partial(jax.jit, static_argnames=())
    def step(params, opt_state, centers, contexts, negatives):
        loss, grads = jax.value_and_grad(sgns_loss)(params, centers,
                                                    contexts, negatives)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step, param_sharding, batch_sharding
