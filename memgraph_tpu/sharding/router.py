"""ShardedClient: the routed query path over a ShardPlane.

Three request shapes, the same trio the reference's distributed
execution layer distinguishes:

* **single-shard** (a routing key is known): point reads and writes go
  straight to the owner; write acks must carry the grant epoch of the
  map the client routed with, and a stale-map bounce (typed
  ``StaleShardEpoch``) refreshes the map — EPOCH-MONOTONICALLY — and
  retries against the new owner under the shared RetryPolicy.
* **scatter-gather** (no key): the query fans out to every shard and
  the gather side merges — partial-aggregate combination for
  count/sum/min/max (grouped or global), ORDER BY re-sort and global
  LIMIT re-application for plain row results. Unsupported shapes
  (DISTINCT aggregates, avg, SKIP, aggregate arithmetic) raise a loud
  typed ``MergeError`` instead of quietly returning wrong answers.
* **cross-shard writes**: grouped per shard and run through 2PC —
  prepare (held transaction + durable journal) on every touched shard,
  then commit; any prepare failure or worker death aborts every
  prepared participant (presumed abort), while a worker death AFTER
  the commit decision re-drives the decision against the respawned
  worker (its journal replays the vote — no half-committed txn).
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid

from ..exceptions import (MemgraphTpuError, ShardError, StaleShardEpoch,
                          WorkerCrashedError, WriteInDoubtError)
from ..observability.metrics import global_metrics
from ..query.frontend import ast as A
from ..query.frontend.parser import parse
from ..utils.retry import RetryPolicy

__all__ = ["MergeError", "MergePlan", "ShardedClient", "plan_merge"]

#: aggregate combiners the gather side knows how to merge from
#: per-shard partials (avg/collect/percentiles need a rewrite the
#: router does not do — they fail loudly instead)
_MERGEABLE = {"count": lambda vals: sum(v for v in vals if v is not None),
              "sum": lambda vals: _sum_sparse(vals),
              "min": lambda vals: _pick(vals, min),
              "max": lambda vals: _pick(vals, max)}


def _sum_sparse(vals):
    vals = [v for v in vals if v is not None]
    return sum(vals) if vals else None


def _pick(vals, fn):
    vals = [v for v in vals if v is not None]
    return fn(vals) if vals else None


class MergeError(ShardError):
    """The query's result shape cannot be merged on the gather side;
    the caller must route it single-shard or rewrite it."""


class MergePlan:
    """How to combine per-shard result sets into one."""

    __slots__ = ("aggregate", "columns", "group_idx", "agg_specs",
                 "order", "limit", "distinct")

    def __init__(self, aggregate, columns, group_idx, agg_specs, order,
                 limit, distinct) -> None:
        self.aggregate = aggregate      # bool
        self.columns = columns          # output column names
        self.group_idx = group_idx      # indexes of group-key columns
        self.agg_specs = agg_specs      # {col_idx: combiner-name}
        self.order = order              # [(col_idx, ascending)]
        self.limit = limit              # int | None (global)
        self.distinct = distinct


def _expr_text(expr) -> str | None:
    """Tiny unparse for the sort-key shapes the merge supports."""
    if isinstance(expr, A.Identifier):
        return expr.name
    if isinstance(expr, A.PropertyLookup) and \
            isinstance(expr.expr, A.Identifier):
        return f"{expr.expr.name}.{expr.prop}"
    return None


def _agg_name(expr) -> str | None:
    """The combiner name when ``expr`` IS a bare mergeable aggregate."""
    if isinstance(expr, A.CountStar):
        return "count"
    if isinstance(expr, A.FunctionCall) and expr.name in _MERGEABLE \
            and not expr.distinct:
        return expr.name
    return None


def _contains_aggregate(expr) -> bool:
    if isinstance(expr, (A.CountStar,)):
        return True
    if isinstance(expr, A.FunctionCall):
        if expr.name in ("count", "sum", "min", "max", "avg",
                         "collect", "stdev", "percentilecont",
                         "percentiledisc"):
            return True
        return any(_contains_aggregate(a) for a in expr.args)
    for attr in ("expr", "left", "right", "index", "lo", "hi"):
        sub = getattr(expr, attr, None)
        if isinstance(sub, A.Expr) and _contains_aggregate(sub):
            return True
    items = getattr(expr, "items", None)
    if isinstance(items, list) and \
            any(isinstance(i, A.Expr) and _contains_aggregate(i)
                for i in items):
        return True
    return False


def _const_int(expr, params) -> int:
    if isinstance(expr, A.Literal) and isinstance(expr.value, int):
        return int(expr.value)
    if isinstance(expr, A.Parameter):
        value = (params or {}).get(expr.name)
        if isinstance(value, int):
            return value
    raise MergeError("scatter-gather LIMIT/SKIP must be an integer "
                     "literal or parameter")


def plan_merge(query: str, params: dict | None = None) -> MergePlan:
    """Derive the gather-side merge plan from the query's RETURN shape.

    Raises MergeError for shapes the merge cannot reproduce exactly —
    the loud-refusal contract: a scatter-gather must never return an
    answer a single store would not have."""
    node = parse(query)
    if not isinstance(node, A.CypherQuery):
        raise MergeError("only Cypher queries scatter-gather")
    if node.unions:
        raise MergeError("UNION queries do not scatter-gather")
    clauses = node.query.clauses
    for cl in clauses:
        if isinstance(cl, A.With) and any(
                _contains_aggregate(it[0]) for it in cl.body.items):
            raise MergeError("aggregating WITH inside a scatter-gather "
                             "would combine per-shard partials wrongly")
    ret = clauses[-1] if clauses and isinstance(clauses[-1], A.Return) \
        else None
    if ret is None:
        raise MergeError("scatter-gather needs a final RETURN")
    body = ret.body
    if body.star:
        raise MergeError("RETURN * does not scatter-gather (column "
                         "set is shard-dependent)")
    if body.skip is not None:
        raise MergeError("SKIP does not scatter-gather (per-shard SKIP "
                         "drops globally-needed rows); paginate on the "
                         "gather side")

    columns, agg_specs, group_idx = [], {}, []
    any_agg = False
    for idx, (expr, alias, text) in enumerate(body.items):
        columns.append(alias or text or f"col{idx}")
        name = _agg_name(expr)
        if name is not None:
            agg_specs[idx] = name
            any_agg = True
            continue
        if _contains_aggregate(expr):
            raise MergeError(
                "only bare count/sum/min/max aggregates merge across "
                "shards (avg, DISTINCT aggregates and aggregate "
                "arithmetic need a rewrite)")
        group_idx.append(idx)

    order = []
    for item in body.order_by:
        text = _expr_text(item.expr)
        if text is None or text not in columns:
            raise MergeError("ORDER BY keys must reference returned "
                            "columns for a scatter-gather merge")
        order.append((columns.index(text), item.ascending))
    limit = _const_int(body.limit, params) \
        if body.limit is not None else None
    if any_agg and limit is not None:
        raise MergeError("LIMIT over a grouped scatter-gather "
                         "aggregate would truncate per-shard partial "
                         "groups; drop the LIMIT or route single-shard")
    return MergePlan(any_agg, columns, group_idx, agg_specs, order,
                     limit, body.distinct)


def merge_rows(plan: MergePlan, shard_rows: list[list]) -> list:
    """Combine per-shard row sets per the plan."""
    if plan.aggregate:
        groups: dict = {}
        order_keys = []
        for rows in shard_rows:
            for row in rows:
                key = tuple(_hashable(row[i]) for i in plan.group_idx)
                if key not in groups:
                    order_keys.append(key)
                    groups[key] = {i: [] for i in plan.agg_specs}
                    groups[key]["_row"] = list(row)
                for i in plan.agg_specs:
                    groups[key][i].append(row[i])
        merged = []
        for key in order_keys:
            bucket = groups[key]
            row = bucket["_row"]
            for i, name in plan.agg_specs.items():
                row[i] = _MERGEABLE[name](bucket[i])
            merged.append(row)
    else:
        merged = [row for rows in shard_rows for row in rows]
        if plan.distinct:
            seen, unique = set(), []
            for row in merged:
                key = tuple(_hashable(v) for v in row)
                if key not in seen:
                    seen.add(key)
                    unique.append(row)
            merged = unique
    for idx, ascending in reversed(plan.order):
        merged.sort(key=lambda r: _sort_key(r[idx]),
                    reverse=not ascending)
    if plan.limit is not None:
        merged = merged[:plan.limit]
    return merged


def _hashable(value):
    if isinstance(value, list):
        return tuple(_hashable(v) for v in value)
    if isinstance(value, dict):
        return tuple(sorted((k, _hashable(v)) for k, v in value.items()))
    return value


def _sort_key(value):
    """Total order mirroring Cypher orderability: NULL sorts last
    ascending, and mixed-type columns group by a type rank (maps <
    lists < strings < booleans < numbers) instead of letting list.sort
    raise TypeError on a cross-shard heterogeneous column."""
    if value is None:
        return (1, 0, 0)
    if isinstance(value, bool):            # before int: bool IS an int
        return (0, 3, value)
    if isinstance(value, (int, float)):
        return (0, 4, value)
    if isinstance(value, str):
        return (0, 2, value)
    if isinstance(value, list):
        return (0, 1, tuple(_sort_key(v) for v in value))
    if isinstance(value, dict):
        return (0, 0, tuple(sorted((k, _sort_key(v))
                                   for k, v in value.items())))
    return (0, 5, str(value))


class ShardedClient:
    """The routed client over one ShardPlane (the in-process
    counterpart of RoutedClient's coordinator-driven routing)."""

    def __init__(self, plane, retry: RetryPolicy | None = None) -> None:
        self.plane = plane
        self.retry = retry or RetryPolicy(base_delay=0.05,
                                          max_delay=1.0, max_retries=8)
        self.map = plane.map
        self._txn_seq = itertools.count()

    # -- shard map -----------------------------------------------------------

    def refresh_map(self) -> bool:
        """Adopt the placement authority's current map — only if it is
        at least as new as what we hold (epoch-monotonic: a stale
        authority read can never steer writes backwards)."""
        fresh = self.plane.placement.current()
        if fresh.epoch < self.map.epoch:
            return False
        self.map = fresh
        return True

    def shard_for(self, key) -> int:
        return self.map.shard_for(key)

    # -- single-shard --------------------------------------------------------

    def read(self, query: str, params: dict | None = None, key=None):
        """Point read (key given) or scatter-gather read (key=None).
        Returns (columns, rows)."""
        if key is None:
            return self.scatter_read(query, params)
        last: Exception | None = None
        t0 = time.perf_counter()
        for _attempt in self.retry.attempts():
            shard = self.map.shard_for(key)
            try:
                _status, body = self.plane.request(
                    shard, "read", {"query": query,
                                    "params": params or {},
                                    "epoch": self.map.epoch})
                self._account(query, t0, rows=len(body["rows"]))
                return body["columns"], body["rows"]
            except StaleShardEpoch as e:
                last = e
                global_metrics.increment(
                    "shard.stale_epoch_bounces_total")
                self.refresh_map()
            except WorkerCrashedError as e:
                last = e
                self.refresh_map()
        self._account(query, t0, rows=0, error=True)
        raise MemgraphTpuError(
            f"sharded read failed after "
            f"{self.retry.max_retries + 1} attempts: {last}") from last

    def _account(self, query: str, t0: float, rows: int,
                 error: bool = False) -> None:
        """Fork-side stats die with the worker process; the PARENT
        registry is the authoritative fingerprint table (the same
        contract as mp_executor), so routed queries account here."""
        from ..observability import trace as mgtrace
        from ..observability.stats import global_query_stats
        global_query_stats.record_text(
            query, time.perf_counter() - t0, rows=rows, error=error,
            trace_id=mgtrace.current_trace_id())

    def write(self, query: str, params: dict | None = None, *, key):
        """Single-shard write routed by key. The ack is only accepted
        at the routing epoch (the worker enforces equality), and a
        stale-map bounce refreshes + retries — the fencing contract
        under live shard moves. Returns (columns, rows, ack)."""
        last: Exception | None = None
        t0 = time.perf_counter()
        for _attempt in self.retry.attempts():
            shard = self.map.shard_for(key)
            epoch = self.map.epoch
            try:
                _status, body = self.plane.request(
                    shard, "write", {"query": query,
                                     "params": params or {},
                                     "epoch": epoch})
                self._account(query, t0, rows=len(body["rows"]))
                return body["columns"], body["rows"], \
                    {"shard": body["shard"], "epoch": body["epoch"],
                     "owner": body.get("owner")}
            except StaleShardEpoch as e:
                last = e
                global_metrics.increment(
                    "shard.stale_epoch_bounces_total")
                self.refresh_map()
            except WorkerCrashedError as e:
                if e.in_doubt:
                    # the owner died AFTER the write was on the wire:
                    # it may be in the shard's WAL already, so a blind
                    # re-send can double-apply a non-idempotent write.
                    # Surface the doubt typed instead of retrying.
                    self._account(query, t0, rows=0, error=True)
                    global_metrics.increment(
                        "shard.write_in_doubt_total")
                    raise WriteInDoubtError(
                        f"sharded write to shard {shard} is in doubt "
                        f"(owner died mid-request): {e}") from e
                last = e
                self.refresh_map()
        self._account(query, t0, rows=0, error=True)
        raise MemgraphTpuError(
            f"sharded write failed after "
            f"{self.retry.max_retries + 1} attempts: {last}") from last

    def ddl(self, query: str) -> None:
        """Broadcast schema DDL (CREATE INDEX / constraints) to EVERY
        shard — the schema is global even though the data is not."""
        for shard in range(self.map.n_shards):
            last: Exception | None = None
            for _attempt in self.retry.attempts():
                try:
                    self.plane.request(
                        shard, "write", {"query": query, "params": {},
                                         "epoch": self.map.epoch})
                    last = None
                    break
                except (StaleShardEpoch, WorkerCrashedError) as e:
                    last = e
                    self.refresh_map()
            if last is not None:
                raise MemgraphTpuError(
                    f"DDL broadcast to shard {shard} failed: "
                    f"{last}") from last

    # -- scatter-gather ------------------------------------------------------

    def scatter_read(self, query: str, params: dict | None = None):
        """Fan the read out to every shard and merge per the plan."""
        plan = plan_merge(query, params)
        global_metrics.increment("shard.scatter_gather_total")
        results: dict[int, list] = {}
        errors: dict[int, Exception] = {}

        def one(shard: int) -> None:
            try:
                for _attempt in self.retry.attempts():
                    try:
                        _status, body = self.plane.request(
                            shard, "read", {"query": query,
                                            "params": params or {},
                                            "epoch": self.map.epoch})
                        results[shard] = body["rows"]
                        return
                    except (StaleShardEpoch, WorkerCrashedError):
                        self.refresh_map()
                raise MemgraphTpuError(
                    f"shard {shard} kept bouncing the scatter read")
            except Exception as e:  # noqa: BLE001 — re-raised below
                errors[shard] = e

        threads = [threading.Thread(target=one, args=(sid,))
                   for sid in range(self.map.n_shards)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            shard, err = sorted(errors.items())[0]
            raise MemgraphTpuError(
                f"scatter-gather failed on shard {shard}: "
                f"{err}") from err
        rows = merge_rows(plan, [results[s]
                                 for s in sorted(results)])
        return plan.columns, rows

    # -- cross-shard 2PC -----------------------------------------------------

    def write_multi(self, statements) -> dict:
        """Atomic cross-shard write: ``statements`` is a list of
        (key, query, params). Statements group per owning shard and run
        through 2PC. Returns {"shards": [...], "epoch": e}.

        Presumed abort: any prepare failure (vote no, fencing bounce,
        worker death) aborts every prepared participant. After the
        commit decision, a dead participant is re-driven — its durable
        pending journal replays the vote on the recovered store."""
        global_metrics.increment("shard.twopc_total")
        by_shard: dict[int, list] = {}
        for key, query, params in statements:
            by_shard.setdefault(self.map.shard_for(key), []).append(
                {"query": query, "params": params or {}})
        txn_id = f"xs-{uuid.uuid4().hex[:12]}-{next(self._txn_seq)}"
        shards = sorted(by_shard)
        prepared: list[int] = []
        try:
            for shard in shards:
                self._prepare_one(shard, txn_id, by_shard[shard])
                prepared.append(shard)
        except Exception:
            global_metrics.increment("shard.twopc_aborts_total")
            # abort every touched shard INCLUDING the one whose prepare
            # failed: it journals before voting, so a crash mid-prepare
            # can leave a pending entry that the abort must prune (else
            # it accumulates, and a late commit for this txn_id would
            # replay writes the client was told aborted)
            for shard in shards[:len(prepared) + 1]:
                self._decide_one(shard, txn_id, "abort",
                                 best_effort=True)
            raise
        for shard in prepared:
            self._decide_one(shard, txn_id, "commit")
        return {"shards": prepared, "epoch": self.map.epoch,
                "txn_id": txn_id}

    def _prepare_one(self, shard: int, txn_id: str,
                     stmts: list) -> None:
        last: Exception | None = None
        for _attempt in self.retry.attempts():
            try:
                status, body = self.plane.request(
                    shard, "prepare", {"txn_id": txn_id,
                                       "statements": stmts,
                                       "epoch": self.map.epoch})
                if body.get("vote") == "yes":
                    return
                raise MemgraphTpuError(
                    f"shard {shard} voted {body!r} on {txn_id}")
            except StaleShardEpoch as e:
                last = e
                global_metrics.increment(
                    "shard.stale_epoch_bounces_total")
                self.refresh_map()
            except WorkerCrashedError as e:
                # nothing was committed: a fresh prepare on the
                # respawned (recovered) worker is safe
                last = e
                self.refresh_map()
        raise MemgraphTpuError(
            f"2PC prepare on shard {shard} failed: {last}") from last

    def _decide_one(self, shard: int, txn_id: str, decision: str,
                    best_effort: bool = False) -> None:
        last: Exception | None = None
        for _attempt in self.retry.attempts():
            try:
                status, body = self.plane.request(
                    shard, "decide", {"txn_id": txn_id,
                                      "decision": decision},
                    raise_typed=False)
                if status == "unknown_txn" and decision == "commit":
                    raise MemgraphTpuError(
                        f"shard {shard} lost prepared txn {txn_id} "
                        "AND its journal — in-doubt")
                return
            except WorkerCrashedError as e:
                # the journal survives the crash: re-drive the decision
                last = e
                self.refresh_map()
                time.sleep(0)   # yield; retry loop backs off
            except MemgraphTpuError as e:
                last = e
                if best_effort:
                    return      # presumed abort needs no ack
                raise
        if best_effort:
            return
        raise MemgraphTpuError(
            f"2PC {decision} on shard {shard} undeliverable: "
            f"{last}") from last
