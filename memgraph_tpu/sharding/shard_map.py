"""Epoch-versioned shard map: shard_id -> owning worker endpoint.

The map is minted by the placement authority (the coordinator's
replicated apply, or its single-process stand-in) and carries ONE
fencing epoch for the whole table: every reassignment bumps it, every
write ack carries the owner's granted epoch, and a client refuses to go
back to an older table — the same monotonic-epoch contract RoutedClient
already enforces for MAIN failover (PR 5), applied per shard.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .partition import shard_for_key

__all__ = ["ShardMap"]


@dataclass(frozen=True)
class ShardMap:
    """Immutable snapshot of shard placement at one fencing epoch."""

    epoch: int
    n_shards: int
    #: shard_id -> owner endpoint name (e.g. "s2g0"; opaque to the map)
    owners: dict = field(default_factory=dict)

    def owner_of(self, shard_id: int) -> str:
        try:
            return self.owners[shard_id]
        except KeyError:
            raise KeyError(f"shard {shard_id} has no owner in the map "
                           f"at epoch {self.epoch}") from None

    def shard_for(self, key) -> int:
        return shard_for_key(key, self.n_shards)

    def to_dict(self) -> dict:
        return {"epoch": self.epoch, "n_shards": self.n_shards,
                "owners": {str(k): v for k, v in self.owners.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "ShardMap":
        return cls(epoch=int(d["epoch"]), n_shards=int(d["n_shards"]),
                   owners={int(k): v
                           for k, v in (d.get("owners") or {}).items()})
