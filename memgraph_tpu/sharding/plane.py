"""ShardPlane: spawn, route to, respawn, kill and MOVE shard workers.

The plane owns the process topology: one long-lived worker per shard
(forked, mp_executor envelope over pipes), a placement authority that
mints the epoch-versioned shard map, and the shard-move protocol
(snapshot ship -> delta catch-up -> epoch bump -> cutover) that lets
the plane rebalance live.

Placement: ``LocalPlacement`` is the single-process stand-in with the
same contract the coordinator provides — every reassignment mints a
strictly-increasing fencing epoch ATOMICALLY with the owner change.
``CoordinatorPlacement`` adapts a real ``CoordinatorInstance`` whose
replicated apply mints the epoch (PR 5 fencing stack), so a stale map
can never route an acked write in the clustered deployment either.

Crash handling: a dead worker is detected on the pipe (EOF/EPIPE),
respawned against the SAME per-shard durability directory — recovery
replays its snapshot + WAL — re-granted at the current epoch, and the
in-flight request fails with the typed retryable ``WorkerCrashedError``
so RetryPolicy-driven callers re-route instead of wedging.
"""

from __future__ import annotations

import logging
import os
import pickle
import signal
import struct
import tempfile
import threading
import time

from ..exceptions import (MemgraphTpuError, StaleShardEpoch,
                          WorkerCrashedError, raise_wire_error)
from ..observability import trace as mgtrace
from ..observability.metrics import global_metrics
from ..server.mp_executor import _recv, _send
from ..utils.locks import tracked_lock
from ..utils.sanitize import shared_field, shared_read, shared_write
from .shard_map import ShardMap

log = logging.getLogger(__name__)

__all__ = ["ShardPlane", "LocalPlacement", "CoordinatorPlacement"]


class LocalPlacement:
    """Single-process placement authority: the mesh-of-1 degeneracy of
    the coordinator's replicated shard map. Epoch minting is atomic
    with the owner change — the same contract the raft apply gives."""

    def __init__(self, n_shards: int) -> None:
        self.n_shards = n_shards
        self._epoch = 0
        self._owners: dict[int, str] = {}
        self._lock = tracked_lock("LocalPlacement._lock")
        shared_field(self, "_epoch", "_owners")

    def assign(self, shard_id: int, owner: str) -> ShardMap:
        with self._lock:
            shared_write(self, "_owners")
            self._epoch += 1
            self._owners[shard_id] = owner
            return ShardMap(epoch=self._epoch, n_shards=self.n_shards,
                            owners=dict(self._owners))

    def current(self) -> ShardMap:
        with self._lock:
            shared_read(self, "_owners")
            return ShardMap(epoch=self._epoch, n_shards=self.n_shards,
                            owners=dict(self._owners))


class CoordinatorPlacement:
    """Placement through a real CoordinatorInstance: assignment is a
    raft proposal and the fencing epoch is minted inside the replicated
    apply — all coordinators agree on (epoch, owner) by log order."""

    def __init__(self, coordinator, n_shards: int) -> None:
        self.coordinator = coordinator
        self.n_shards = n_shards

    def assign(self, shard_id: int, owner: str) -> ShardMap:
        if not self.coordinator.assign_shard(shard_id, owner):
            raise MemgraphTpuError(
                f"shard {shard_id} assignment to {owner!r} did not "
                "commit (no raft quorum?)")
        return self.current()

    def current(self) -> ShardMap:
        view = self.coordinator.shard_map_view()
        return ShardMap(epoch=view["epoch"], n_shards=self.n_shards,
                        owners={int(k): v
                                for k, v in view["owners"].items()})


class _Worker:
    """Parent-side handle: one forked shard worker + its dispatch lock
    (requests to one shard serialize — the single-threaded model-server
    shape; concurrency comes from shard fan-out)."""

    __slots__ = ("name", "shard_id", "generation", "pid", "req_fd",
                 "resp_fd", "lock", "closed")

    def __init__(self, name, shard_id, generation, pid, req_fd, resp_fd):
        self.name = name
        self.shard_id = shard_id
        self.generation = generation
        self.pid = pid
        self.req_fd = req_fd
        self.resp_fd = resp_fd
        self.lock = threading.Lock()
        # set True (under ``lock``) before the fds are closed: a thread
        # that was queued on the lock must NEVER touch the fds after —
        # the numbers may already be reused by a later-spawned worker's
        # pipes, and a stale write would corrupt an unrelated framing
        # stream (reader blocks forever on a garbage length prefix)
        self.closed = False


class ShardPlane:
    """N shard workers + the shard map + the move/respawn machinery."""

    #: delta catch-up rounds before the cutover fence (each round ships
    #: the frames committed during the previous round's apply)
    MOVE_CATCHUP_ROUNDS = 8

    def __init__(self, n_shards: int = 4, base_dir: str | None = None,
                 placement=None) -> None:
        self.n_shards = n_shards
        self._owns_dir = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(prefix="mgshard-")
        self.placement = placement or LocalPlacement(n_shards)
        self._lock = tracked_lock("ShardPlane._lock")
        self._workers: dict[int, _Worker] = {}     # shard -> live owner
        self._generations: dict[int, int] = {}
        self._inflight: dict[int, int] = {}
        self._closed = False
        shared_field(self, "_workers", "_generations", "_inflight",
                     "_closed")
        self.map = ShardMap(epoch=0, n_shards=n_shards, owners={})

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ShardPlane":
        for sid in range(self.n_shards):
            worker = self._spawn(sid, generation=0)
            with self._lock:
                shared_write(self, "_workers")
                self._workers[sid] = worker
                self._generations[sid] = 0
            self.map = self.placement.assign(sid, worker.name)
        self._broadcast_grant()
        return self

    def _spawn(self, shard_id: int, generation: int) -> _Worker:
        name = f"s{shard_id}g{generation}"
        req_r, req_w = os.pipe()
        resp_r, resp_w = os.pipe()
        pid = os.fork()
        if pid == 0:                                  # ---- child ----
            os.close(req_w)
            os.close(resp_r)
            try:
                from .worker import shard_worker_main
                shard_worker_main(shard_id, name, req_r, resp_w,
                                  self.base_dir, generation,
                                  epoch=0)
            finally:
                os._exit(0)
        os.close(req_r)
        os.close(resp_w)
        return _Worker(name, shard_id, generation, pid, req_w, resp_r)

    def close(self) -> None:
        with self._lock:
            shared_write(self, "_workers")
            workers = list(self._workers.values())
            self._workers = {}
            self._closed = True
        for w in workers:
            self._retire(w)
        if self._owns_dir:
            import shutil
            shutil.rmtree(self.base_dir, ignore_errors=True)

    def _retire(self, worker: _Worker) -> None:
        with worker.lock:
            if worker.closed:
                return
            worker.closed = True
            try:
                _send(worker.req_fd, None)
            except OSError:
                pass
            for fd in (worker.req_fd, worker.resp_fd):
                try:
                    os.close(fd)
                except OSError:
                    pass
        try:
            os.waitpid(worker.pid, 0)
        except ChildProcessError:
            pass

    # -- request path --------------------------------------------------------

    def owner(self, shard_id: int) -> _Worker:
        with self._lock:
            shared_read(self, "_workers")
            try:
                return self._workers[shard_id]
            except KeyError:
                raise MemgraphTpuError(
                    f"shard {shard_id} has no live worker "
                    "(plane not started or closed)") from None

    def request(self, shard_id: int, op: str, payload: dict,
                raise_typed: bool = True):
        """One envelope round-trip to a shard's owner. Returns (status,
        payload). Typed raises: a dead worker respawns (with per-shard
        WAL recovery) and raises WorkerCrashedError — ``in_doubt=True``
        when it died after the request was on the wire (writers must
        not blindly re-send), False when it was replaced before the
        send (safe to retry); a stale-epoch/fenced bounce raises
        StaleShardEpoch carrying the owner's epoch unless
        ``raise_typed`` is False."""
        worker = self.owner(shard_id)
        with self._lock:
            shared_write(self, "_inflight")
            depth = self._inflight.get(shard_id, 0) + 1
            self._inflight[shard_id] = depth
        global_metrics.set_gauge(f"shard.queue_depth.{shard_id}",
                                 float(depth))
        global_metrics.increment("shard.requests_total")
        global_metrics.increment(f"shard.ops.{shard_id}")
        t0 = time.perf_counter()
        try:
            with mgtrace.span("shard.request", shard=shard_id, op=op):
                with worker.lock:
                    if worker.closed:
                        # replaced (crash respawn or move cutover)
                        # while we queued on its lock — never touch
                        # the fds; re-route against the fresh owner
                        raise WorkerCrashedError(
                            f"shard {shard_id} worker {worker.name} "
                            "was replaced while this request queued — "
                            "retry")
                    try:
                        _send(worker.req_fd,
                              (op, payload, mgtrace.inject()))
                        out = _recv(worker.resp_fd)
                    except (OSError, EOFError, struct.error,
                            ValueError, pickle.UnpicklingError) as e:
                        # codec failure on the control wire (torn
                        # frame from a dying worker) means the same
                        # thing the pipe errors do: this owner is gone
                        self._handle_dead(shard_id, worker)
                        raise WorkerCrashedError(
                            f"shard {shard_id} worker {worker.name} "
                            f"(pid {worker.pid}) died mid-request; "
                            "respawned with per-shard recovery",
                            in_doubt=True) from e
        finally:
            with self._lock:
                shared_write(self, "_inflight")
                depth = max(self._inflight.get(shard_id, 1) - 1, 0)
                self._inflight[shard_id] = depth
            global_metrics.set_gauge(f"shard.queue_depth.{shard_id}",
                                     float(depth))
            global_metrics.observe(f"shard.op_latency_sec.{shard_id}",
                                   time.perf_counter() - t0)
        status, body, _stats, spans = out
        if spans:
            mgtrace.adopt_spans(spans)
        if status == "err":
            raise_wire_error(body[0], f"shard {shard_id}: {body[1]}")
        if raise_typed and status in ("stale_epoch", "fenced"):
            raise StaleShardEpoch(shard_id, int(body.get("epoch") or 0),
                                  fenced=(status == "fenced"))
        return status, body

    def _handle_dead(self, shard_id: int, worker: _Worker) -> None:
        """Respawn a crashed owner against its durability dir; recovery
        replays the shard's snapshot + WAL, then the worker is
        re-granted at the current epoch. Caller holds ``worker.lock``
        (so setting ``closed`` + closing the fds is race-free against
        queued senders)."""
        worker.closed = True
        try:
            os.waitpid(worker.pid, os.WNOHANG)
        except ChildProcessError:
            pass
        for fd in (worker.req_fd, worker.resp_fd):
            try:
                os.close(fd)
            except OSError:
                pass
        with self._lock:
            shared_write(self, "_workers")
            if self._closed or \
                    self._workers.get(shard_id) is not worker:
                return          # someone else already replaced it
            fresh = self._spawn(shard_id, worker.generation)
            self._workers[shard_id] = fresh
        global_metrics.increment("shard.worker_respawn_total")
        self._grant(shard_id, fresh)

    # -- fencing / grants ----------------------------------------------------

    def _grant(self, shard_id: int, worker: _Worker) -> None:
        epoch = self.map.epoch
        try:
            with worker.lock:
                if worker.closed:
                    return
                _send(worker.req_fd,
                      ("grant", {"shard": shard_id, "epoch": epoch},
                       None))
                _recv(worker.resp_fd)
        except (OSError, EOFError, struct.error, ValueError,
                pickle.UnpicklingError):
            # dead owner: the next routed request respawns + re-grants
            log.warning("grant(%d, epoch %d) found worker %s dead",
                        shard_id, epoch, worker.name)
        global_metrics.set_gauge("shard.map_epoch", float(epoch))

    def _broadcast_grant(self) -> None:
        """The table epoch is global: every mint re-grants every live
        owner so no owner is left refusing current-map writes."""
        with self._lock:
            shared_read(self, "_workers")
            workers = dict(self._workers)
        for sid, worker in workers.items():
            self._grant(sid, worker)

    # -- chaos hooks ---------------------------------------------------------

    def kill_worker(self, shard_id: int) -> int:
        """SIGKILL a shard's owner (nemesis: shard_worker_kill). The
        next request detects the death, respawns and recovers."""
        worker = self.owner(shard_id)
        try:
            os.kill(worker.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        return worker.pid

    def restart_worker(self, shard_id: int) -> None:
        """Proactive respawn of a killed owner (nemesis heal); a no-op
        when the worker is alive."""
        worker = self.owner(shard_id)
        with worker.lock:
            if worker.closed:
                return   # already replaced by another path
            try:
                pid, _status = os.waitpid(worker.pid, os.WNOHANG)
            except ChildProcessError:
                pid = worker.pid
            if pid == 0:
                return   # still alive
            self._handle_dead(shard_id, worker)

    # -- shard move ----------------------------------------------------------

    def shard_move(self, shard_id: int) -> str:
        """Live rebalance: move a shard to a FRESH worker process.

        Protocol (the acked-write-loss-free order):
          1. spawn the target (next generation, empty store);
          2. ``begin_move`` on the source: snapshot + arm frame buffer;
          3. target applies the snapshot;
          4. bounded delta catch-up rounds ship committed frames;
          5. the placement authority mints the new epoch (stale maps
             can no longer produce accepted acks);
          6. ``end_move`` fences the source and returns the frame tail
             (writes acked at the OLD epoch are all in snapshot+frames);
          7. target applies the tail, is granted at the new epoch (and
             snapshots once, re-baselining its own durability dir);
          8. the source retires.
        Returns the new owner's name.
        """
        t0 = time.perf_counter()
        source = self.owner(shard_id)
        with self._lock:
            # claim the generation in the same region that records it:
            # a failed move burns a generation number (dirs stay
            # unique), it never reuses one
            shared_write(self, "_generations")
            generation = self._generations.get(shard_id, 0) + 1
            self._generations[shard_id] = generation
        target = self._spawn(shard_id, generation)
        ceded = False
        try:
            _status, begin = self._direct(source, "begin_move", {})
            self._direct(target, "apply_snapshot",
                         {"snapshot": begin["snapshot"]})
            for _round in range(self.MOVE_CATCHUP_ROUNDS):
                _status, out = self._direct(source, "drain_frames", {})
                if not out["frames"]:
                    break
                self._direct(target, "apply_frames",
                             {"frames": out["frames"]})
            # epoch bump INSIDE the placement authority: from here a
            # stale-map client's write cannot produce an accepted ack
            self.map = self.placement.assign(shard_id, target.name)
            ceded = True
            _status, end = self._direct(source, "end_move",
                                        {"epoch": self.map.epoch})
            if end["frames"]:
                self._direct(target, "apply_frames",
                             {"frames": end["frames"]})
        except (OSError, EOFError, MemgraphTpuError):
            # presumed abort of the move: retire the half-built target
            self._retire(target)
            if ceded:
                # the epoch already moved to the target: hand ownership
                # back through the placement authority (fresh epoch, so
                # the grant un-fences an end_move-fenced source) — else
                # the still-installed source stale-bounces every write
                # at the new map epoch forever
                try:
                    self.map = self.placement.assign(shard_id,
                                                     source.name)
                    self._grant(shard_id, source)
                except (OSError, EOFError, MemgraphTpuError):
                    log.exception(
                        "shard %d: could not restore source owner %s "
                        "after aborted move; shard stays "
                        "write-unavailable until reassigned", shard_id,
                        source.name)
            raise
        with self._lock:
            shared_write(self, "_workers")
            self._workers[shard_id] = target
        self._broadcast_grant()
        self._retire(source)
        global_metrics.increment("shard.moves_total")
        global_metrics.observe("shard.move_duration_sec",
                               time.perf_counter() - t0)
        return target.name

    def _direct(self, worker: _Worker, op: str, payload: dict):
        """Move-protocol RPC to a specific worker (not via the map)."""
        with worker.lock:
            if worker.closed:
                raise WorkerCrashedError(
                    f"worker {worker.name} already retired")
            _send(worker.req_fd, (op, payload, None))
            out = _recv(worker.resp_fd)
        status, body = out[0], out[1]
        if status == "err":
            raise MemgraphTpuError(
                f"{op} on {worker.name}: {body[0]}: {body[1]}")
        return status, body

    # -- observability -------------------------------------------------------

    def health(self) -> dict:
        out = {}
        with self._lock:
            shared_read(self, "_workers")
            workers = dict(self._workers)
        for sid, worker in workers.items():
            try:
                _status, body = self._direct(worker, "health", {})
                out[sid] = body
            except (OSError, EOFError, MemgraphTpuError) as e:
                out[sid] = {"error": f"{type(e).__name__}: {e}"}
        return out
