"""Shard worker: one long-lived process owning one shard's storage.

Each worker owns a FULL storage engine for its hash range — its own WAL
directory (``<base>/shard_<id>`` — per-shard durability and recovery),
its own MVCC, its own indexes — and serves framed requests over the
mp_executor pipe envelope (same trace-carrier and error-transport
machinery, but the worker is a durable owner, not a disposable
snapshot).

Fencing contract (the shard-level half of the PR 5 epoch chain): the
worker holds a granted ``(shard, epoch)``; writes and 2PC prepares are
refused unless the request's routing epoch equals the grant and the
worker is not fenced, and every write ack carries the grant epoch — so
a client must prove it routed with the current map, and a deposed
owner can never produce an ack a current-map client would accept.

2PC (cross-shard writes): ``prepare`` executes the statement inside a
held-open explicit transaction AND journals it to a small durable
pending log before voting yes; ``decide`` commits or rolls back. A
worker that dies between prepare and decide recovers the pending log:
a later ``commit`` decision re-executes the journaled statement (the
presumed-commit direction replicas already use for voted frames), and
an ``abort`` — or silence — discards it (presumed abort).

Shard-move support: ``begin_move`` snapshots the shard and arms a
committed-frame buffer (the SAME WAL frame encoding replication ships),
``drain_frames`` pages the buffer out for delta catch-up, ``end_move``
fences this owner and returns the final tail.
"""

from __future__ import annotations

import json
import os
import pickle
import struct
import time

from ..observability import trace as mgtrace
from ..server.mp_executor import _recv, _send

__all__ = ["shard_worker_main", "PENDING_2PC_FILE"]

PENDING_2PC_FILE = "pending_2pc.json"


def _shard_dir(base_dir: str, shard_id: int, generation: int) -> str:
    """One durability dir per (shard, ownership generation): a respawn
    of the same owner reuses it (recovery), a move target gets a fresh
    one (its cutover snapshot re-baselines durability)."""
    if generation == 0:
        return os.path.join(base_dir, f"shard_{shard_id}")
    return os.path.join(base_dir, f"shard_{shard_id}.g{generation}")


class _WorkerState:
    """Everything the child process owns; built AFTER the fork so the
    storage engine, WAL file handles and interpreter never cross the
    process boundary."""

    def __init__(self, shard_id: int, name: str, data_dir: str,
                 epoch: int) -> None:
        from ..query.interpreter import Interpreter, InterpreterContext
        from ..storage.durability.recovery import recover, wire_durability
        from ..storage.storage import InMemoryStorage, StorageConfig

        self.shard_id = shard_id
        self.name = name
        self.data_dir = data_dir
        self.epoch = epoch
        self.owner_fenced = False
        os.makedirs(data_dir, exist_ok=True)
        self.storage = InMemoryStorage(StorageConfig(
            durability_dir=data_dir, wal_enabled=True))
        recover(self.storage)
        wire_durability(self.storage)
        self.ictx = InterpreterContext(self.storage)
        self.interp = Interpreter(self.ictx)
        self._make_interp = lambda: Interpreter(self.ictx)
        # txn_id -> Interpreter holding an open explicit transaction
        self.held_2pc: dict[str, object] = {}
        # txn_id -> {"query", "params"} journaled before the yes vote;
        # survives a crash so a commit decision can be honored
        self.pending_2pc: dict[str, dict] = self._load_pending()
        # shard-move: buffered (commit_ts, frame) since begin_move
        self.move_frames: list | None = None
        # data applied outside the commit pipeline (snapshot/frames from
        # a move) has no WAL trail yet; snapshot at grant to re-baseline
        self.needs_snapshot = False
        self.ops = 0
        self._buffer_hook = self._buffer_frame

    # -- pending-2PC journal -------------------------------------------------

    def _pending_path(self) -> str:
        return os.path.join(self.data_dir, PENDING_2PC_FILE)

    def _load_pending(self) -> dict:
        try:
            with open(self._pending_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _save_pending(self) -> None:
        tmp = self._pending_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.pending_2pc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._pending_path())

    # -- move-frame buffering ------------------------------------------------

    def _buffer_frame(self, frame: bytes, commit_ts: int) -> None:
        if self.move_frames is not None:
            self.move_frames.append((commit_ts, frame))

    def apply_frame(self, frame: bytes) -> None:
        """Apply a shipped WAL frame (delta catch-up on the move target)
        — the same shared applier recovery and replicas use."""
        from ..storage.durability import wal as W
        from ..storage.durability.recovery import _apply_wal_txn
        changed: set = set()
        for commit_ts, ops in W.iter_txns_from_bytes(frame):
            changed |= _apply_wal_txn(self.storage, ops)
            with self.storage._engine_lock:
                self.storage._timestamp = max(self.storage._timestamp,
                                              commit_ts)
        self.storage._bump_topology(changed)
        self.needs_snapshot = True


def _snapshot_bytes(storage) -> bytes:
    """Serialize the whole shard for a move's initial state transfer
    (the replication snapshot format — the target applies it with the
    same loader replicas use)."""
    from ..storage.durability.snapshot import create_snapshot
    path = create_snapshot(storage)
    with open(path, "rb") as f:
        return f.read()


def _execute(state: _WorkerState, query: str, params: dict,
             read_only: bool):
    """Run one statement on the worker's interpreter; returns
    (columns, rows, summary)."""
    from ..query.frontend import ast as A
    node = state.ictx.cached_parse(query)
    # Cypher plus per-shard schema DDL (indexes/constraints broadcast
    # by the router); everything else — auth, admin, replication —
    # belongs to the routing tier, not a hash range
    if not isinstance(node, (A.CypherQuery, A.IndexQuery,
                             A.ConstraintQuery)):
        raise RuntimeError("only Cypher and index/constraint DDL may "
                           "run on a shard worker (admin runs on the "
                           "routing tier)")
    if read_only and not isinstance(node, A.CypherQuery):
        raise RuntimeError("DDL routed on the read path")
    prepared = state.interp.prepare(query, params)
    if read_only and prepared.is_write:
        state.interp.abort()
        raise RuntimeError("write statement routed on the read path")
    rows, _more, summary = state.interp.pull(-1)
    return prepared.columns, rows, summary


def _handle(state: _WorkerState, op: str, payload: dict):
    """Dispatch one request; returns (status, payload). Raising maps to
    the generic ("err", ...) envelope in the loop."""
    if op == "grant":
        epoch = int(payload["epoch"])
        if epoch < state.epoch:
            return "stale_epoch", {"epoch": state.epoch}
        state.epoch = epoch
        state.owner_fenced = False
        if state.needs_snapshot:
            # moved-in data has no WAL trail in THIS dir yet: snapshot
            # once at cutover so a crash after the grant recovers it
            from ..storage.durability.snapshot import create_snapshot
            create_snapshot(state.storage)
            state.needs_snapshot = False
        return "ok", {"epoch": state.epoch, "shard": state.shard_id}

    if op == "revoke":
        epoch = int(payload["epoch"])
        if epoch >= state.epoch:
            state.owner_fenced = True
        return "ok", {"epoch": state.epoch,
                      "last_ts": state.storage.latest_commit_ts()}

    if op in ("read", "write"):
        if state.owner_fenced:
            return "fenced", {"epoch": state.epoch}
        if op == "write":
            req_epoch = int(payload.get("epoch") or 0)
            if req_epoch != state.epoch:
                # stale map (or a grant still in flight): the client
                # must refresh and re-route — never ack across epochs
                return "stale_epoch", {"epoch": state.epoch}
        cols, rows, summary = _execute(state, payload["query"],
                                       payload.get("params") or {},
                                       read_only=(op == "read"))
        state.ops += 1
        return "ok", {"columns": cols, "rows": rows, "summary": summary,
                      "shard": state.shard_id, "epoch": state.epoch,
                      "owner": state.name}

    if op == "prepare":
        if state.owner_fenced:
            return "fenced", {"epoch": state.epoch}
        if int(payload.get("epoch") or 0) != state.epoch:
            return "stale_epoch", {"epoch": state.epoch}
        txn_id = str(payload["txn_id"])
        statements = payload["statements"]
        interp = state._make_interp()
        interp.execute("BEGIN")
        try:
            for stmt in statements:
                interp.execute(stmt["query"], stmt.get("params") or {})
        except Exception:
            interp.execute("ROLLBACK")
            raise
        # journal BEFORE voting: the yes vote is a durable promise
        state.pending_2pc[txn_id] = {"statements": statements}
        state._save_pending()
        state.held_2pc[txn_id] = interp
        return "ok", {"vote": "yes", "shard": state.shard_id,
                      "epoch": state.epoch}

    if op == "decide":
        txn_id = str(payload["txn_id"])
        decision = payload["decision"]
        interp = state.held_2pc.pop(txn_id, None)
        if interp is not None:
            interp.execute("COMMIT" if decision == "commit"
                           else "ROLLBACK")
            # journal removal strictly AFTER the decision applied: a
            # crash in between leaves the entry behind, and a re-driven
            # commit replays it — never the reverse (entry gone while
            # the commit was lost, a half-committed cross-shard txn)
            if state.pending_2pc.pop(txn_id, None) is not None:
                state._save_pending()
            state.ops += 1
            return "ok", {"shard": state.shard_id, "epoch": state.epoch}
        if decision == "abort":
            # presumed abort: nothing committed here, but a crash
            # between prepare and decide may have left a journal entry
            # — prune it so it can never replay (and never accumulates)
            if state.pending_2pc.pop(txn_id, None) is not None:
                state._save_pending()
            return "ok", {"shard": state.shard_id, "epoch": state.epoch}
        journaled = state.pending_2pc.get(txn_id)
        if journaled is not None:
            # crash between prepare and decide: the journaled
            # statements re-execute against the recovered store (the
            # same presumed-commit direction replicas use for voted
            # frames), atomically via one held transaction; the entry
            # is removed only after that commit succeeds
            interp = state._make_interp()
            interp.execute("BEGIN")
            try:
                for stmt in journaled["statements"]:
                    interp.execute(stmt["query"],
                                   stmt.get("params") or {})
            except Exception:
                interp.execute("ROLLBACK")
                raise
            interp.execute("COMMIT")
            state.pending_2pc.pop(txn_id, None)
            state._save_pending()
            state.ops += 1
            return "ok", {"shard": state.shard_id, "epoch": state.epoch,
                          "replayed": True}
        return "unknown_txn", {"shard": state.shard_id}

    if op == "begin_move":
        state.move_frames = []
        if state._buffer_hook not in state.storage.frame_consumers:
            state.storage.frame_consumers.append(state._buffer_hook)
        snap = _snapshot_bytes(state.storage)
        return "ok", {"snapshot": snap,
                      "ts": state.storage.latest_commit_ts()}

    if op == "drain_frames":
        frames = state.move_frames or []
        state.move_frames = [] if state.move_frames is not None else None
        return "ok", {"frames": frames}

    if op == "end_move":
        epoch = int(payload["epoch"])
        if epoch >= state.epoch:
            state.owner_fenced = True
        frames = state.move_frames or []
        state.move_frames = None
        try:
            state.storage.frame_consumers.remove(state._buffer_hook)
        except ValueError:
            pass
        return "ok", {"frames": frames, "epoch": state.epoch,
                      "last_ts": state.storage.latest_commit_ts()}

    if op == "apply_snapshot":
        from ..storage.durability.recovery import (_apply_snapshot,
                                                   _clear_storage)
        from ..storage.durability.snapshot import load_snapshot
        import tempfile
        with tempfile.NamedTemporaryFile(delete=False,
                                         suffix=".mgsnap") as f:
            f.write(payload["snapshot"])
            path = f.name
        try:
            parsed = load_snapshot(path)
            _clear_storage(state.storage)
            _apply_snapshot(state.storage, parsed)
            with state.storage._engine_lock:
                state.storage._timestamp = max(state.storage._timestamp,
                                               parsed["timestamp"])
            state.storage._bump_topology()
            state.needs_snapshot = True
        finally:
            os.unlink(path)
        return "ok", {"ts": state.storage.latest_commit_ts()}

    if op == "apply_frames":
        for _ts, frame in payload["frames"]:
            state.apply_frame(frame)
        return "ok", {"ts": state.storage.latest_commit_ts()}

    if op == "health":
        return "ok", {"pid": os.getpid(), "shard": state.shard_id,
                      "name": state.name, "epoch": state.epoch,
                      "fenced": state.owner_fenced, "ops": state.ops,
                      "pending_2pc": sorted(state.pending_2pc),
                      "last_ts": state.storage.latest_commit_ts()}

    raise RuntimeError(f"unknown shard op {op!r}")


def shard_worker_main(shard_id: int, name: str, req_fd: int,
                      resp_fd: int, base_dir: str, generation: int,
                      epoch: int) -> None:
    """The child-process loop: build the shard's state, then serve the
    envelope until EOF/None. Every response carries the worker's spans
    (trace carrier machinery shared with mp_executor)."""
    data_dir = _shard_dir(base_dir, shard_id, generation)
    state = _WorkerState(shard_id, name, data_dir, epoch)
    while True:
        try:
            msg = _recv(req_fd)
        except (EOFError, OSError, struct.error, ValueError,
                pickle.UnpicklingError):
            # torn/garbage frame on the request pipe: the plane side
            # is gone or corrupt — exit; the plane respawns this shard
            # with per-shard WAL recovery
            return
        if msg is None:
            return
        op, payload, carrier = msg
        t0 = time.perf_counter()
        try:
            with mgtrace.adopt(carrier):
                with mgtrace.span("shard.worker"):
                    status, out = _handle(state, op, payload or {})
            spans = mgtrace.take_trace(carrier["trace_id"]) \
                if carrier else []
            _send(resp_fd, (status, out,
                            {"elapsed": time.perf_counter() - t0},
                            spans))
        except Exception as e:  # noqa: BLE001 — ship the error back
            try:
                _send(resp_fd, ("err", (type(e).__name__, str(e)),
                                {"elapsed": time.perf_counter() - t0},
                                []))
            except (OSError, ValueError, struct.error):
                return      # response pipe gone: die, get respawned
