"""mgshard: shard-per-process OLTP execution plane (r18).

The Bolt worker pool gives concurrency, not CPU parallelism — the GIL
caps aggregate multi-client OLTP at ~1.2x (OLTP_r05/r06). This package
promotes the mp-executor experiment to the architecture: storage is
hash-sharded across N long-lived worker processes, each owning a full
Storage engine with its own WAL directory and per-shard crash recovery;
a coordinator-minted, epoch-versioned shard map routes every request;
and the client layer does single-shard point routing, scatter-gather
reads with merge, and cross-shard 2PC writes with presumed-abort.

Layout:
    partition.py  stable hash partitioner (key -> shard)
    shard_map.py  epoch-versioned shard_id -> owner map
    worker.py     the shard worker process loop (storage + WAL + 2PC)
    plane.py      ShardPlane: spawn/respawn/kill/move shard workers
    router.py     ShardedClient: routing, scatter-gather merge, 2PC
"""

from .partition import shard_for_key, shard_for_gid
from .shard_map import ShardMap
from .plane import ShardPlane, LocalPlacement, CoordinatorPlacement
from .router import ShardedClient, MergeError

__all__ = ["shard_for_key", "shard_for_gid", "ShardMap", "ShardPlane",
           "LocalPlacement", "CoordinatorPlacement", "ShardedClient",
           "MergeError"]
