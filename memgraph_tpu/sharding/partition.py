"""Stable hash partitioner: routing keys -> shard ids.

The partitioner must be a pure function of the VALUE, identical in
every process that ever routes (the parent plane, forked shard workers,
bench clients, a recovering worker) — so Python's salted ``hash()`` is
out. We hash a canonical byte encoding with crc32, which is stable
across processes, platforms and restarts (the reference analog: the
fixed fnv1a the reference uses for its property-sharded indices).

Keys are whatever the workload routes by — in the OLTP bench that is
the ``id`` property value; gids work too (``shard_for_gid``).
"""

from __future__ import annotations

import struct
import zlib

__all__ = ["N_SHARDS_DEFAULT", "canonical_key_bytes", "shard_for_key",
           "shard_for_gid"]

N_SHARDS_DEFAULT = 4


def canonical_key_bytes(key) -> bytes:
    """One canonical encoding per value so int 7 and float 7.0 and the
    string "7" land deterministically (ints/floats that compare equal
    share an encoding, mirroring Cypher value equality)."""
    if isinstance(key, bool):
        return b"b" + (b"1" if key else b"0")
    if isinstance(key, int):
        return b"i" + struct.pack("<q", key)
    if isinstance(key, float):
        if key.is_integer():
            return b"i" + struct.pack("<q", int(key))
        return b"f" + struct.pack("<d", key)
    if isinstance(key, str):
        return b"s" + key.encode("utf-8")
    if isinstance(key, bytes):
        return b"y" + key
    if key is None:
        return b"n"
    raise TypeError(f"unroutable partition key type {type(key).__name__}")


def shard_for_key(key, n_shards: int) -> int:
    """Map a routing key onto [0, n_shards)."""
    if n_shards <= 0:
        raise ValueError("n_shards must be positive")
    return zlib.crc32(canonical_key_bytes(key)) % n_shards


def shard_for_gid(gid: int, n_shards: int) -> int:
    return shard_for_key(int(gid), n_shards)
