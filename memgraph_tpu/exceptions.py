"""Framework-wide exception hierarchy.

Mirrors the error taxonomy the reference surfaces to clients (storage errors
at /root/reference/src/storage/v2/storage.hpp, query exceptions at
/root/reference/src/query/exceptions.hpp) without copying its structure.
"""


class MemgraphTpuError(Exception):
    """Base class for all framework errors."""


# --- storage-level -----------------------------------------------------------

class StorageError(MemgraphTpuError):
    pass


class SerializationError(StorageError):
    """Write-write conflict between concurrent transactions (optimistic MVCC)."""


class ConstraintViolation(StorageError):
    def __init__(self, message, constraint=None):
        super().__init__(message)
        self.constraint = constraint


class DurabilityError(StorageError):
    pass


# --- query-level -------------------------------------------------------------

class QueryException(MemgraphTpuError):
    pass


class SyntaxException(QueryException):
    """Cypher lexical/grammatical error. Client code: Memgraph.ClientError."""


class SemanticException(QueryException):
    """Valid syntax, invalid meaning (unbound symbol, bad aggregation, ...)."""


class TypeException(QueryException):
    """Runtime type mismatch in expression evaluation."""


class EntityNotFound(QueryException):
    """Access to a deleted graph entity's properties or labels
    (TCK: EntityNotFound / DeletedEntityAccess)."""


class ArithmeticException(QueryException):
    pass


class ProfileException(QueryException):
    pass


class HintedAbortError(QueryException):
    """Query killed (timeout / TERMINATE TRANSACTIONS / shutdown)."""


class TransactionException(QueryException):
    pass


class ReplicaUnavailableException(TransactionException):
    """Commit refused BEFORE any replica prepared: the write definitely
    did not happen anywhere (a safe, non-ambiguous failure — chaos
    clients may record it as a clean fail, not indeterminate)."""


class FencedException(TransactionException):
    """This MAIN holds a stale fencing epoch — a newer MAIN was
    promoted. Refused before any effect; definitely did not happen."""


class ProcedureException(QueryException):
    """Error raised from a CALLed query module procedure."""


class WorkerCrashedError(MemgraphTpuError, ConnectionError):
    """A pooled worker process died mid-request. The pool has already
    respawned it, so the request is RETRYABLE — ConnectionError in the
    MRO means RetryPolicy's default ``retry_on`` catches it without
    special-casing (mp_executor and the shard plane both raise this)."""


class ShardError(MemgraphTpuError):
    pass


class StaleShardEpoch(ShardError):
    """A shard owner refused a write because the request's routing
    epoch does not match its grant (stale client map, or a fenced
    deposed owner). Carries the owner's epoch so the client can refresh
    the shard map and retry against the current owner."""

    def __init__(self, shard_id: int, epoch: int,
                 fenced: bool = False) -> None:
        what = "fenced owner" if fenced else "stale routing epoch"
        super().__init__(f"shard {shard_id}: {what} "
                         f"(owner epoch {epoch})")
        self.shard_id = shard_id
        self.epoch = epoch
        self.fenced = fenced


class AuthException(MemgraphTpuError):
    pass
