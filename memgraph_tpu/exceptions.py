"""Framework-wide exception hierarchy.

Mirrors the error taxonomy the reference surfaces to clients (storage errors
at /root/reference/src/storage/v2/storage.hpp, query exceptions at
/root/reference/src/query/exceptions.hpp) without copying its structure.
"""


class MemgraphTpuError(Exception):
    """Base class for all framework errors."""


# --- storage-level -----------------------------------------------------------

class StorageError(MemgraphTpuError):
    pass


class SerializationError(StorageError):
    """Write-write conflict between concurrent transactions (optimistic MVCC)."""


class ConstraintViolation(StorageError):
    def __init__(self, message, constraint=None):
        super().__init__(message)
        self.constraint = constraint


class DurabilityError(StorageError):
    pass


# --- query-level -------------------------------------------------------------

class QueryException(MemgraphTpuError):
    pass


class SyntaxException(QueryException):
    """Cypher lexical/grammatical error. Client code: Memgraph.ClientError."""


class SemanticException(QueryException):
    """Valid syntax, invalid meaning (unbound symbol, bad aggregation, ...)."""


class TypeException(QueryException):
    """Runtime type mismatch in expression evaluation."""


class EntityNotFound(QueryException):
    """Access to a deleted graph entity's properties or labels
    (TCK: EntityNotFound / DeletedEntityAccess)."""


class ArithmeticException(QueryException):
    pass


class ProfileException(QueryException):
    pass


class HintedAbortError(QueryException):
    """Query killed (timeout / TERMINATE TRANSACTIONS / shutdown)."""


class TransactionException(QueryException):
    pass


class ReplicaUnavailableException(TransactionException):
    """Commit refused BEFORE any replica prepared: the write definitely
    did not happen anywhere (a safe, non-ambiguous failure — chaos
    clients may record it as a clean fail, not indeterminate)."""


class FencedException(TransactionException):
    """This MAIN holds a stale fencing epoch — a newer MAIN was
    promoted. Refused before any effect; definitely did not happen."""


class ProcedureException(QueryException):
    """Error raised from a CALLed query module procedure."""


class WorkerCrashedError(MemgraphTpuError, ConnectionError):
    """A pooled worker process died mid-request. The pool has already
    respawned it, so reads are RETRYABLE — ConnectionError in the MRO
    means RetryPolicy's default ``retry_on`` catches it without
    special-casing (mp_executor and the shard plane both raise this).

    ``in_doubt`` distinguishes the two crash windows for writers: False
    means the request was never handed to the worker (replaced while
    queued — safe to blindly re-send), True means it died after the
    request was on the wire, so a non-idempotent op may or may not have
    applied and must NOT be blindly retried (see WriteInDoubtError)."""

    def __init__(self, message: str, *, in_doubt: bool = False) -> None:
        super().__init__(message)
        self.in_doubt = in_doubt


class WriteInDoubtError(MemgraphTpuError):
    """A non-idempotent write crashed in the in-doubt window: the owner
    died after the request was sent but before the ack, so the write
    may or may not be in the shard's WAL. Surfaced instead of retried —
    a blind re-send could double-apply. Callers that can verify
    (read-your-write, idempotency keys) may resolve the doubt
    themselves; chaos checkers record it as indeterminate."""


class ShardError(MemgraphTpuError):
    pass


class StaleShardEpoch(ShardError):
    """A shard owner refused a write because the request's routing
    epoch does not match its grant (stale client map, or a fenced
    deposed owner). Carries the owner's epoch so the client can refresh
    the shard map and retry against the current owner."""

    def __init__(self, shard_id: int, epoch: int,
                 fenced: bool = False) -> None:
        what = "fenced owner" if fenced else "stale routing epoch"
        super().__init__(f"shard {shard_id}: {what} "
                         f"(owner epoch {epoch})")
        self.shard_id = shard_id
        self.epoch = epoch
        self.fenced = fenced


class AuthException(MemgraphTpuError):
    pass


#: Worker-shipped error envelopes carry ``(type_name, message)``
#: strings; this is the decode table back into the typed taxonomy.
#: Message-only constructors only — classes with structured payloads
#: (StaleShardEpoch) or process-lifecycle semantics (WorkerCrashedError,
#: WriteInDoubtError) are deliberately absent and fall through to the
#: MemgraphTpuError catch-all.
WIRE_ERRORS = {
    "MemgraphTpuError": MemgraphTpuError,
    "StorageError": StorageError,
    "SerializationError": SerializationError,
    "ConstraintViolation": ConstraintViolation,
    "DurabilityError": DurabilityError,
    "QueryException": QueryException,
    "SyntaxException": SyntaxException,
    "SemanticException": SemanticException,
    "TypeException": TypeException,
    "EntityNotFound": EntityNotFound,
    "ArithmeticException": ArithmeticException,
    "ProfileException": ProfileException,
    "HintedAbortError": HintedAbortError,
    "TransactionException": TransactionException,
    "ReplicaUnavailableException": ReplicaUnavailableException,
    "FencedException": FencedException,
    "ProcedureException": ProcedureException,
    "ShardError": ShardError,
    "AuthException": AuthException,
}


def raise_wire_error(type_name: str, message: str):
    """Rehydrate a worker error envelope into its taxonomy class, so
    pool/plane clients surface SyntaxException as SyntaxException
    instead of a stringly generic error. Unknown type names (builtin
    exceptions, future classes crossing an old wire) degrade to
    MemgraphTpuError with the name preserved in the message."""
    cls = WIRE_ERRORS.get(type_name)
    if cls is None:
        raise MemgraphTpuError(f"{type_name}: {message}")
    raise cls(message)
