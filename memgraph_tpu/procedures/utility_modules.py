"""Utility modules: mg.procedures, graph stats, kmeans.

Counterparts of the reference's introspection/utility modules
(mage/cpp/{meta,util}_module, query_modules/schema.cpp surface, and
mage/python/kmeans.py).
"""

from __future__ import annotations

import numpy as np

from . import mgp


@mgp.read_proc("mg.procedures",
               results=[("name", "STRING"), ("signature", "STRING"),
                        ("is_write", "BOOLEAN")])
def mg_procedures(ctx):
    from ..query.procedures.registry import global_registry
    for proc in global_registry.all_procedures():
        args = ", ".join(f"{n} :: {t}" for n, t in proc.args)
        opts = ", ".join(f"{n} = {d!r} :: {t}"
                         for n, t, d in proc.opt_args)
        res = ", ".join(f"{n} :: {t}" for n, t in proc.results)
        sig = f"{proc.name}({', '.join(x for x in (args, opts) if x)}) " \
              f":: ({res})"
        yield {"name": proc.name, "signature": sig,
               "is_write": proc.is_write}


@mgp.read_proc("graph_util.stats",
               results=[("num_nodes", "INTEGER"), ("num_edges", "INTEGER"),
                        ("avg_degree", "FLOAT"), ("num_components", "INTEGER")])
def graph_stats(ctx):
    from ..ops.components import weakly_connected_components
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        yield {"num_nodes": 0, "num_edges": 0, "avg_degree": 0.0,
               "num_components": 0}
        return
    comp, _ = weakly_connected_components(graph)
    n_comp = len(set(np.asarray(comp).tolist()))
    yield {"num_nodes": graph.n_nodes, "num_edges": graph.n_edges,
           "avg_degree": 2.0 * graph.n_edges / graph.n_nodes,
           "num_components": n_comp}


@mgp.read_proc("kmeans.get_clusters",
               args=[("property", "STRING"), ("n_clusters", "INTEGER")],
               opt_args=[("iterations", "INTEGER", 10),
                         ("seed", "INTEGER", 0)],
               results=[("node", "NODE"), ("cluster_id", "INTEGER")])
def kmeans_clusters(ctx, property, n_clusters, iterations=10, seed=0):
    import jax
    from ..ops.knn import kmeans_fit
    from .vector_search import _get_index
    entry = _get_index(ctx, str(property))
    if entry.matrix is None:
        return
    # compact to live rows (the delta-maintained matrix may hold freed
    # rows); row order follows the index layout
    live = [(row, gid) for row, gid in enumerate(entry.row_gids)
            if gid is not None]
    if not live:
        return
    rows = np.asarray([r for r, _ in live], dtype=np.int32)
    matrix = entry.matrix[rows]
    gids = [g for _, g in live]
    k = max(1, min(int(n_clusters), matrix.shape[0]))
    _, assign = kmeans_fit(matrix, jax.random.PRNGKey(int(seed)), k,
                           iters=int(iterations))
    assign = np.asarray(assign)
    for gid, cluster in zip(gids, assign):
        node = ctx.accessor.find_vertex(gid, ctx.view)
        if node is not None:
            yield {"node": node, "cluster_id": int(cluster)}
