"""tgn.* — temporal graph network for streamed edge batches.

Compact JAX re-design of /root/reference/mage/python/tgn.py (itself the
TGN of Rossi et al.): per-node MEMORY updated by a GRU cell on message
aggregation, sinusoidal time encoding of inter-event deltas, and an
MLP link predictor over (memory[src], memory[dst], time_enc) — trained
online on each streamed edge batch with negative sampling, exactly the
module's role in the reference (self-supervised mode). The full
attention-embedding stack is collapsed to the memory path: that is the
part that carries TGN's temporal signal, and it keeps every step a
dense batched matmul (MXU) instead of per-edge python.

Surface parity: set_params / update / train_and_eval / get /
predict_link_score / reset.
"""

from __future__ import annotations

import numpy as np

from ..exceptions import QueryException
from . import mgp

_STATE: dict = {}


def _defaults():
    return {"memory_dim": 32, "time_dim": 8, "learning_rate": 0.01,
            "num_neg_samples": 1, "seed": 7}


def _init_state(params, n_hint=256):
    import jax
    import jax.numpy as jnp
    import optax

    p = _defaults()
    p.update(params or {})
    d, t = int(p["memory_dim"]), int(p["time_dim"])
    key = jax.random.PRNGKey(int(p["seed"]))
    k1, k2, k3, k4 = jax.random.split(key, 4)
    scale = 0.1
    weights = {
        # GRU cell: input = [other_memory, time_enc]
        "W_z": jax.random.normal(k1, (d + t + d, d)) * scale,
        "W_r": jax.random.normal(k2, (d + t + d, d)) * scale,
        "W_h": jax.random.normal(k3, (d + t + d, d)) * scale,
        # link predictor MLP over [mem_src, mem_dst, mem_src*mem_dst,
        # feat_src*feat_dst, time_enc] — the product terms make pair
        # affinity linearly learnable, and the FEATURE product survives
        # the GRU's contractive dynamics (memories of structurally
        # symmetric nodes converge to one attractor)
        "W_p1": jax.random.normal(k4, (4 * d + t, d)) * scale,
        "b_p1": jnp.zeros((d,)),
        "W_p2": jax.random.normal(k1, (d, 1)) * scale,
        "b_p2": jnp.zeros((1,)),
    }
    optimizer = optax.adam(float(p["learning_rate"]))
    init_mem = jnp.asarray(_init_rows(n_hint, d, seed=0))
    _STATE.update({
        "params": p, "weights": weights, "optimizer": optimizer,
        "opt_state": optimizer.init(weights),
        "memory": init_mem,
        "init_memory": init_mem,
        "last_seen": jnp.zeros((n_hint,)),
        "gid_to_row": {}, "clock": 0.0, "step": 0,
        "train_losses": [], "eval_scores": [],
    })


def _ensure_state():
    if not _STATE:
        _init_state({})
    return _STATE


def _init_rows(n_rows, d, seed):
    """Fixed pseudorandom per-node initial memory: the stand-in for node
    features (zeros would make structurally-symmetric nodes permanently
    indistinguishable to the link predictor)."""
    rng = np.random.default_rng(seed)
    return 0.1 * rng.standard_normal((n_rows, d)).astype(np.float32)


def _rows_for(gids):
    st = _ensure_state()
    import jax.numpy as jnp
    mapping = st["gid_to_row"]
    rows = []
    for g in gids:
        if g not in mapping:
            mapping[g] = len(mapping)
        rows.append(mapping[g])
    need = len(mapping)
    cap = st["memory"].shape[0]
    if need > cap:
        new_cap = max(need, cap * 2)
        d = st["memory"].shape[1]
        grow = _init_rows(new_cap - cap, d, seed=cap)
        st["memory"] = jnp.concatenate([st["memory"], jnp.asarray(grow)])
        st["init_memory"] = jnp.concatenate(
            [st["init_memory"], jnp.asarray(grow)])
        st["last_seen"] = jnp.concatenate(
            [st["last_seen"], jnp.zeros((new_cap - cap,))])
    return np.asarray(rows, dtype=np.int32)


def _time_encode(delta, t_dim):
    import jax.numpy as jnp
    freqs = jnp.exp(-jnp.arange(t_dim // 2) * 1.0)
    ang = delta[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=1)


def _batch_step(weights, memory, feats, last_seen, src_r, dst_r, ts,
                neg_r, optimizer, opt_state, train=True):
    """One streamed batch: loss on pos vs neg links, grad step, memory
    update. All dense (B, d) matmuls."""
    import jax
    import jax.numpy as jnp

    t_dim = weights["W_p1"].shape[0] - 4 * memory.shape[1]

    def link_logits(w, mem, s, d_, te):
        h = jnp.concatenate([mem[s], mem[d_], mem[s] * mem[d_],
                             feats[s] * feats[d_], te], axis=1)
        h = jnp.tanh(h @ w["W_p1"] + w["b_p1"])
        return (h @ w["W_p2"] + w["b_p2"])[:, 0]

    delta = ts - last_seen[src_r]
    te = _time_encode(delta, t_dim)

    def loss_fn(w):
        pos = link_logits(w, memory, src_r, dst_r, te)
        neg = link_logits(w, memory, src_r, neg_r, te)
        return jnp.mean(jax.nn.softplus(-pos) + jax.nn.softplus(neg))

    if train:
        loss, grads = jax.value_and_grad(loss_fn)(weights)
        import optax
        updates, opt_state = optimizer.update(grads, opt_state, weights)
        weights = optax.apply_updates(weights, updates)
    else:
        loss = loss_fn(weights)

    # GRU memory update for the DESTINATION of each event (message from
    # src), then symmetric for the source
    def gru(mem, rows, other_rows, te_):
        x = jnp.concatenate([mem[other_rows], te_], axis=1)
        xin = jnp.concatenate([x, mem[rows]], axis=1)
        z = jax.nn.sigmoid(xin @ weights["W_z"])
        r = jax.nn.sigmoid(xin @ weights["W_r"])
        xh = jnp.concatenate([x, r * mem[rows]], axis=1)
        h = jnp.tanh(xh @ weights["W_h"])
        return mem.at[rows].set((1 - z) * mem[rows] + z * h)

    memory = gru(memory, dst_r, src_r, te)
    memory = gru(memory, src_r, dst_r, te)
    last_seen = last_seen.at[src_r].set(ts)
    last_seen = last_seen.at[dst_r].set(ts)
    return weights, opt_state, memory, last_seen, float(loss)


def _ingest(edges_spec, train):
    """edges_spec: list of (src_gid, dst_gid, timestamp)."""
    import jax.numpy as jnp
    st = _ensure_state()
    if not edges_spec:
        return 0.0
    src_g = [e[0] for e in edges_spec]
    dst_g = [e[1] for e in edges_spec]
    ts = np.asarray([float(e[2]) for e in edges_spec], np.float32)
    src_r = _rows_for(src_g)
    dst_r = _rows_for(dst_g)
    st["step"] = st.get("step", 0) + 1   # fresh negatives every batch
    rng = np.random.default_rng(st["step"])
    neg_r = rng.integers(0, len(st["gid_to_row"]),
                         len(src_r)).astype(np.int32)
    (st["weights"], st["opt_state"], st["memory"], st["last_seen"],
     loss) = _batch_step(
        st["weights"], st["memory"], st["init_memory"], st["last_seen"],
        jnp.asarray(src_r), jnp.asarray(dst_r), jnp.asarray(ts),
        jnp.asarray(neg_r), st["optimizer"], st["opt_state"],
        train=train)
    st["clock"] = max(st["clock"], float(ts.max()))
    (st["train_losses"] if train else st["eval_scores"]).append(loss)
    return loss


def _edges_from_graph(ctx, timestamp_property):
    pid = ctx.storage.property_mapper.maybe_name_to_id(timestamp_property)
    out = []
    for ea in ctx.accessor.edges(ctx.view):
        ts = ea.properties(ctx.view).get(pid, 0) if pid is not None else 0
        if not isinstance(ts, (int, float)):
            ts = 0
        out.append((ea.from_vertex().gid, ea.to_vertex().gid, ts))
    out.sort(key=lambda e: e[2])
    return out


@mgp.read_proc("tgn.set_params",
               args=[("params", "MAP")],
               results=[("message", "STRING")])
def set_params(ctx, params):
    _init_state(dict(params or {}))
    yield {"message": f"tgn initialized with {_STATE['params']}"}


@mgp.read_proc("tgn.update",
               args=[("edges", "LIST")],
               opt_args=[("timestamp_property", "STRING", "timestamp")],
               results=[("loss", "FLOAT")])
def update(ctx, edges, timestamp_property="timestamp"):
    """Online-train on a batch of edges (self-supervised link signal)."""
    pid = ctx.storage.property_mapper.maybe_name_to_id(timestamp_property)
    spec = []
    for ea in edges or []:
        ts = 0
        if pid is not None:
            val = ea.properties(ctx.view).get(pid, 0)
            ts = val if isinstance(val, (int, float)) else 0
        spec.append((ea.from_vertex().gid, ea.to_vertex().gid, ts))
    yield {"loss": _ingest(spec, train=True)}


@mgp.read_proc("tgn.train_and_eval",
               args=[("num_epochs", "INTEGER")],
               opt_args=[("timestamp_property", "STRING", "timestamp"),
                         ("train_fraction", "FLOAT", 0.8),
                         ("batch_size", "INTEGER", 64)],
               results=[("epoch", "INTEGER"), ("train_loss", "FLOAT"),
                        ("eval_loss", "FLOAT")])
def train_and_eval(ctx, num_epochs, timestamp_property="timestamp",
                   train_fraction=0.8, batch_size=64):
    """Epoch training over the graph's edges in timestamp order."""
    edges = _edges_from_graph(ctx, timestamp_property)
    if not edges:
        raise QueryException("tgn: the graph has no edges to train on")
    cut = max(1, int(len(edges) * float(train_fraction)))
    train_edges, eval_edges = edges[:cut], edges[cut:]
    bs = max(1, int(batch_size))
    import jax.numpy as jnp
    st = _ensure_state()
    for epoch in range(int(num_epochs)):
        # memory restarts from the node-feature init each epoch (standard
        # TGN training loop; the reference does the same)
        st["memory"] = jnp.asarray(st["init_memory"])
        st["last_seen"] = jnp.zeros_like(st["last_seen"])
        t_losses, e_losses = [], []
        for i in range(0, len(train_edges), bs):
            t_losses.append(_ingest(train_edges[i:i + bs], train=True))
        for i in range(0, len(eval_edges), bs):
            e_losses.append(_ingest(eval_edges[i:i + bs], train=False))
        yield {"epoch": epoch,
               "train_loss": float(np.mean(t_losses)) if t_losses else 0.0,
               "eval_loss": float(np.mean(e_losses)) if e_losses else 0.0}


@mgp.read_proc("tgn.get",
               results=[("node", "NODE"), ("embedding", "LIST")])
def get(ctx):
    """Current memory embedding of every tracked node."""
    st = _ensure_state()
    mem = np.asarray(st["memory"])
    for gid, row in st["gid_to_row"].items():
        node = ctx.accessor.find_vertex(gid, ctx.view)
        if node is not None:
            yield {"node": node, "embedding": [float(x)
                                               for x in mem[row]]}


@mgp.read_proc("tgn.predict_link_score",
               args=[("src", "NODE"), ("dest", "NODE")],
               results=[("prediction", "FLOAT")])
def predict_link_score(ctx, src, dest):
    import jax
    import jax.numpy as jnp
    st = _ensure_state()
    rows = _rows_for([src.gid, dest.gid])
    mem = st["memory"]
    feats = st["init_memory"]
    t_dim = st["weights"]["W_p1"].shape[0] - 4 * mem.shape[1]
    te = _time_encode(jnp.zeros((1,)), t_dim)
    ms, md = mem[rows[0]][None], mem[rows[1]][None]
    fs, fd = feats[rows[0]][None], feats[rows[1]][None]
    h = jnp.concatenate([ms, md, ms * md, fs * fd, te], axis=1)
    h = jnp.tanh(h @ st["weights"]["W_p1"] + st["weights"]["b_p1"])
    logit = (h @ st["weights"]["W_p2"] + st["weights"]["b_p2"])[0, 0]
    yield {"prediction": float(jax.nn.sigmoid(logit))}


@mgp.read_proc("tgn.reset", results=[("message", "STRING")])
def reset(ctx):
    _STATE.clear()
    yield {"message": "tgn state cleared"}
