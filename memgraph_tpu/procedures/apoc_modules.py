"""Graph-utility query modules (the APOC-like MAGE surface).

Counterparts of the reference's C++ utility modules under mage/cpp/:
uuid, label, node, nodes, neighbors, meta, path, merge, text, util,
distance_calculator, and periodic (periodic.iterate / periodic.delete run
batched Cypher through a system interpreter session, committing per batch
exactly like the reference's periodic module). Procedure names, arguments,
and result fields follow the reference modules.
"""

from __future__ import annotations

import collections
import hashlib
import re
import uuid as _uuid

from ..exceptions import QueryException
from . import mgp
from .igraph_module import _haversine

# --- uuid / util / text ------------------------------------------------------


@mgp.read_proc("uuid.get", results=[("uuid", "STRING")])
def uuid_get(ctx):
    yield {"uuid": str(_uuid.uuid4())}


@mgp.read_proc("util.md5", args=[("values", "LIST")],
               results=[("result", "STRING")])
def util_md5(ctx, values):
    digest = hashlib.md5()
    for v in values:
        digest.update(str(v).encode("utf-8"))
    yield {"result": digest.hexdigest()}


@mgp.read_proc("text.join",
               args=[("strings", "LIST"), ("delimiter", "STRING")],
               results=[("string", "STRING")])
def text_join(ctx, strings, delimiter):
    if any(not isinstance(s, str) for s in strings):
        raise QueryException("text.join expects a list of strings")
    yield {"string": delimiter.join(strings)}


@mgp.read_proc("text.format",
               args=[("text", "STRING"), ("params", "LIST")],
               results=[("result", "STRING")])
def text_format(ctx, text, params):
    yield {"result": text.format(*params)}


@mgp.read_proc("text.regex_groups",
               args=[("input", "STRING"), ("regex", "STRING")],
               results=[("results", "LIST")])
def text_regex_groups(ctx, input, regex):
    out = []
    for m in re.finditer(regex, input):
        out.append([m.group(0), *m.groups()])
    yield {"results": out}


# --- label / node / nodes ----------------------------------------------------


@mgp.read_proc("label.exists",
               args=[("node", "ANY"), ("label", "STRING")],
               results=[("exists", "BOOLEAN")])
def label_exists(ctx, node, label):
    lid = ctx.storage.label_mapper.maybe_name_to_id(label)
    exists = (lid is not None and hasattr(node, "has_label")
              and node.has_label(lid, ctx.view))
    yield {"exists": bool(exists)}


@mgp.read_proc("node.degree_in",
               args=[("node", "NODE")],
               opt_args=[("type", "STRING", "")],
               results=[("degree", "INTEGER")])
def node_degree_in(ctx, node, type=""):
    yield {"degree": _degree(ctx, node, type, incoming=True)}


@mgp.read_proc("node.degree_out",
               args=[("node", "NODE")],
               opt_args=[("type", "STRING", "")],
               results=[("degree", "INTEGER")])
def node_degree_out(ctx, node, type=""):
    yield {"degree": _degree(ctx, node, type, incoming=False)}


def _degree(ctx, node, type_name, incoming):
    type_ids = None
    if type_name:
        tid = ctx.storage.edge_type_mapper.maybe_name_to_id(type_name)
        if tid is None:
            return 0
        type_ids = [tid]
    edges = (node.in_edges(ctx.view, edge_types=type_ids) if incoming
             else node.out_edges(ctx.view, edge_types=type_ids))
    return len(edges)


@mgp.read_proc("node.relationship_types",
               args=[("node", "NODE")],
               results=[("relationship_types", "LIST")])
def node_relationship_types(ctx, node):
    mapper = ctx.storage.edge_type_mapper
    types = {mapper.id_to_name(e.edge_type)
             for e in node.out_edges(ctx.view)}
    types |= {mapper.id_to_name(e.edge_type)
              for e in node.in_edges(ctx.view)}
    yield {"relationship_types": sorted(types)}


@mgp.read_proc("node.relationships_exist",
               args=[("node", "NODE"), ("relationships", "LIST")],
               results=[("result", "MAP")])
def node_relationships_exist(ctx, node, relationships):
    """Each pattern is "TYPE" / "TYPE>" (outgoing) / "<TYPE" (incoming),
    as in the reference's node module."""
    result = {}
    for pattern in relationships:
        result[pattern] = _relationship_exists(ctx, node, pattern)
    yield {"result": result}


def _relationship_exists(ctx, node, pattern):
    name = pattern.strip("<>")
    tid = ctx.storage.edge_type_mapper.maybe_name_to_id(name)
    if tid is None:
        return False
    check_out = not pattern.startswith("<")
    check_in = not pattern.endswith(">")
    if check_out and node.out_edges(ctx.view, edge_types=[tid]):
        return True
    if check_in and node.in_edges(ctx.view, edge_types=[tid]):
        return True
    return False


@mgp.write_proc("nodes.link",
                args=[("nodes", "LIST"), ("type", "STRING")],
                results=[("success", "BOOLEAN")])
def nodes_link(ctx, nodes, type):
    """Chain-link the given nodes with TYPE relationships (reference
    nodes_module Link)."""
    tid = ctx.storage.edge_type_mapper.name_to_id(type)
    for a, b in zip(nodes, nodes[1:]):
        ctx.accessor.create_edge(a, b, tid)
    yield {"success": True}


@mgp.write_proc("nodes.delete",
                args=[("nodes", "LIST")],
                results=[("success", "BOOLEAN")])
def nodes_delete(ctx, nodes):
    for node in nodes:
        ctx.accessor.delete_vertex(node, detach=True)
    yield {"success": True}


# --- neighbors ---------------------------------------------------------------


def _hop_frontiers(ctx, node, rel_types, max_distance):
    """[{gids at hop 1}, {hop 2}, ...] breadth-first, undirected unless a
    pattern pins a direction ("TYPE>" out, "<TYPE" in)."""
    out_ids, in_ids, any_dir = set(), set(), not rel_types
    for pattern in rel_types or []:
        name = pattern.strip("<>")
        tid = ctx.storage.edge_type_mapper.maybe_name_to_id(name)
        if tid is None:
            continue
        if not pattern.startswith("<"):
            out_ids.add(tid)
        if not pattern.endswith(">"):
            in_ids.add(tid)
    seen = {node.gid}
    frontier = [node]
    layers = []
    for _ in range(max_distance):
        nxt = []
        for v in frontier:
            for e in v.out_edges(ctx.view):
                if any_dir or e.edge_type in out_ids:
                    o = e.to_vertex()
                    if o.gid not in seen:
                        seen.add(o.gid)
                        nxt.append(o)
            for e in v.in_edges(ctx.view):
                if any_dir or e.edge_type in in_ids:
                    o = e.from_vertex()
                    if o.gid not in seen:
                        seen.add(o.gid)
                        nxt.append(o)
        if not nxt:
            break
        layers.append(nxt)
        frontier = nxt
    return layers


@mgp.read_proc("neighbors.at_hop",
               args=[("node", "NODE"), ("rel_type", "LIST"),
                     ("distance", "INTEGER")],
               results=[("nodes", "NODE")])
def neighbors_at_hop(ctx, node, rel_type, distance):
    if distance < 1:
        raise QueryException("distance must be a positive integer")
    layers = _hop_frontiers(ctx, node, rel_type, distance)
    if len(layers) >= distance:
        for v in layers[distance - 1]:
            yield {"nodes": v}


@mgp.read_proc("neighbors.by_hop",
               args=[("node", "NODE"), ("rel_type", "LIST"),
                     ("distance", "INTEGER")],
               results=[("nodes", "LIST")])
def neighbors_by_hop(ctx, node, rel_type, distance):
    if distance < 1:
        raise QueryException("distance must be a positive integer")
    layers = _hop_frontiers(ctx, node, rel_type, distance)
    for k in range(distance):
        yield {"nodes": layers[k] if k < len(layers) else []}


# --- meta --------------------------------------------------------------------


_META_RESULTS = [("labelCount", "INTEGER"),
                 ("relationshipTypeCount", "INTEGER"),
                 ("propertyKeyCount", "INTEGER"),
                 ("nodeCount", "INTEGER"),
                 ("relationshipCount", "INTEGER"),
                 ("labels", "MAP"), ("relationshipTypes", "MAP"),
                 ("relationshipTypesCount", "MAP"), ("stats", "MAP")]


def _meta_stats(ctx):
    """Result fields and key formats follow the reference meta_module
    (algorithm/meta.hpp kReturnStats1-9, meta.cpp UpdateRelationshipTypes:
    "(:Label)-[:TYPE]->()" / "()-[:TYPE]->(:Label)" keys)."""
    label_mapper = ctx.storage.label_mapper
    type_mapper = ctx.storage.edge_type_mapper
    labels = collections.Counter()
    rel_types = collections.Counter()
    rel_types_cnt = collections.Counter()
    node_count = 0
    rel_count = 0
    for v in ctx.accessor.vertices(ctx.view):
        node_count += 1
        for lid in v.labels(ctx.view):
            labels[label_mapper.id_to_name(lid)] += 1
        for e in v.out_edges(ctx.view):
            rel_count += 1
            type_name = type_mapper.id_to_name(e.edge_type)
            rel_types_cnt[type_name] += 1
            for lid in e.from_vertex().labels(ctx.view):
                key = f"(:{label_mapper.id_to_name(lid)})-" \
                      f"[:{type_name}]->()"
                rel_types[key] += 1
            for lid in e.to_vertex().labels(ctx.view):
                key = f"()-[:{type_name}]->" \
                      f"(:{label_mapper.id_to_name(lid)})"
                rel_types[key] += 1
    out = {
        "labelCount": len(labels),
        "relationshipTypeCount": len(rel_types_cnt),
        "propertyKeyCount": len(ctx.storage.property_mapper.all_names()),
        "nodeCount": node_count,
        "relationshipCount": rel_count,
        "labels": dict(labels),
        "relationshipTypes": dict(rel_types),
        "relationshipTypesCount": dict(rel_types_cnt),
    }
    out["stats"] = dict(out)
    return out


@mgp.read_proc("meta.stats_online", results=_META_RESULTS)
def meta_stats_online(ctx):
    yield _meta_stats(ctx)


@mgp.read_proc("meta.stats_offline", results=_META_RESULTS)
def meta_stats_offline(ctx):
    yield _meta_stats(ctx)


# --- path --------------------------------------------------------------------


@mgp.read_proc("path.expand",
               args=[("start", "ANY"), ("relationships", "LIST"),
                     ("labels", "LIST"), ("min_hops", "INTEGER"),
                     ("max_hops", "INTEGER")],
               results=[("result", "PATH")])
def path_expand(ctx, start, relationships, labels, min_hops, max_hops):
    """BFS path expansion with relationship-direction patterns ("TYPE>",
    "<TYPE", "TYPE") and label filters ("+Allowed", "-Forbidden"),
    following the reference path_module Expand."""
    from ..query.values import Path
    starts = start if isinstance(start, (list, tuple)) else [start]
    allow, deny = set(), set()
    for spec in labels or []:
        if spec.startswith("-"):
            deny.add(spec[1:])
        else:
            allow.add(spec.lstrip("+"))
    out_ids, in_ids, any_dir = set(), set(), not relationships
    for pattern in relationships or []:
        name = pattern.strip("<>")
        tid = ctx.storage.edge_type_mapper.maybe_name_to_id(name)
        if tid is None:
            continue
        if not pattern.startswith("<"):
            out_ids.add(tid)
        if not pattern.endswith(">"):
            in_ids.add(tid)

    def label_ok(v):
        names = {ctx.storage.label_mapper.id_to_name(l)
                 for l in v.labels(ctx.view)}
        if names & deny:
            return False
        return not allow or bool(names & allow)

    for s in starts:
        stack = [(s, [s], [])]
        while stack:
            cur, nodes, edges = stack.pop()
            if len(edges) >= min_hops:
                items = [nodes[0]]
                for k, e in enumerate(edges):
                    items.extend([e, nodes[k + 1]])
                yield {"result": Path(items)}
            if len(edges) >= max_hops:
                continue
            steps = []
            for e in cur.out_edges(ctx.view):
                if any_dir or e.edge_type in out_ids:
                    steps.append((e, e.to_vertex()))
            for e in cur.in_edges(ctx.view):
                if any_dir or e.edge_type in in_ids:
                    steps.append((e, e.from_vertex()))
            for e, nxt in steps:
                if any(nxt.gid == v.gid for v in nodes):
                    continue
                if not label_ok(nxt):
                    continue
                stack.append((nxt, nodes + [nxt], edges + [e]))


@mgp.read_proc("path.subgraph_nodes",
               args=[("start", "ANY"), ("config", "MAP")],
               results=[("nodes", "NODE")])
def path_subgraph_nodes(ctx, start, config):
    for v in _subgraph(ctx, start, config):
        yield {"nodes": v}


@mgp.read_proc("path.subgraph_all",
               args=[("start", "ANY"), ("config", "MAP")],
               results=[("nodes", "LIST"), ("rels", "LIST")])
def path_subgraph_all(ctx, start, config):
    nodes = _subgraph(ctx, start, config)
    gids = {v.gid for v in nodes}
    rels = []
    for v in nodes:
        for e in v.out_edges(ctx.view):
            if e.to_vertex().gid in gids:
                rels.append(e)
    yield {"nodes": nodes, "rels": rels}


def _subgraph(ctx, start, config):
    config = config or {}
    max_level = config.get("max_level", -1)
    max_level = float("inf") if max_level is None or max_level < 0 \
        else int(max_level)
    starts = start if isinstance(start, (list, tuple)) else [start]
    seen = {v.gid: v for v in starts}
    frontier = list(starts)
    level = 0
    while frontier and level < max_level:
        nxt = []
        for v in frontier:
            for e in list(v.out_edges(ctx.view)) + list(v.in_edges(ctx.view)):
                o = e.to_vertex() if e.from_vertex().gid == v.gid \
                    else e.from_vertex()
                if o.gid not in seen:
                    seen[o.gid] = o
                    nxt.append(o)
        frontier = nxt
        level += 1
    return list(seen.values())


# --- merge -------------------------------------------------------------------


@mgp.write_proc("merge.node",
                args=[("labels", "LIST"), ("identProps", "MAP"),
                      ("createProps", "MAP"), ("matchProps", "MAP")],
                results=[("node", "NODE")])
def merge_node(ctx, labels, identProps, createProps, matchProps):
    """MERGE semantics: find a node carrying all labels + identProps; on
    create also set createProps, on match also set matchProps (reference
    merge_module Node)."""
    if not identProps:
        raise QueryException("merge.node requires non-empty identProps")
    lids = [ctx.storage.label_mapper.name_to_id(name) for name in labels]
    pid_of = ctx.storage.property_mapper.name_to_id
    ident = {pid_of(k): v for k, v in identProps.items()}
    for v in ctx.accessor.vertices(ctx.view):
        if all(v.has_label(l, ctx.view) for l in lids) and \
                all(v.get_property(p, ctx.view) == val
                    for p, val in ident.items()):
            for k, val in (matchProps or {}).items():
                v.set_property(pid_of(k), val)
            yield {"node": v}
            return
    v = ctx.accessor.create_vertex()
    for l in lids:
        v.add_label(l)
    for p, val in ident.items():
        v.set_property(p, val)
    for k, val in (createProps or {}).items():
        v.set_property(pid_of(k), val)
    yield {"node": v}


@mgp.write_proc("merge.relationship",
                args=[("startNode", "NODE"), ("relationshipType", "STRING"),
                      ("identProps", "MAP"), ("createProps", "MAP"),
                      ("endNode", "NODE"), ("matchProps", "MAP")],
                results=[("rel", "RELATIONSHIP")])
def merge_relationship(ctx, startNode, relationshipType, identProps,
                       createProps, endNode, matchProps):
    tid = ctx.storage.edge_type_mapper.name_to_id(relationshipType)
    pid_of = ctx.storage.property_mapper.name_to_id
    ident = {pid_of(k): v for k, v in (identProps or {}).items()}
    for e in startNode.out_edges(ctx.view, edge_types=[tid]):
        if e.to_vertex().gid != endNode.gid:
            continue
        if all(e.get_property(p, ctx.view) == val
               for p, val in ident.items()):
            for k, val in (matchProps or {}).items():
                e.set_property(pid_of(k), val)
            yield {"rel": e}
            return
    e = ctx.accessor.create_edge(startNode, endNode, tid)
    for p, val in ident.items():
        e.set_property(p, val)
    for k, val in (createProps or {}).items():
        e.set_property(pid_of(k), val)
    yield {"rel": e}


# --- distance_calculator -----------------------------------------------------


def _node_latlng(ctx, node, metrics_ignored=None):
    lat_pid = ctx.storage.property_mapper.maybe_name_to_id("lat")
    lng_pid = ctx.storage.property_mapper.maybe_name_to_id("lng")
    lat = node.get_property(lat_pid, ctx.view) if lat_pid is not None \
        else None
    lng = node.get_property(lng_pid, ctx.view) if lng_pid is not None \
        else None
    if lat is None or lng is None:
        raise QueryException(
            "distance_calculator nodes need 'lat' and 'lng' properties")
    return float(lat), float(lng)


_METRIC_SCALE = {"m": 1.0, "km": 1 / 1000.0}


@mgp.read_proc("distance_calculator.single",
               args=[("start", "NODE"), ("end", "NODE")],
               opt_args=[("metrics", "STRING", "m")],
               results=[("distance", "FLOAT")])
def distance_single(ctx, start, end, metrics="m"):
    scale = _METRIC_SCALE.get(metrics)
    if scale is None:
        raise QueryException('metrics must be "m" or "km"')
    d = _haversine(_node_latlng(ctx, start), _node_latlng(ctx, end))
    yield {"distance": d * scale}


@mgp.read_proc("distance_calculator.multiple",
               args=[("start_points", "LIST"), ("end_points", "LIST")],
               opt_args=[("metrics", "STRING", "m")],
               results=[("distances", "LIST")])
def distance_multiple(ctx, start_points, end_points, metrics="m"):
    scale = _METRIC_SCALE.get(metrics)
    if scale is None:
        raise QueryException('metrics must be "m" or "km"')
    if len(start_points) != len(end_points):
        raise QueryException(
            "start_points and end_points must be the same length")
    yield {"distances": [
        _haversine(_node_latlng(ctx, a), _node_latlng(ctx, b)) * scale
        for a, b in zip(start_points, end_points)]}


# --- periodic ----------------------------------------------------------------


def _sub_interpreter(ctx):
    """Interpreter for sub-queries run on behalf of the calling user:
    NOT a system session — RBAC applies with the caller's username, so a
    read-only user cannot escalate through do.*/periodic.* sub-queries."""
    from ..query.interpreter import Interpreter
    ictx = getattr(ctx.exec_ctx, "interpreter_context", None)
    if ictx is None:
        raise QueryException(
            "do.*/periodic.* require a server interpreter context")
    interp = Interpreter(ictx)
    eval_ctx = getattr(ctx.exec_ctx, "eval_ctx", None)
    interp.username = getattr(eval_ctx, "username", "") or ""
    return interp


@mgp.read_proc("periodic.iterate",
               args=[("input_query", "STRING"),
                     ("running_query", "STRING"), ("config", "MAP")],
               results=[("success", "BOOLEAN"),
                        ("number_of_executed_batches", "INTEGER")])
def periodic_iterate(ctx, input_query, running_query, config):
    """Stream input_query rows, batch them, and run running_query once per
    batch with each input column bound per-row — the reference's prefix
    construction (periodic_module/periodic.cpp ConstructQueryPrefix):
    'UNWIND $__batch AS __batch_row WITH __batch_row.col AS col ...' with
    node/relationship columns re-matched by id, committed per batch."""
    config = config or {}
    batch_size = int(config.get("batch_size", 1000))
    if batch_size <= 0:
        raise QueryException("batch_size must be a positive integer")
    interp = _sub_interpreter(ctx)
    columns, rows, _ = interp.execute(input_query)
    if not columns:
        yield {"success": True, "number_of_executed_batches": 0}
        return
    # classify columns from the first row (reference: by value type)
    from ..storage.storage import EdgeAccessor, VertexAccessor
    node_cols, rel_cols, prim_cols = set(), set(), set()
    for k, col in enumerate(columns):
        sample = rows[0][k] if rows else None
        if isinstance(sample, VertexAccessor):
            node_cols.add(col)
        elif isinstance(sample, EdgeAccessor):
            rel_cols.add(col)
        else:
            prim_cols.add(col)
    with_parts = []
    match_parts = []
    for col in columns:
        if col in node_cols:
            with_parts.append(f"__batch_row.{col} AS __{col}_id")
            match_parts.append(f"MATCH ({col}) WHERE id({col}) = __{col}_id")
        elif col in rel_cols:
            with_parts.append(f"__batch_row.{col} AS __{col}_id")
            match_parts.append(
                f"MATCH ()-[{col}]->() WHERE id({col}) = __{col}_id")
        else:
            with_parts.append(f"__batch_row.{col} AS {col}")
    prefix = ("UNWIND $__batch AS __batch_row WITH "
              + ", ".join(with_parts)
              + (" " + " ".join(match_parts) if match_parts else " "))
    batches = 0
    runner = _sub_interpreter(ctx)
    try:
        for i in range(0, len(rows), batch_size):
            batch = rows[i:i + batch_size]
            payload = []
            for r in batch:
                entry = {}
                for k, col in enumerate(columns):
                    v = r[k]
                    entry[col] = v.gid if col in node_cols or \
                        col in rel_cols else v
                payload.append(entry)
            runner.execute(prefix + " " + running_query,
                           {"__batch": payload})
            batches += 1
    except Exception:
        import logging
        logging.getLogger("memgraph_tpu.periodic").exception(
            "periodic.iterate batch %d failed", batches + 1)
        yield {"success": False, "number_of_executed_batches": batches}
        return
    yield {"success": True, "number_of_executed_batches": batches}


@mgp.read_proc("periodic.delete",
               args=[("config", "MAP")],
               results=[("success", "BOOLEAN"),
                        ("number_of_deleted_nodes", "INTEGER")])
def periodic_delete(ctx, config):
    """Delete nodes matching config.labels in batches of config.batch_size
    (reference periodic_module Delete)."""
    config = config or {}
    batch_size = int(config.get("batch_size", 1000))
    if batch_size <= 0:
        raise QueryException("batch_size must be a positive integer")
    labels = config.get("labels", [])
    where = ""
    if labels:
        where = ":" + ":".join(labels)
    interp = _sub_interpreter(ctx)
    total = 0
    while True:
        _, rows, _ = interp.execute(
            f"MATCH (n{where}) WITH n LIMIT $lim DETACH DELETE n "
            f"RETURN count(n)", {"lim": batch_size})
        deleted = rows[0][0] if rows else 0
        total += deleted
        if deleted < batch_size:
            break
    yield {"success": True, "number_of_deleted_nodes": total}


# --- do ----------------------------------------------------------------------


def _is_global_operation(query):
    """Parse and classify (the reference inspects the parsed query too:
    do_module IsGlobalOperation) — substring checks both miss legal
    whitespace variants and false-positive on string literals."""
    from ..query.frontend import ast as A
    from ..query.frontend.parser import parse_with_source
    try:
        node = parse_with_source(query)
    # mglint: disable=MG003 — classification only; execution re-parses
    # and surfaces the real syntax error to the caller
    except Exception:
        return False
    return isinstance(node, (A.IndexQuery, A.ConstraintQuery,
                             A.IsolationLevelQuery, A.StorageModeQuery))


def _run_conditional_query(ctx, query, params):
    """Execute a sub-query for do.case/do.when, yielding each result row as
    a map (reference do_module InsertConditionalResults)."""
    if _is_global_operation(query):
        raise QueryException(
            f"The query {query} isn't supported by `do` because it "
            f"would execute a global operation.")
    interp = _sub_interpreter(ctx)
    columns, rows, _ = interp.execute(query, params or {})
    for row in rows:
        yield {"value": dict(zip(columns, row))}


@mgp.read_proc("do.when",
               args=[("condition", "BOOLEAN"), ("if_query", "STRING"),
                     ("else_query", "STRING")],
               opt_args=[("params", "MAP", None)],
               results=[("value", "MAP")])
def do_when(ctx, condition, if_query, else_query, params=None):
    yield from _run_conditional_query(
        ctx, if_query if condition else else_query, params)


@mgp.read_proc("do.case",
               args=[("conditionals", "LIST"), ("else_query", "STRING")],
               opt_args=[("params", "MAP", None)],
               results=[("value", "MAP")])
def do_case(ctx, conditionals, else_query, params=None):
    if not conditionals:
        raise QueryException("Conditionals list must not be empty!")
    if len(conditionals) % 2:
        raise QueryException("Size of the conditionals size must be even!")
    for i, item in enumerate(conditionals):
        if i % 2 == 0 and not isinstance(item, bool):
            raise QueryException(
                f"Argument on index {i} in do.case conditionals is not "
                f"bool!")
        if i % 2 == 1 and not isinstance(item, str):
            raise QueryException(
                f"Argument on index {i} in do.case conditionals is not "
                f"string!")
    query = else_query
    for i in range(0, len(conditionals), 2):
        if conditionals[i]:
            query = conditionals[i + 1]
            break
    yield from _run_conditional_query(ctx, query, params)
