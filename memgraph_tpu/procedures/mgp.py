"""Module-author API: decorators for registering procedures.

Counterpart of the reference's include/mgp.py decorator surface
(@mgp.read_proc / @mgp.write_proc): a procedure declares its result fields
and receives a ProcedureContext as first argument. Registration happens at
import time into the global registry.

    from memgraph_tpu.procedures import mgp

    @mgp.read_proc("my_module.my_proc",
                   args=[("limit", "INTEGER")],
                   results=[("node", "NODE"), ("score", "FLOAT")])
    def my_proc(ctx, limit=10):
        graph = ctx.device_graph()
        ...
        yield {"node": ctx.vertex_by_index(graph, 0), "score": 1.0}
"""

from __future__ import annotations

from ..query.procedures.registry import Procedure, global_registry


def read_proc(name: str, args=None, opt_args=None, results=None):
    def deco(fn):
        global_registry.register(Procedure(
            name=name, func=fn, args=args or [], opt_args=opt_args or [],
            results=results or [], is_write=False))
        return fn
    return deco


def write_proc(name: str, args=None, opt_args=None, results=None):
    def deco(fn):
        global_registry.register(Procedure(
            name=name, func=fn, args=args or [], opt_args=opt_args or [],
            results=results or [], is_write=True))
        return fn
    return deco
