"""embeddings.* — device-batched node text embeddings.

Counterpart of /root/reference/mage/python/embeddings.py (+
embed_worker): build a "sentence" per node from its labels/properties,
encode all sentences in device-sized batches, write the vectors to a
node property (composing with the vector index / knn procedures).

TPU-first redesign of the compute path: the reference shards texts over
GPU workers running sentence-transformers; here the default encoder is
a feature-hashing n-gram projection evaluated as ONE batched matmul per
chunk on the device (deterministic, dependency-free, MXU-shaped). When
a HuggingFace model is available locally, `model` config switches to it
(gated import — this image has transformers but no model weights/egress,
so the hashing encoder is the always-works default).
"""

from __future__ import annotations

import numpy as np

from ..exceptions import QueryException
from . import mgp

_N_FEATURES = 1 << 14          # hashed n-gram vocabulary
_SEED = 1234567


def build_text(vertex, label_names, prop_named, excluded) -> str:
    """Node sentence: labels + 'key: value' pairs, property-name sorted
    (reference: embeddings.build_texts)."""
    parts = [" ".join(label_names)]
    for key, value in sorted(prop_named.items()):
        if key in excluded or value is None:
            continue
        parts.append(f"{key}: {value}")
    return " ".join(p for p in parts if p).strip()


def _hash_tokens(text: str):
    """Word unigrams + character trigrams -> hashed feature ids."""
    import zlib
    ids = []
    for tok in text.lower().split():
        ids.append(zlib.crc32(tok.encode()) % _N_FEATURES)
        for i in range(len(tok) - 2):
            ids.append(zlib.crc32(tok[i:i + 3].encode("utf-8"))
                       % _N_FEATURES)
    return ids


def hashing_encode(texts, dimension: int, batch_size: int = 2048):
    """Deterministic feature-hash embedding: sparse counts x a fixed
    random projection, one device matmul per chunk, L2-normalized."""
    import jax
    import jax.numpy as jnp
    key = jax.random.PRNGKey(_SEED)
    proj = jax.random.normal(key, (_N_FEATURES, dimension),
                             dtype=jnp.float32) / np.sqrt(dimension)

    @jax.jit
    def _encode(counts):                     # (B, F) -> (B, D)
        emb = counts @ proj
        norm = jnp.linalg.norm(emb, axis=1, keepdims=True)
        return emb / jnp.maximum(norm, 1e-12)

    out = np.zeros((len(texts), dimension), dtype=np.float32)
    for lo in range(0, len(texts), batch_size):
        chunk = texts[lo:lo + batch_size]
        counts = np.zeros((batch_size, _N_FEATURES), dtype=np.float32)
        for i, t in enumerate(chunk):
            for fid in _hash_tokens(t):
                counts[i, fid] += 1.0
        out[lo:lo + len(chunk)] = np.asarray(_encode(counts))[:len(chunk)]
    return out


def _transformer_encode(texts, model_name, batch_size):
    try:
        import torch
        from transformers import AutoModel, AutoTokenizer
    except ImportError as e:
        raise QueryException(
            "embeddings: transformers/torch are not available") from e
    tok = AutoTokenizer.from_pretrained(model_name)
    model = AutoModel.from_pretrained(model_name)
    model.eval()
    outs = []
    with torch.no_grad():
        for lo in range(0, len(texts), batch_size):
            batch = tok(texts[lo:lo + batch_size], padding=True,
                        truncation=True, return_tensors="pt")
            hidden = model(**batch).last_hidden_state
            mask = batch["attention_mask"].unsqueeze(-1)
            emb = (hidden * mask).sum(1) / mask.sum(1).clamp(min=1)
            outs.append(torch.nn.functional.normalize(emb, dim=1).numpy())
    return np.concatenate(outs)


def _gather(ctx, excluded):
    storage = ctx.accessor.storage
    nodes, texts = [], []
    for va in ctx.accessor.vertices():
        labels = [storage.label_mapper.id_to_name(l) for l in va.labels()]
        props = {storage.property_mapper.id_to_name(pid): val
                 for pid, val in va.properties().items()}
        nodes.append(va)
        texts.append(build_text(va, labels, props, excluded))
    return nodes, texts


@mgp.write_proc("embeddings.compute_embeddings",
                opt_args=[("configuration", "MAP", None)],
                results=[("success", "BOOLEAN"),
                         ("count", "INTEGER"),
                         ("dimension", "INTEGER")])
def compute_embeddings(ctx, configuration=None):
    cfg = dict(configuration or {})
    prop_name = cfg.get("embedding_property", "embedding")
    dimension = int(cfg.get("dimension", 256))
    batch_size = int(cfg.get("batch_size", 2048))
    model = cfg.get("model")          # None -> hashing encoder
    excluded = set(cfg.get("excluded_properties") or [prop_name])
    excluded.add(prop_name)
    if dimension <= 0 or batch_size <= 0:
        raise QueryException("embeddings: dimension and batch_size "
                             "must be positive")
    nodes, texts = _gather(ctx, excluded)
    if not nodes:
        yield {"success": True, "count": 0, "dimension": dimension}
        return
    if model:
        vecs = _transformer_encode(texts, model, batch_size)
        dimension = vecs.shape[1]
    else:
        vecs = hashing_encode(texts, dimension, batch_size)
    pid = ctx.accessor.storage.property_mapper.name_to_id(prop_name)
    for va, vec in zip(nodes, vecs):
        va.set_property(pid, [float(x) for x in vec])
    yield {"success": True, "count": len(nodes), "dimension": dimension}


@mgp.write_proc("embeddings.node_sentence",
                opt_args=[("configuration", "MAP", None)],
                results=[("node", "NODE"), ("sentence", "STRING")])
def node_sentence(ctx, configuration=None):
    """The sentence each node would be embedded with (debugging aid,
    reference: embeddings.node_sentence)."""
    cfg = dict(configuration or {})
    excluded = set(cfg.get("excluded_properties") or [])
    excluded.add(cfg.get("embedding_property", "embedding"))
    nodes, texts = _gather(ctx, excluded)
    for va, text in zip(nodes, texts):
        yield {"node": va, "sentence": text}


@mgp.read_proc("embeddings.model_info",
               opt_args=[("configuration", "MAP", None)],
               results=[("name", "STRING"), ("dimension", "INTEGER"),
                        ("device", "STRING")])
def model_info(ctx, configuration=None):
    cfg = dict(configuration or {})
    model = cfg.get("model")
    if model:
        yield {"name": model, "dimension": -1, "device": "cpu"}
        return
    import jax
    yield {"name": "feature-hashing/ngram-projection",
           "dimension": int(cfg.get("dimension", 256)),
           "device": jax.default_backend()}
