"""GraphRAG hybrid retrieval: vector kNN → k-hop expand → PageRank rerank.

The BASELINE.md config #5 pipeline (reference pieces:
query_modules/vector_search_module.cpp + hops expansion + pagerank rerank,
with mage/python/llm_util formatting the retrieved context). Every stage
runs on device: MXU matmul kNN seeds, Bellman-Ford k-hop frontier, and
personalized PageRank restarted on the seed set — one pipeline, no
host round-trips between stages beyond index bookkeeping.
"""

from __future__ import annotations

import numpy as np

from . import mgp


@mgp.read_proc("graphrag.retrieve",
               args=[("property", "STRING"), ("query_vector", "LIST"),
                     ("k_seeds", "INTEGER")],
               opt_args=[("hops", "INTEGER", 2),
                         ("limit", "INTEGER", 10),
                         ("damping", "FLOAT", 0.85),
                         ("metric", "STRING", "cosine")],
               results=[("node", "NODE"), ("score", "FLOAT"),
                        ("seed_similarity", "FLOAT")])
def retrieve(ctx, property, query_vector, k_seeds, hops=2, limit=10,
             damping=0.85, metric="cosine"):
    """Hybrid retrieval over the current graph snapshot."""
    import jax.numpy as jnp
    from ..ops.pagerank import personalized_pagerank
    from ..ops.traversal import khop_neighborhood
    from .vector_search import _get_index, _search_entry

    entry = _get_index(ctx, str(property))
    if entry.matrix is None:
        return
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return

    # 1) seed selection: vector kNN over the embedding index (MXU,
    #    delta-maintained — streaming GraphRAG never full-rebuilds)
    q = jnp.asarray(np.asarray([query_vector], dtype=np.float32))
    sims, idx = _search_entry(entry, q, int(k_seeds), str(metric))
    if sims is None:
        return
    sims = np.asarray(sims[0])
    idx = np.asarray(idx[0])
    seed_sim: dict[int, float] = {}
    seed_indices = []
    for sim, i in zip(sims, idx):
        gid = entry.row_gids[int(i)]
        if gid is None:
            continue
        di = graph.gid_to_idx.get(gid)
        if di is not None:
            seed_indices.append(di)
            seed_sim[di] = float(sim)
    if not seed_indices:
        return

    # 2+3) expansion + rerank. With a resident kernel server configured
    # the whole tail is ONE coalesced round trip: the seeds restart a
    # personalized-PageRank fixpoint batched with every concurrent
    # retrieve/search on the daemon, the server extracts the top-k on
    # device, and repeats ride its change-log-invalidated result cache.
    # PPR mass localizes around the restart set, so the top-k IS the
    # neighborhood expansion + rerank in one step.
    from .graph_algorithms import _kernel_server_ppr
    served = _kernel_server_ppr(ctx, graph, seed_indices, float(damping),
                                100, 1e-6, top_k=int(limit))
    if served is not None:
        _h, out = served
        for score, i in zip(out["topk_val"], out["topk_idx"]):
            if score <= 0:
                break
            node = ctx.vertex_by_index(graph, int(i))
            if node is not None:
                yield {"node": node, "score": float(score),
                       "seed_similarity": seed_sim.get(int(i), 0.0)}
        return

    # in-process fallback: k-hop neighborhood mask (device frontier)
    # then personalized PageRank restarted on the seeds
    mask = np.asarray(khop_neighborhood(graph, seed_indices, int(hops),
                                        directed=False))
    ranks, _, _ = personalized_pagerank(graph, seed_indices,
                                        damping=float(damping),
                                        max_iterations=100)
    ranks = np.asarray(ranks)
    scores = np.where(mask, ranks, 0.0)
    order = np.argsort(-scores)[:int(limit)]
    for i in order:
        if scores[i] <= 0:
            break
        node = ctx.vertex_by_index(graph, int(i))
        if node is not None:
            yield {"node": node, "score": float(scores[i]),
                   "seed_similarity": seed_sim.get(int(i), 0.0)}


@mgp.read_proc("graphrag.context",
               args=[("nodes", "LIST")],
               opt_args=[("include_edges", "BOOLEAN", True)],
               results=[("context", "STRING")])
def context(ctx, nodes, include_edges=True):
    """Format retrieved nodes (+ interconnecting edges) as LLM context —
    the llm_util analog (reference: mage/python/llm_util.py)."""
    storage = ctx.storage
    lm, pm, tm = (storage.label_mapper, storage.property_mapper,
                  storage.edge_type_mapper)
    lines = []
    gid_set = {n.gid for n in nodes if n is not None}
    for n in nodes:
        if n is None:
            continue
        labels = ":".join(lm.id_to_name(l) for l in n.labels(ctx.view))
        props = ", ".join(
            f"{pm.id_to_name(k)}: {v!r}"
            for k, v in sorted(n.properties(ctx.view).items())
            if not isinstance(v, list) or len(v) <= 8)
        lines.append(f"({labels} {{{props}}})")
        if include_edges:
            for ea in n.out_edges(ctx.view):
                if ea.to_vertex().gid in gid_set:
                    lines.append(
                        f"  -[{tm.id_to_name(ea.edge_type)}]-> "
                        f"node:{ea.to_vertex().gid}")
    yield {"context": "\n".join(lines)}


@mgp.read_proc("graphrag.schema",
               results=[("schema", "STRING")])
def schema(ctx):
    """Graph schema summary for Text2Cypher prompts (reference:
    SHOW SCHEMA INFO / llm_util schema formatting)."""
    storage = ctx.storage
    label_counts: dict[int, int] = {}
    edge_patterns: dict[tuple, int] = {}
    label_props: dict[int, set] = {}
    for va in ctx.accessor.vertices(ctx.view):
        for l in va.labels(ctx.view):
            label_counts[l] = label_counts.get(l, 0) + 1
            label_props.setdefault(l, set()).update(
                va.properties(ctx.view).keys())
    for ea in ctx.accessor.edges(ctx.view):
        src_labels = tuple(sorted(ea.from_vertex().labels(ctx.view)))
        dst_labels = tuple(sorted(ea.to_vertex().labels(ctx.view)))
        key = (src_labels, ea.edge_type, dst_labels)
        edge_patterns[key] = edge_patterns.get(key, 0) + 1
    lm, pm, tm = (storage.label_mapper, storage.property_mapper,
                  storage.edge_type_mapper)
    lines = ["Node labels:"]
    for l, count in sorted(label_counts.items()):
        props = ", ".join(sorted(pm.id_to_name(p)
                                 for p in label_props.get(l, ())))
        lines.append(f"  :{lm.id_to_name(l)} ({count} nodes) "
                     f"properties: [{props}]")
    lines.append("Relationships:")
    for (src, t, dst), count in sorted(edge_patterns.items(),
                                       key=lambda kv: -kv[1]):
        src_s = ":".join(lm.id_to_name(l) for l in src) or "?"
        dst_s = ":".join(lm.id_to_name(l) for l in dst) or "?"
        lines.append(f"  (:{src_s})-[:{tm.id_to_name(t)}]->(:{dst_s}) "
                     f"x{count}")
    yield {"schema": "\n".join(lines)}
