"""Structure modules: louvain, node similarity, bridges, cycles,
biconnected components, point index, nxalg bridge.

Counterparts: mage/cpp/{community_detection,node_similarity,bridges,cycles,
biconnected_components}_module and the reference's NetworkX bridge
(query_modules/nxalg.py, mgp_networkx.py) — the same delegation pattern:
export the visible graph, run the algorithm, stream rows.
"""

from __future__ import annotations

import numpy as np

from . import mgp


@mgp.read_proc("community_detection.louvain",
               opt_args=[("weight_property", "STRING", None)],
               results=[("node", "NODE"), ("community_id", "INTEGER"),
                        ("modularity", "FLOAT")])
def louvain_proc(ctx, weight_property=None):
    from ..ops.louvain import louvain
    graph = ctx.device_graph(weight_property=weight_property)
    if graph.n_nodes == 0:
        return
    comm, modularity = louvain(graph)
    for i in range(graph.n_nodes):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, "community_id": int(comm[i]) + 1,
                   "modularity": modularity}


@mgp.read_proc("node_similarity.jaccard",
               results=[("node1", "NODE"), ("node2", "NODE"),
                        ("similarity", "FLOAT")])
def jaccard_all(ctx):
    yield from _similarity_all(ctx, "jaccard")


@mgp.read_proc("node_similarity.overlap",
               results=[("node1", "NODE"), ("node2", "NODE"),
                        ("similarity", "FLOAT")])
def overlap_all(ctx):
    yield from _similarity_all(ctx, "overlap")


@mgp.read_proc("node_similarity.cosine",
               results=[("node1", "NODE"), ("node2", "NODE"),
                        ("similarity", "FLOAT")])
def cosine_all(ctx):
    yield from _similarity_all(ctx, "cosine")


def _similarity_all(ctx, mode):
    from ..ops.similarity import DENSE_LIMIT, similarity_matrix
    graph = ctx.device_graph()
    n = graph.n_nodes
    if n == 0:
        return
    if n > DENSE_LIMIT:
        from ..exceptions import ProcedureException
        raise ProcedureException(
            f"all-pairs similarity supports up to {DENSE_LIMIT} nodes; "
            f"use node_similarity.pairwise for larger graphs")
    sim = np.asarray(similarity_matrix(graph, mode))
    for i in range(n):
        ni = ctx.vertex_by_index(graph, i)
        if ni is None:
            continue
        for j in range(i + 1, n):
            if sim[i, j] <= 0:
                continue
            nj = ctx.vertex_by_index(graph, j)
            if nj is not None:
                yield {"node1": ni, "node2": nj,
                       "similarity": float(sim[i, j])}


@mgp.read_proc("node_similarity.pairwise",
               args=[("pairs", "LIST")],
               opt_args=[("mode", "STRING", "jaccard")],
               results=[("node1", "NODE"), ("node2", "NODE"),
                        ("similarity", "FLOAT")])
def pairwise(ctx, pairs, mode="jaccard"):
    from ..ops.similarity import pairwise_similarity
    graph = ctx.device_graph()
    index_pairs = []
    for pair in pairs:
        if not isinstance(pair, (list, tuple)) or len(pair) != 2:
            continue
        a, b = pair
        ia = graph.gid_to_idx.get(a.gid) if a is not None else None
        ib = graph.gid_to_idx.get(b.gid) if b is not None else None
        if ia is not None and ib is not None:
            index_pairs.append((ia, ib))
    for (i, j, score) in pairwise_similarity(graph, index_pairs, str(mode)):
        n1 = ctx.vertex_by_index(graph, i)
        n2 = ctx.vertex_by_index(graph, j)
        if n1 is not None and n2 is not None:
            yield {"node1": n1, "node2": n2, "similarity": float(score)}


def _nx_graph(ctx, graph, directed=False):
    import networkx as nx
    g = nx.DiGraph() if directed else nx.Graph()
    g.add_nodes_from(range(graph.n_nodes))
    src = np.asarray(graph.src_idx)[:graph.n_edges]
    dst = np.asarray(graph.col_idx)[:graph.n_edges]
    g.add_edges_from(zip(src.tolist(), dst.tolist()))
    return g


@mgp.read_proc("bridges.get",
               results=[("node_from", "NODE"), ("node_to", "NODE")])
def bridges_get(ctx):
    """Bridge edges (mage/cpp/bridges_module counterpart)."""
    import networkx as nx
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    g = _nx_graph(ctx, graph, directed=False)
    for (u, v) in nx.bridges(g):
        nu = ctx.vertex_by_index(graph, u)
        nv = ctx.vertex_by_index(graph, v)
        if nu is not None and nv is not None:
            yield {"node_from": nu, "node_to": nv}


@mgp.read_proc("cycles.get", results=[("cycle", "LIST")])
def cycles_get(ctx):
    """Simple cycles (mage/cpp/cycles_module counterpart; undirected base
    cycles via the cycle basis)."""
    import networkx as nx
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    g = _nx_graph(ctx, graph, directed=False)
    for cycle in nx.cycle_basis(g):
        nodes = [ctx.vertex_by_index(graph, v) for v in cycle]
        if all(n is not None for n in nodes):
            yield {"cycle": nodes}


@mgp.read_proc("biconnected_components.get",
               results=[("bcc_id", "INTEGER"), ("node_from", "NODE"),
                        ("node_to", "NODE")])
def biconnected_get(ctx):
    import networkx as nx
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    g = _nx_graph(ctx, graph, directed=False)
    for bcc_id, comp_edges in enumerate(nx.biconnected_component_edges(g)):
        for (u, v) in comp_edges:
            nu = ctx.vertex_by_index(graph, u)
            nv = ctx.vertex_by_index(graph, v)
            if nu is not None and nv is not None:
                yield {"bcc_id": bcc_id, "node_from": nu, "node_to": nv}


@mgp.read_proc("betweenness_centrality.get",
               opt_args=[("directed", "BOOLEAN", True),
                         ("normalized", "BOOLEAN", True),
                         ("samples", "INTEGER", 0)],
               results=[("node", "NODE"),
                        ("betweenness_centrality", "FLOAT")])
def betweenness_get(ctx, directed=True, normalized=True, samples=0):
    """Native batched-Brandes device kernel (ops/betweenness.py) —
    counterpart of /root/reference/mage/cpp/betweenness_centrality_module/
    (exact when samples=0, sampled approximation otherwise)."""
    import numpy as np
    from ..ops.betweenness import betweenness_centrality
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    bc = np.asarray(betweenness_centrality(
        graph, directed=bool(directed), normalized=bool(normalized),
        samples=int(samples) or None))
    for i, score in enumerate(bc):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, "betweenness_centrality": float(score)}


@mgp.read_proc("nxalg.betweenness_centrality",
               opt_args=[("normalized", "BOOLEAN", True)],
               results=[("node", "NODE"), ("betweenness", "FLOAT")])
def nx_betweenness(ctx, normalized=True):
    """Exact Brandes via the NetworkX bridge (reference: nxalg.py)."""
    import networkx as nx
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    g = _nx_graph(ctx, graph, directed=True)
    bc = nx.betweenness_centrality(g, normalized=bool(normalized))
    for i, score in bc.items():
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, "betweenness": float(score)}


# --- point index procedures --------------------------------------------------

@mgp.write_proc("point_index.create",
                args=[("label", "STRING"), ("property", "STRING")],
                results=[("status", "STRING")])
def point_index_create(ctx, label, property):
    from ..storage.point_index import point_indices
    point_indices(ctx.storage).create(str(label), str(property))
    yield {"status": "point index created"}


@mgp.write_proc("point_index.drop",
                args=[("label", "STRING"), ("property", "STRING")],
                results=[("status", "STRING")])
def point_index_drop(ctx, label, property):
    from ..storage.point_index import point_indices
    dropped = point_indices(ctx.storage).drop(str(label), str(property))
    yield {"status": "dropped" if dropped else "no such index"}


@mgp.read_proc("point_index.within_distance",
               args=[("label", "STRING"), ("property", "STRING"),
                     ("center", "POINT"), ("radius", "FLOAT")],
               results=[("node", "NODE"), ("distance", "FLOAT")])
def point_within_distance(ctx, label, property, center, radius):
    from ..storage.point_index import point_indices
    from ..exceptions import ProcedureException
    index = point_indices(ctx.storage).get(str(label), str(property))
    if index is None:
        raise ProcedureException("point index does not exist")
    for gid, dist in index.within_distance(center, float(radius)):
        node = ctx.accessor.find_vertex(gid, ctx.view)
        if node is not None:
            yield {"node": node, "distance": float(dist)}
