"""migrate.* — pull rows from external systems into Cypher pipelines.

Counterpart of the reference's cross-database migration module
(/root/reference/mage/python/cross_database.py: migrate.mysql/
postgresql/oracle_db/sql_server/duckdb/neo4j/s3/...): each procedure
streams the source's rows as `row` maps, composing with UNWIND/CREATE
for ingest. Drivers are optional — sqlite3 ships with CPython and is
fully functional; the rest raise a clear error when their client
library is absent.
"""

from __future__ import annotations

from ..exceptions import QueryException
from . import mgp


def _is_table_name(text: str) -> bool:
    return all(c.isalnum() or c in "._$" for c in text.strip()) \
        and bool(text.strip())


def _sql_for(table_or_sql: str) -> str:
    t = table_or_sql.strip()
    return f"SELECT * FROM {t}" if _is_table_name(t) else t


def _rows_from_cursor(cursor, columns=None):
    cols = columns or [d[0] for d in cursor.description]
    for rec in cursor:
        yield {"row": dict(zip(cols, rec))}


@mgp.read_proc("migrate.sqlite",
               args=[("table_or_sql", "STRING"), ("config", "MAP")],
               opt_args=[("params", "LIST", None)],
               results=[("row", "MAP")])
def migrate_sqlite(ctx, table_or_sql, config, params=None):
    """Rows from a sqlite database file; config: {"database": path}."""
    import sqlite3
    path = (config or {}).get("database")
    if not path:
        raise QueryException("migrate.sqlite: config.database is required")
    con = sqlite3.connect(path)
    try:
        cur = con.execute(_sql_for(table_or_sql), tuple(params or ()))
        yield from _rows_from_cursor(cur)
    finally:
        con.close()


def _gated(module_name, pip_name):
    try:
        return __import__(module_name)
    except ImportError as e:
        raise QueryException(
            f"migrate: the {pip_name!r} client library is not installed "
            f"in this environment") from e


@mgp.read_proc("migrate.mysql",
               args=[("table_or_sql", "STRING"), ("config", "MAP")],
               opt_args=[("params", "LIST", None)],
               results=[("row", "MAP")])
def migrate_mysql(ctx, table_or_sql, config, params=None):
    connector = _gated("mysql.connector", "mysql-connector-python")
    con = connector.connect(**(config or {}))
    try:
        cur = con.cursor()
        cur.execute(_sql_for(table_or_sql), tuple(params or ()))
        yield from _rows_from_cursor(cur)
    finally:
        con.close()


@mgp.read_proc("migrate.postgresql",
               args=[("table_or_sql", "STRING"), ("config", "MAP")],
               opt_args=[("params", "LIST", None)],
               results=[("row", "MAP")])
def migrate_postgresql(ctx, table_or_sql, config, params=None):
    psycopg2 = _gated("psycopg2", "psycopg2")
    con = psycopg2.connect(**(config or {}))
    try:
        cur = con.cursor()
        cur.execute(_sql_for(table_or_sql), tuple(params or ()))
        yield from _rows_from_cursor(cur)
    finally:
        con.close()


@mgp.read_proc("migrate.duckdb",
               args=[("table_or_sql", "STRING"), ("config", "MAP")],
               opt_args=[("params", "LIST", None)],
               results=[("row", "MAP")])
def migrate_duckdb(ctx, table_or_sql, config, params=None):
    duckdb = _gated("duckdb", "duckdb")
    con = duckdb.connect((config or {}).get("database", ":memory:"))
    try:
        cur = con.execute(_sql_for(table_or_sql), params or [])
        cols = [d[0] for d in cur.description]
        for rec in cur.fetchall():
            yield {"row": dict(zip(cols, rec))}
    finally:
        con.close()


@mgp.read_proc("migrate.neo4j",
               args=[("label_or_rel_or_query", "STRING"),
                     ("config", "MAP")],
               results=[("row", "MAP")])
def migrate_neo4j(ctx, label_or_rel_or_query, config):
    neo4j = _gated("neo4j", "neo4j")
    text = label_or_rel_or_query.strip()
    if _is_table_name(text):
        # a bare name is a node LABEL (relationship types are pulled
        # with an explicit MATCH ()-[r:T]->() query — the casing
        # heuristic the reference uses misroutes all-caps labels)
        query = f"MATCH (n:{text}) RETURN properties(n) AS props"
    else:
        query = text
    driver = neo4j.GraphDatabase.driver(
        (config or {}).get("uri", "bolt://localhost:7687"),
        auth=((config or {}).get("username", ""),
              (config or {}).get("password", "")))
    try:
        with driver.session() as session:
            for rec in session.run(query):
                yield {"row": dict(rec)}
    finally:
        driver.close()


@mgp.read_proc("migrate.s3",
               args=[("file_path", "STRING"), ("config", "MAP")],
               results=[("row", "MAP")])
def migrate_s3(ctx, file_path, config):
    """CSV object from S3; config: {"bucket", ...boto3 client kwargs}."""
    boto3 = _gated("boto3", "boto3")
    import csv
    import io
    cfg = dict(config or {})
    bucket = cfg.pop("bucket", None)
    if not bucket:
        raise QueryException("migrate.s3: config.bucket is required")
    client = boto3.client("s3", **cfg)
    body = client.get_object(Bucket=bucket, Key=file_path)["Body"]
    reader = csv.DictReader(io.TextIOWrapper(body, encoding="utf-8"))
    for row in reader:
        yield {"row": dict(row)}
