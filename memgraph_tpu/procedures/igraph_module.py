"""igraph-bridge and path-algorithm query modules.

Counterparts of the reference's igraph bridge (mage/python/igraphalg.py —
same procedure names, arguments, result fields) and the C++ algo module
(mage/cpp/algo_module — astar / all_simple_paths / cover). Where the
reference delegates to the igraph C library, this build routes bulk work
through the TPU kernels (pagerank, Bellman-Ford SSSP) or scipy.csgraph over
the same CSR export (spanning tree, all-pairs shortest paths); path
enumeration and A* run on the host adjacency, which is where pointer-chasing
belongs.
"""

from __future__ import annotations

import collections
import heapq
import math

import numpy as np

from ..exceptions import QueryException
from . import mgp
from .combinatorial_modules import _EARTH_RADIUS_M, _solve_max_flow


def _haversine(a, b):
    """Scalar great-circle distance in meters between (lat, lng) pairs."""
    la1, lo1, la2, lo2 = map(math.radians, (*a, *b))
    h = (math.sin((la2 - la1) / 2) ** 2
         + math.cos(la1) * math.cos(la2) * math.sin((lo2 - lo1) / 2) ** 2)
    return 2 * _EARTH_RADIUS_M * math.asin(min(1.0, math.sqrt(h)))

# --- helpers -----------------------------------------------------------------


def _dense_index(ctx, graph, vertex):
    idx = graph.gid_to_idx.get(vertex.gid)
    if idx is None:
        raise QueryException("vertex is not part of the current graph")
    return int(idx)


def _host_adjacency(ctx, directed=True, weight_property=None,
                    edge_types=None):
    """gid -> [(gid, weight, edge)]; None weight_property -> weight 1.0."""
    pid = None
    if weight_property is not None:
        pid = ctx.storage.property_mapper.maybe_name_to_id(weight_property)
    type_ids = None
    if edge_types:
        type_ids = {ctx.storage.edge_type_mapper.maybe_name_to_id(t)
                    for t in edge_types}
        type_ids.discard(None)
    adj = collections.defaultdict(list)
    for v in ctx.accessor.vertices(ctx.view):
        adj[v.gid]
        for e in v.out_edges(ctx.view):
            if type_ids is not None and e.edge_type not in type_ids:
                continue
            w = 1.0
            if pid is not None:
                val = e.get_property(pid, ctx.view)
                w = float(val) if val is not None else 1.0
            adj[v.gid].append((e.to_vertex().gid, w, e))
            if not directed:
                adj[e.to_vertex().gid].append((v.gid, w, e))
    return adj


def _scipy_csr(ctx, weight_property, directed):
    """(scipy matrix, DeviceGraph) over the cached CSR export.

    Parallel edges keep the MINIMUM weight (shortest-path semantics —
    csr_matrix's default COO handling would sum them), and undirected
    graphs take the minimum of reciprocal directed weights."""
    from scipy.sparse import csr_matrix
    graph = ctx.device_graph(weight_property=weight_property)
    n, m = graph.n_nodes, graph.n_edges
    src = np.asarray(graph.src_idx[:m])
    dst = np.asarray(graph.col_idx[:m])
    w = np.asarray(graph.weights[:m], dtype=np.float64)
    if not directed:
        src, dst = np.concatenate([src, dst]), np.concatenate([dst, src])
        w = np.concatenate([w, w])
    if len(src):
        order = np.lexsort((w, dst, src))
        src, dst, w = src[order], dst[order], w[order]
        first = np.ones(len(src), dtype=bool)
        first[1:] = (src[1:] != src[:-1]) | (dst[1:] != dst[:-1])
        src, dst, w = src[first], dst[first], w[first]
    mat = csr_matrix((w, (src, dst)), shape=(n, n))
    return mat, graph


# --- igraphalg ---------------------------------------------------------------


@mgp.read_proc("igraphalg.pagerank",
               opt_args=[("damping", "FLOAT", 0.85),
                         ("weights", "STRING", None),
                         ("directed", "BOOLEAN", True),
                         ("implementation", "STRING", "prpack")],
               results=[("node", "NODE"), ("rank", "FLOAT")])
def igraph_pagerank(ctx, damping=0.85, weights=None, directed=True,
                    implementation="prpack"):
    if implementation not in ("prpack", "arpack"):
        raise QueryException(
            'Implementation argument value can be "prpack" or "arpack"')
    from ..ops.csr import from_coo
    from ..ops.pagerank import pagerank
    graph = ctx.device_graph(weight_property=weights)
    if graph.n_nodes == 0:
        return
    if not directed:
        # symmetrize before the kernel (each edge walks both ways)
        m = graph.n_edges
        src = np.asarray(graph.src_idx[:m])
        dst = np.asarray(graph.col_idx[:m])
        w = np.asarray(graph.weights[:m])
        sym = from_coo(np.concatenate([src, dst]),
                       np.concatenate([dst, src]),
                       np.concatenate([w, w]), n_nodes=graph.n_nodes,
                       node_gids=np.asarray(graph.node_gids))
        ranks, _, _ = pagerank(sym, damping=float(damping))
        graph = sym
    else:
        ranks, _, _ = pagerank(graph, damping=float(damping))
    ranks = np.asarray(ranks)
    for i in range(graph.n_nodes):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, "rank": float(ranks[i])}


@mgp.read_proc("igraphalg.maxflow",
               args=[("source", "NODE"), ("target", "NODE")],
               opt_args=[("capacity", "STRING", "weight")],
               results=[("max_flow", "FLOAT")])
def igraph_maxflow(ctx, source, target, capacity="weight"):
    _, total, _ = _solve_max_flow(ctx, source, target, capacity)
    yield {"max_flow": float(total)}


def _simple_path_chains(adj, start_gid, end_gid, max_edges):
    """All simple start->end chains as (node_gids, edges) pairs, DFS with
    at most max_edges hops. Single enumerator shared by the igraphalg and
    algo variants (they differ only in output shape)."""
    stack = [(start_gid, [start_gid], [])]
    while stack:
        cur, nodes, edges = stack.pop()
        if cur == end_gid and edges:
            yield nodes, edges
            continue
        if len(edges) >= max_edges:
            continue
        for nb, _, e in adj.get(cur, ()):
            if nb not in nodes:
                stack.append((nb, nodes + [nb], edges + [e]))


@mgp.read_proc("igraphalg.get_all_simple_paths",
               args=[("v", "NODE"), ("to", "NODE")],
               opt_args=[("cutoff", "INTEGER", -1)],
               results=[("path", "LIST")])
def igraph_all_simple_paths(ctx, v, to, cutoff=-1):
    adj = _host_adjacency(ctx, directed=True)
    limit = math.inf if cutoff is None or cutoff < 0 else int(cutoff)
    by_gid = {}

    def vertex(gid):
        if gid not in by_gid:
            by_gid[gid] = ctx.accessor.find_vertex(gid, ctx.view)
        return by_gid[gid]

    if v.gid == to.gid:
        yield {"path": [vertex(v.gid)]}
        return
    for nodes, _ in _simple_path_chains(adj, v.gid, to.gid, limit):
        yield {"path": [vertex(g) for g in nodes]}


@mgp.read_proc("igraphalg.mincut",
               args=[("source", "NODE"), ("target", "NODE")],
               opt_args=[("capacity", "STRING", None),
                         ("directed", "BOOLEAN", True)],
               results=[("node", "NODE"), ("partition_id", "INTEGER")])
def igraph_mincut(ctx, source, target, capacity=None, directed=True):
    """s-t mincut via max-flow: the source side is what stays reachable in
    the solver's final residual (null capacity follows igraph's
    unit-capacity convention)."""
    from .combinatorial_modules import (_capacity_network, max_flow_on,
                                        residual_reachable,
                                        undirect_capacities)
    if capacity is None:
        cap = collections.defaultdict(
            lambda: collections.defaultdict(float))
        for v in ctx.accessor.vertices(ctx.view):
            for e in v.out_edges(ctx.view):
                cap[v.gid][e.to_vertex().gid] += 1.0
    else:
        cap, _ = _capacity_network(ctx, capacity)
    if not directed:
        cap = undirect_capacities(cap)
    _, _, residual = max_flow_on(cap, source.gid, target.gid)
    reachable = residual_reachable(residual, source.gid)
    for v in ctx.accessor.vertices(ctx.view):
        yield {"node": v,
               "partition_id": 0 if v.gid in reachable else 1}


@mgp.read_proc("igraphalg.topological_sort",
               opt_args=[("mode", "STRING", "out")],
               results=[("nodes", "LIST")])
def igraph_topological_sort(ctx, mode="out"):
    if mode not in ("out", "in"):
        raise QueryException('Mode can only be either "out" or "in"')
    adj = _host_adjacency(ctx, directed=True)
    if mode == "in":
        rev = collections.defaultdict(list)
        for u, nbrs in adj.items():
            rev[u]
            for nb, w, e in nbrs:
                rev[nb].append((u, w, e))
        adj = rev
    indeg = {g: 0 for g in adj}
    for u, nbrs in adj.items():
        for nb, _, _ in nbrs:
            indeg[nb] = indeg.get(nb, 0) + 1
    queue = collections.deque(sorted(g for g, d in indeg.items() if d == 0))
    out = []
    while queue:
        u = queue.popleft()
        out.append(u)
        for nb, _, _ in adj.get(u, ()):
            indeg[nb] -= 1
            if indeg[nb] == 0:
                queue.append(nb)
    if len(out) != len(indeg):
        raise QueryException(
            "Topological sort can't be performed on graph that contains "
            "cycle!")
    yield {"nodes": [ctx.accessor.find_vertex(g, ctx.view) for g in out]}


@mgp.read_proc("igraphalg.spanning_tree",
               opt_args=[("weights", "STRING", None),
                         ("directed", "BOOLEAN", False)],
               results=[("tree", "LIST")])
def igraph_spanning_tree(ctx, weights=None, directed=False):
    """directed=True keeps each directed edge as-is (scipy, like igraph,
    still treats entries as undirected edges for the MST); directed=False
    first min-combines reciprocal weights."""
    from scipy.sparse.csgraph import minimum_spanning_tree
    mat, graph = _scipy_csr(ctx, weights, directed=directed)
    if graph.n_nodes == 0:
        yield {"tree": []}
        return
    mst = minimum_spanning_tree(mat).tocoo()
    tree = []
    for i, j in zip(mst.row, mst.col):
        a = ctx.vertex_by_index(graph, int(i))
        b = ctx.vertex_by_index(graph, int(j))
        if a is not None and b is not None:
            tree.append([a, b])
    yield {"tree": tree}


@mgp.read_proc("igraphalg.shortest_path_length",
               args=[("source", "NODE"), ("target", "NODE")],
               opt_args=[("weights", "STRING", None),
                         ("directed", "BOOLEAN", True)],
               results=[("length", "FLOAT")])
def igraph_shortest_path_length(ctx, source, target, weights=None,
                                directed=True):
    from ..ops.traversal import sssp
    graph = ctx.device_graph(weight_property=weights)
    src = _dense_index(ctx, graph, source)
    dst = _dense_index(ctx, graph, target)
    dist, _ = sssp(graph, src, weighted=weights is not None,
                   directed=directed)
    length = float(np.asarray(dist)[dst])
    yield {"length": length if math.isfinite(length) else math.inf}


@mgp.read_proc("igraphalg.all_shortest_path_lengths",
               opt_args=[("weights", "STRING", None),
                         ("directed", "BOOLEAN", False)],
               results=[("src_node", "NODE"), ("dest_node", "NODE"),
                        ("length", "FLOAT")])
def igraph_all_shortest_path_lengths(ctx, weights=None, directed=False):
    from scipy.sparse.csgraph import shortest_path
    mat, graph = _scipy_csr(ctx, weights, directed)
    if graph.n_nodes == 0:
        return
    unweighted = weights is None
    lengths = shortest_path(mat, directed=directed,
                            unweighted=unweighted)
    nodes = [ctx.vertex_by_index(graph, i) for i in range(graph.n_nodes)]
    for i in range(graph.n_nodes):
        for j in range(graph.n_nodes):
            if nodes[i] is not None and nodes[j] is not None:
                yield {"src_node": nodes[i], "dest_node": nodes[j],
                       "length": float(lengths[i][j])}


@mgp.read_proc("igraphalg.get_shortest_path",
               args=[("source", "NODE"), ("target", "NODE")],
               opt_args=[("weights", "STRING", None),
                         ("directed", "BOOLEAN", True)],
               results=[("path", "LIST")])
def igraph_get_shortest_path(ctx, source, target, weights=None,
                             directed=True):
    from scipy.sparse.csgraph import dijkstra
    mat, graph = _scipy_csr(ctx, weights, directed)
    src = _dense_index(ctx, graph, source)
    dst = _dense_index(ctx, graph, target)
    if weights is None:
        mat = mat.sign()  # hop counts
    _, predecessors = dijkstra(mat, directed=directed, indices=src,
                               return_predecessors=True)
    if predecessors[dst] < 0 and src != dst:
        yield {"path": []}
        return
    chain = [dst]
    while chain[-1] != src:
        chain.append(int(predecessors[chain[-1]]))
    chain.reverse()
    yield {"path": [ctx.vertex_by_index(graph, i) for i in chain]}


# --- algo (astar / all_simple_paths / cover) ---------------------------------


@mgp.read_proc("algo.astar",
               args=[("start", "NODE"), ("target", "NODE")],
               opt_args=[("config", "MAP", None)],
               results=[("path", "PATH"), ("weight", "FLOAT")])
def algo_astar(ctx, start, target, config=None):
    """A* over edge distances with a great-circle heuristic when nodes
    carry latitude/longitude (config: distance_prop, latitude_name,
    longitude_name, unweighted — reference algo_module astar)."""
    from ..query.values import Path
    config = config or {}
    distance_prop = config.get("distance_prop", "distance")
    lat_name = config.get("latitude_name", "lat")
    lon_name = config.get("longitude_name", "lon")
    unweighted = bool(config.get("unweighted", False))
    adj = _host_adjacency(
        ctx, directed=True,
        weight_property=None if unweighted else distance_prop)

    lat_pid = ctx.storage.property_mapper.maybe_name_to_id(lat_name)
    lon_pid = ctx.storage.property_mapper.maybe_name_to_id(lon_name)
    coord_cache = {}

    def coords(gid):
        if gid in coord_cache:
            return coord_cache[gid]
        out = None
        if lat_pid is not None and lon_pid is not None:
            v = ctx.accessor.find_vertex(gid, ctx.view)
            if v is not None:
                lat = v.get_property(lat_pid, ctx.view)
                lon = v.get_property(lon_pid, ctx.view)
                if lat is not None and lon is not None:
                    out = (float(lat), float(lon))
        coord_cache[gid] = out
        return out

    t_coords = coords(target.gid)
    h_cache = {}

    def heuristic(gid):
        if unweighted or t_coords is None:
            return 0.0
        h = h_cache.get(gid)
        if h is None:
            c = coords(gid)
            h = 0.0 if c is None else _haversine(c, t_coords)
            h_cache[gid] = h
        return h

    dist = {start.gid: 0.0}
    parent = {}
    heap = [(heuristic(start.gid), start.gid)]
    seen = set()
    while heap:
        _, u = heapq.heappop(heap)
        if u in seen:
            continue
        if u == target.gid:
            break
        seen.add(u)
        for nb, w, e in adj.get(u, ()):
            nd = dist[u] + w
            if nd < dist.get(nb, math.inf):
                dist[nb] = nd
                parent[nb] = (u, e)
                heapq.heappush(heap, (nd + heuristic(nb), nb))
    if target.gid not in dist:
        return
    items = [ctx.accessor.find_vertex(target.gid, ctx.view)]
    cur = target.gid
    while cur != start.gid:
        prev, edge = parent[cur]
        items = [ctx.accessor.find_vertex(prev, ctx.view), edge] + items
        cur = prev
    yield {"path": Path(items), "weight": float(dist[target.gid])}


@mgp.read_proc("algo.all_simple_paths",
               args=[("start_node", "NODE"), ("end_node", "NODE"),
                     ("relationship_types", "LIST"),
                     ("max_length", "INTEGER")],
               results=[("path", "PATH")])
def algo_all_simple_paths(ctx, start_node, end_node, relationship_types,
                          max_length):
    from ..query.values import Path
    adj = _host_adjacency(ctx, directed=True,
                          edge_types=relationship_types or None)
    if max_length is None or max_length < 0:
        raise QueryException("max_length must be a non-negative integer")
    for nodes, edges in _simple_path_chains(adj, start_node.gid,
                                            end_node.gid, max_length):
        items = [ctx.accessor.find_vertex(nodes[0], ctx.view)]
        for k, e in enumerate(edges):
            items.extend(
                [e, ctx.accessor.find_vertex(nodes[k + 1], ctx.view)])
        yield {"path": Path(items)}


@mgp.read_proc("algo.cover",
               args=[("nodes", "LIST")],
               results=[("rel", "RELATIONSHIP")])
def algo_cover(ctx, nodes):
    """All relationships whose both endpoints are in the given node set
    (reference algo_module cover)."""
    wanted = {v.gid for v in nodes}
    for v in nodes:
        for e in v.out_edges(ctx.view):
            if e.to_vertex().gid in wanted:
                yield {"rel": e}
