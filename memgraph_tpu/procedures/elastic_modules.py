"""elastic_search.* — serialize graph objects into Elasticsearch.

Counterpart of /root/reference/mage/python/elastic_search_serialization.py:
connect/create_index/index_db/scroll against a live cluster (gated on
the `elasticsearch` client), plus the document serialization itself
exposed as `elastic_search.serialize_db` — usable (and tested) without
any cluster, and the piece the synchronization triggers compose with.
"""

from __future__ import annotations

from ..exceptions import QueryException
from . import mgp

_CLIENTS: dict = {}


def _serialize_vertex(ctx, va):
    storage = ctx.storage
    return {
        "_id": str(va.gid),
        "labels": [storage.label_mapper.id_to_name(l)
                   for l in va.labels(ctx.view)],
        "properties": {storage.property_mapper.id_to_name(k): v
                       for k, v in va.properties(ctx.view).items()},
    }


def _serialize_edge(ctx, ea):
    storage = ctx.storage
    return {
        "_id": str(ea.gid),
        "edge_type": storage.edge_type_mapper.id_to_name(ea.edge_type),
        "source": str(ea.from_vertex().gid),
        "target": str(ea.to_vertex().gid),
        "properties": {storage.property_mapper.id_to_name(k): v
                       for k, v in ea.properties(ctx.view).items()},
    }


@mgp.read_proc("elastic_search.serialize_db",
               opt_args=[("edges", "BOOLEAN", False)],
               results=[("id", "STRING"), ("document", "MAP")])
def serialize_db(ctx, edges=False):
    """Every vertex (or edge) as the ES document the reference's bulk
    indexers ship — no cluster required."""
    if edges:
        for ea in ctx.accessor.edges(ctx.view):
            doc = _serialize_edge(ctx, ea)
            yield {"id": doc["_id"], "document": doc}
    else:
        for va in ctx.accessor.vertices(ctx.view):
            doc = _serialize_vertex(ctx, va)
            yield {"id": doc["_id"], "document": doc}


def _client():
    es = _CLIENTS.get("default")
    if es is None:
        raise QueryException(
            "elastic_search: call elastic_search.connect(...) first")
    return es


@mgp.read_proc("elastic_search.connect",
               args=[("elastic_url", "STRING")],
               opt_args=[("ca_certs", "STRING", None),
                         ("elastic_user", "STRING", None),
                         ("elastic_password", "STRING", None)],
               results=[("connection_status", "STRING")])
def connect(ctx, elastic_url, ca_certs=None, elastic_user=None,
            elastic_password=None):
    try:
        from elasticsearch import Elasticsearch
    except ImportError as e:
        raise QueryException(
            "the 'elasticsearch' client library is not installed in "
            "this environment") from e
    kwargs = {}
    if ca_certs:
        kwargs["ca_certs"] = ca_certs
    if elastic_user:
        kwargs["basic_auth"] = (elastic_user, elastic_password or "")
    es = Elasticsearch(elastic_url, **kwargs)
    _CLIENTS["default"] = es
    yield {"connection_status": str(es.info())}


@mgp.read_proc("elastic_search.create_index",
               args=[("index_name", "STRING"), ("schema", "MAP")],
               results=[("message", "STRING")])
def create_index(ctx, index_name, schema):
    es = _client()
    es.indices.create(index=index_name, body=dict(schema or {}))
    yield {"message": f"created index {index_name}"}


@mgp.read_proc("elastic_search.index_db",
               args=[("node_index", "STRING"), ("edge_index", "STRING")],
               opt_args=[("thread_count", "INTEGER", 1)],
               results=[("number_of_nodes", "INTEGER"),
                        ("number_of_edges", "INTEGER")])
def index_db(ctx, node_index, edge_index, thread_count=1):
    """Bulk-index the whole graph (reference: streaming_bulk /
    parallel_bulk paths, selected by thread_count)."""
    from elasticsearch.helpers import parallel_bulk, streaming_bulk
    es = _client()

    def bulk(docs):
        if int(thread_count) > 1:
            return parallel_bulk(es, docs,
                                 thread_count=int(thread_count))
        return streaming_bulk(es, docs)

    n_nodes = n_edges = 0
    node_docs = ({"_index": node_index, "_id": d["id"],
                  "_source": d["document"]}
                 for d in serialize_db(ctx))
    for ok, _ in bulk(node_docs):
        n_nodes += bool(ok)
    edge_docs = ({"_index": edge_index, "_id": d["id"],
                  "_source": d["document"]}
                 for d in serialize_db(ctx, edges=True))
    for ok, _ in bulk(edge_docs):
        n_edges += bool(ok)
    yield {"number_of_nodes": n_nodes, "number_of_edges": n_edges}


@mgp.read_proc("elastic_search.scroll",
               args=[("index_name", "STRING"), ("query", "MAP")],
               results=[("document", "MAP")])
def scroll(ctx, index_name, query):
    es = _client()
    resp = es.search(index=index_name, body=dict(query or {}),
                     scroll="1m")
    while resp["hits"]["hits"]:
        for hit in resp["hits"]["hits"]:
            yield {"document": hit["_source"]}
        resp = es.scroll(scroll_id=resp["_scroll_id"], scroll="1m")
