"""Builtin query modules (the MAGE-equivalent algorithm surface).

Counterparts of /root/reference/query_modules/ and /root/reference/mage/:
the same `CALL module.proc() YIELD ...` API, with the compute running as
TPU kernels over CSR device snapshots instead of C++ loops over adjacency
lists. Reference-named modules (pagerank, katz_centrality,
community_detection, ...) plus explicitly-TPU variants (pagerank_tpu, ...)
that expose device knobs.
"""

_LOADED = False


def load_builtin_modules() -> None:
    global _LOADED
    if _LOADED:
        return
    _LOADED = True
    from . import graph_algorithms  # noqa: F401 — registers on import
    from . import vector_search     # noqa: F401
    from . import node2vec_module   # noqa: F401
    from . import utility_modules   # noqa: F401
    from . import text_search_module  # noqa: F401
    from . import structure_modules   # noqa: F401
    from . import data_modules        # noqa: F401
    from . import graphrag            # noqa: F401
    from . import export_import       # noqa: F401
    from . import combinatorial_modules  # noqa: F401
    from . import igraph_module           # noqa: F401
    from . import apoc_modules            # noqa: F401
    from . import ml_modules              # noqa: F401
    from . import compat_modules          # noqa: F401
    from . import migrate_modules         # noqa: F401
    from . import elastic_modules         # noqa: F401
    from . import tgn_module              # noqa: F401
    from . import llm_util_module         # noqa: F401
    from . import embeddings_module       # noqa: F401
    from . import cross_database          # noqa: F401
