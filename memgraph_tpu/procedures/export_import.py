"""Export / import modules: JSON, cypherl.

Counterparts of /root/reference/mage/python/export_util.py and
import_util.py: whole-graph export to JSON/cypherl files and JSON import.
"""

from __future__ import annotations

import json
import os

from . import mgp
from ..exceptions import ProcedureException


def _value_to_json(v, storage, view):
    from ..storage.storage import EdgeAccessor, VertexAccessor
    if isinstance(v, (VertexAccessor, EdgeAccessor)):
        raise ProcedureException("nested graph values are not exportable")
    if isinstance(v, (list, tuple)):
        return [_value_to_json(x, storage, view) for x in v]
    if isinstance(v, dict):
        return {k: _value_to_json(x, storage, view) for k, x in v.items()}
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)  # temporal/point → ISO-ish strings


@mgp.read_proc("export_util.json", args=[("path", "STRING")],
               results=[("path", "STRING"), ("nodes", "INTEGER"),
                        ("relationships", "INTEGER")])
def export_json(ctx, path):
    storage = ctx.storage
    lm, pm, tm = (storage.label_mapper, storage.property_mapper,
                  storage.edge_type_mapper)
    out = []
    n_nodes = n_rels = 0
    for va in ctx.accessor.vertices(ctx.view):
        out.append({
            "type": "node", "id": va.gid,
            "labels": [lm.id_to_name(l) for l in va.labels(ctx.view)],
            "properties": {pm.id_to_name(k):
                           _value_to_json(v, storage, ctx.view)
                           for k, v in va.properties(ctx.view).items()}})
        n_nodes += 1
    for ea in ctx.accessor.edges(ctx.view):
        out.append({
            "type": "relationship", "id": ea.gid,
            "label": tm.id_to_name(ea.edge_type),
            "start": ea.from_vertex().gid, "end": ea.to_vertex().gid,
            "properties": {pm.id_to_name(k):
                           _value_to_json(v, storage, ctx.view)
                           for k, v in ea.properties(ctx.view).items()}})
        n_rels += 1
    os.makedirs(os.path.dirname(os.path.abspath(str(path))), exist_ok=True)
    with open(str(path), "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
    yield {"path": str(path), "nodes": n_nodes, "relationships": n_rels}


@mgp.read_proc("export_util.cypherl", args=[("path", "STRING")],
               results=[("path", "STRING"), ("statements", "INTEGER")])
def export_cypherl(ctx, path):
    from ..query.dump import dump_database
    count = 0
    os.makedirs(os.path.dirname(os.path.abspath(str(path))), exist_ok=True)
    with open(str(path), "w", encoding="utf-8") as f:
        for line in dump_database(ctx.accessor):
            f.write(line + "\n")
            count += 1
    yield {"path": str(path), "statements": count}


@mgp.write_proc("import_util.json", args=[("path", "STRING")],
                results=[("nodes", "INTEGER"), ("relationships", "INTEGER")])
def import_json(ctx, path):
    storage = ctx.storage
    try:
        with open(str(path), encoding="utf-8") as f:
            records = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ProcedureException(f"cannot read {path}: {e}") from e
    id_map: dict[int, object] = {}
    n_nodes = n_rels = 0
    for rec in records:
        if rec.get("type") == "node":
            va = ctx.accessor.create_vertex()
            for label in rec.get("labels", []):
                va.add_label(storage.label_mapper.name_to_id(label))
            for key, value in rec.get("properties", {}).items():
                va.set_property(storage.property_mapper.name_to_id(key),
                                value)
            id_map[rec["id"]] = va
            n_nodes += 1
    for rec in records:
        if rec.get("type") == "relationship":
            src = id_map.get(rec.get("start"))
            dst = id_map.get(rec.get("end"))
            if src is None or dst is None:
                raise ProcedureException(
                    f"relationship {rec.get('id')} references an unknown "
                    f"node id")
            tid = storage.edge_type_mapper.name_to_id(rec["label"])
            ea = ctx.accessor.create_edge(src, dst, tid)
            for key, value in rec.get("properties", {}).items():
                ea.set_property(storage.property_mapper.name_to_id(key),
                                value)
            n_rels += 1
    yield {"nodes": n_nodes, "relationships": n_rels}


@mgp.read_proc("export_util.graphml",
               opt_args=[("path", "STRING", ""), ("config", "MAP", None)],
               results=[("status", "STRING")])
def export_graphml(ctx, path="", config=None):
    """Whole-database GraphML export (reference export_util.py graphml):
    nodes carry a 'labels' data key (:A:B form) plus properties; edges a
    'label' key. config.leaveOutLabels / leaveOutProperties are BOOLEANS
    (omit all labels / all properties, as in the reference's
    set_default_config); config.stream returns the XML in `status`
    instead of writing a file. Property keys get sequential GraphML ids
    (d0, d1, ...) so user properties can't collide with the reserved
    labels/label keys."""
    from xml.sax.saxutils import escape, quoteattr
    config = config or {}
    if not isinstance(config.get("leaveOutLabels", False), bool) or \
            not isinstance(config.get("leaveOutProperties", False), bool):
        raise ProcedureException(
            "leaveOutLabels / leaveOutProperties must be booleans")
    drop_labels = bool(config.get("leaveOutLabels", False))
    drop_props = bool(config.get("leaveOutProperties", False))
    stream = bool(config.get("stream", False))
    if not path and not stream:
        raise ProcedureException(
            "export_util.graphml requires a path or {stream: true}")
    storage = ctx.storage
    lm, pm, tm = (storage.label_mapper, storage.property_mapper,
                  storage.edge_type_mapper)
    key_ids: dict = {}

    def key_id(name):
        if name not in key_ids:
            key_ids[name] = f"d{len(key_ids)}"
        return key_ids[name]

    nodes, edges = [], []
    for va in ctx.accessor.vertices(ctx.view):
        labels = [] if drop_labels else \
            [lm.id_to_name(l) for l in va.labels(ctx.view)]
        props = {} if drop_props else \
            {pm.id_to_name(k): _value_to_json(v, storage, ctx.view)
             for k, v in va.properties(ctx.view).items()}
        for name in props:
            key_id(name)
        nodes.append((va.gid, labels, props))
    for ea in ctx.accessor.edges(ctx.view):
        props = {} if drop_props else \
            {pm.id_to_name(k): _value_to_json(v, storage, ctx.view)
             for k, v in ea.properties(ctx.view).items()}
        for name in props:
            key_id(name)
        edges.append((ea.gid, ea.from_vertex().gid, ea.to_vertex().gid,
                      tm.id_to_name(ea.edge_type), props))

    def data_value(v):
        return escape(json.dumps(v) if isinstance(v, (list, dict))
                      else str(v))

    parts = []
    parts.append('<?xml version="1.0" encoding="UTF-8"?>\n')
    parts.append('<graphml xmlns='
                 '"http://graphml.graphdrawing.org/xmlns">\n')
    parts.append('<key id="labels" for="node" attr.name="labels" '
                 'attr.type="string"/>\n')
    parts.append('<key id="label" for="edge" attr.name="label" '
                 'attr.type="string"/>\n')
    for name, kid in sorted(key_ids.items(), key=lambda kv: kv[1]):
        parts.append(f'<key id="{kid}" for="all" '
                     f'attr.name={quoteattr(str(name))}/>\n')
    parts.append('<graph id="G" edgedefault="directed">\n')
    for gid, labels, props in nodes:
        parts.append(f'<node id="n{gid}">')
        if labels:
            parts.append('<data key="labels">'
                         + escape(":" + ":".join(labels)) + "</data>")
        for k, v in sorted(props.items()):
            parts.append(f'<data key="{key_ids[k]}">'
                         + data_value(v) + "</data>")
        parts.append("</node>\n")
    for gid, src, dst, type_name, props in edges:
        parts.append(f'<edge id="e{gid}" source="n{src}" '
                     f'target="n{dst}">')
        parts.append('<data key="label">' + escape(type_name) + "</data>")
        for k, v in sorted(props.items()):
            parts.append(f'<data key="{key_ids[k]}">'
                         + data_value(v) + "</data>")
        parts.append("</edge>\n")
    parts.append("</graph>\n</graphml>\n")
    document = "".join(parts)
    if path:
        os.makedirs(os.path.dirname(os.path.abspath(str(path))),
                    exist_ok=True)
        with open(str(path), "w", encoding="utf-8") as f:
            f.write(document)
        yield {"status": f"Exported {len(nodes)} nodes and {len(edges)} "
                         f"relationships to {path}."}
    else:
        yield {"status": document}


@mgp.read_proc("export_util.csv_query",
               args=[("query", "STRING")],
               opt_args=[("file_path", "STRING", ""),
                         ("stream", "BOOLEAN", False)],
               results=[("file_path", "STRING"), ("data", "STRING")])
def export_csv_query(ctx, query, file_path="", stream=False):
    """Run a query and emit its results as CSV to a file, a returned
    stream, or both (reference export_util.py csv_query)."""
    import csv
    import io
    if not file_path and not stream:
        raise ProcedureException(
            "provide a file_path or set stream to true")
    from .apoc_modules import _sub_interpreter
    interp = _sub_interpreter(ctx)
    columns, rows, _ = interp.execute(query)
    from ..storage.storage import EdgeAccessor, VertexAccessor

    def cell(v):
        if v is None:
            return ""
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return v
        if isinstance(v, (VertexAccessor, EdgeAccessor, list, dict)):
            # structured values serialize as JSON, not object reprs
            from ..query.functions import _jsonable
            from ..query.eval import Evaluator, EvalContext
            ev = Evaluator(EvalContext(ctx.accessor, view=ctx.view))
            return json.dumps(_jsonable(ev, v), separators=(",", ":"))
        return str(v)

    buf = io.StringIO()
    writer = csv.writer(buf, quoting=csv.QUOTE_NONNUMERIC)
    writer.writerow(columns)
    for row in rows:
        writer.writerow([cell(v) for v in row])
    data = buf.getvalue()
    if file_path:
        os.makedirs(os.path.dirname(os.path.abspath(str(file_path))),
                    exist_ok=True)
        with open(str(file_path), "w", encoding="utf-8") as f:
            f.write(data)
    yield {"file_path": str(file_path),
           "data": data if stream else ""}


@mgp.read_proc("csv_utils.create_csv_file",
               args=[("filepath", "STRING"), ("content", "STRING")],
               opt_args=[("is_append", "BOOLEAN", False)],
               results=[("filepath", "STRING")])
def csv_utils_create(ctx, filepath, content, is_append=False):
    """Create or append to a CSV file (reference mage/cpp/csv_utils)."""
    os.makedirs(os.path.dirname(os.path.abspath(str(filepath))),
                exist_ok=True)
    with open(str(filepath), "a" if is_append else "w",
              encoding="utf-8") as f:
        f.write(str(content))
    yield {"filepath": str(filepath)}


@mgp.read_proc("csv_utils.delete_csv_file",
               args=[("filepath", "STRING")],
               results=[("filepath", "STRING")])
def csv_utils_delete(ctx, filepath):
    try:
        os.remove(str(filepath))
    except FileNotFoundError:
        raise ProcedureException(f"file {filepath!r} does not exist")
    yield {"filepath": str(filepath)}
