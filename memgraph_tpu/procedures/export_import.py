"""Export / import modules: JSON, cypherl.

Counterparts of /root/reference/mage/python/export_util.py and
import_util.py: whole-graph export to JSON/cypherl files and JSON import.
"""

from __future__ import annotations

import json
import os

from . import mgp
from ..exceptions import ProcedureException


def _value_to_json(v, storage, view):
    from ..storage.storage import EdgeAccessor, VertexAccessor
    if isinstance(v, (VertexAccessor, EdgeAccessor)):
        raise ProcedureException("nested graph values are not exportable")
    if isinstance(v, (list, tuple)):
        return [_value_to_json(x, storage, view) for x in v]
    if isinstance(v, dict):
        return {k: _value_to_json(x, storage, view) for k, x in v.items()}
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)  # temporal/point → ISO-ish strings


@mgp.read_proc("export_util.json", args=[("path", "STRING")],
               results=[("path", "STRING"), ("nodes", "INTEGER"),
                        ("relationships", "INTEGER")])
def export_json(ctx, path):
    storage = ctx.storage
    lm, pm, tm = (storage.label_mapper, storage.property_mapper,
                  storage.edge_type_mapper)
    out = []
    n_nodes = n_rels = 0
    for va in ctx.accessor.vertices(ctx.view):
        out.append({
            "type": "node", "id": va.gid,
            "labels": [lm.id_to_name(l) for l in va.labels(ctx.view)],
            "properties": {pm.id_to_name(k):
                           _value_to_json(v, storage, ctx.view)
                           for k, v in va.properties(ctx.view).items()}})
        n_nodes += 1
    for ea in ctx.accessor.edges(ctx.view):
        out.append({
            "type": "relationship", "id": ea.gid,
            "label": tm.id_to_name(ea.edge_type),
            "start": ea.from_vertex().gid, "end": ea.to_vertex().gid,
            "properties": {pm.id_to_name(k):
                           _value_to_json(v, storage, ctx.view)
                           for k, v in ea.properties(ctx.view).items()}})
        n_rels += 1
    os.makedirs(os.path.dirname(os.path.abspath(str(path))), exist_ok=True)
    with open(str(path), "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2)
    yield {"path": str(path), "nodes": n_nodes, "relationships": n_rels}


@mgp.read_proc("export_util.cypherl", args=[("path", "STRING")],
               results=[("path", "STRING"), ("statements", "INTEGER")])
def export_cypherl(ctx, path):
    from ..query.dump import dump_database
    count = 0
    os.makedirs(os.path.dirname(os.path.abspath(str(path))), exist_ok=True)
    with open(str(path), "w", encoding="utf-8") as f:
        for line in dump_database(ctx.accessor):
            f.write(line + "\n")
            count += 1
    yield {"path": str(path), "statements": count}


@mgp.write_proc("import_util.json", args=[("path", "STRING")],
                results=[("nodes", "INTEGER"), ("relationships", "INTEGER")])
def import_json(ctx, path):
    storage = ctx.storage
    try:
        with open(str(path), encoding="utf-8") as f:
            records = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ProcedureException(f"cannot read {path}: {e}") from e
    id_map: dict[int, object] = {}
    n_nodes = n_rels = 0
    for rec in records:
        if rec.get("type") == "node":
            va = ctx.accessor.create_vertex()
            for label in rec.get("labels", []):
                va.add_label(storage.label_mapper.name_to_id(label))
            for key, value in rec.get("properties", {}).items():
                va.set_property(storage.property_mapper.name_to_id(key),
                                value)
            id_map[rec["id"]] = va
            n_nodes += 1
    for rec in records:
        if rec.get("type") == "relationship":
            src = id_map.get(rec.get("start"))
            dst = id_map.get(rec.get("end"))
            if src is None or dst is None:
                raise ProcedureException(
                    f"relationship {rec.get('id')} references an unknown "
                    f"node id")
            tid = storage.edge_type_mapper.name_to_id(rec["label"])
            ea = ctx.accessor.create_edge(src, dst, tid)
            for key, value in rec.get("properties", {}).items():
                ea.set_property(storage.property_mapper.name_to_id(key),
                                value)
            n_rels += 1
    yield {"nodes": n_nodes, "relationships": n_rels}
