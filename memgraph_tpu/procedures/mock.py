"""Mock procedure context for module unit tests.

Counterpart of the reference's module-author mocking surface
(/root/reference/include/mgp_mock.py + tests/e2e/mock_api): build a tiny
graph from edge lists, get a real ProcedureContext over a real (throwaway)
storage, and call procedures directly — no server needed.

    from memgraph_tpu.procedures.mock import mock_context

    ctx, nodes = mock_context(
        nodes=[{"labels": ["User"], "name": "ana"}, {...}],
        edges=[(0, 1, "KNOWS", {"w": 1.0})])
    rows = list(my_proc(ctx, some_arg))
"""

from __future__ import annotations

from ..query.plan.operators import ExecutionContext
from ..query.procedures.registry import ProcedureContext
from ..storage import InMemoryStorage


def mock_context(nodes=None, edges=None, storage=None):
    """Build (ProcedureContext, [VertexAccessor]) over a fresh storage.

    nodes: list of dicts; the "labels" key (list of label names) is special,
           every other key becomes a property.
    edges: (from_index, to_index, type_name, properties?) tuples.
    """
    storage = storage or InMemoryStorage()
    acc = storage.access()
    vas = []
    for spec in nodes or []:
        va = acc.create_vertex()
        for label in spec.get("labels", []):
            va.add_label(storage.label_mapper.name_to_id(label))
        for key, value in spec.items():
            if key == "labels":
                continue
            va.set_property(storage.property_mapper.name_to_id(key), value)
        vas.append(va)
    for edge in edges or []:
        src, dst, type_name = edge[0], edge[1], edge[2]
        props = edge[3] if len(edge) > 3 else {}
        ea = acc.create_edge(vas[src], vas[dst],
                             storage.edge_type_mapper.name_to_id(type_name))
        for key, value in (props or {}).items():
            ea.set_property(storage.property_mapper.name_to_id(key), value)
    acc.commit()

    read_acc = storage.access()
    exec_ctx = ExecutionContext(read_acc)
    pctx = ProcedureContext(exec_ctx)
    fresh = [read_acc.find_vertex(va.gid) for va in vas]
    return pctx, fresh


def call_procedure(name: str, *args, nodes=None, edges=None):
    """Convenience: build a mock graph and call a REGISTERED procedure by
    its dotted name; returns the list of result records."""
    from ..query.procedures.registry import global_registry
    proc = global_registry.find(name)
    if proc is None:
        raise KeyError(f"procedure {name!r} is not registered")
    pctx, _ = mock_context(nodes=nodes, edges=edges)
    return list(proc.func(pctx, *args))
