"""text_search module: full-text index management + BM25 search.

Counterpart of /root/reference/query_modules/text_search_module.cpp
(which fronts the tantivy index): create/drop/list indexes, ranked search.
"""

from __future__ import annotations

from . import mgp


@mgp.write_proc("text_search.create_index",
                args=[("index_name", "STRING"), ("label", "STRING")],
                results=[("status", "STRING")])
def create_index(ctx, index_name, label):
    from ..storage.text_index import text_indices
    text_indices(ctx.storage).create(str(index_name), str(label))
    yield {"status": f"text index {index_name} created"}


@mgp.write_proc("text_search.drop_index",
                args=[("index_name", "STRING")],
                results=[("status", "STRING")])
def drop_index(ctx, index_name):
    from ..storage.text_index import text_indices
    dropped = text_indices(ctx.storage).drop(str(index_name))
    yield {"status": ("dropped" if dropped else "no such index")}


@mgp.read_proc("text_search.search",
               args=[("index_name", "STRING"), ("search_query", "STRING")],
               opt_args=[("limit", "INTEGER", 10)],
               results=[("node", "NODE"), ("score", "FLOAT")])
def search(ctx, index_name, search_query, limit=10):
    from ..storage.text_index import text_indices
    index = text_indices(ctx.storage).get(str(index_name))
    if index is None:
        from ..exceptions import ProcedureException
        raise ProcedureException(f"text index {index_name!r} does not exist")
    for gid, score in index.search(str(search_query), int(limit)):
        node = ctx.accessor.find_vertex(gid, ctx.view)
        if node is not None:
            yield {"node": node, "score": float(score)}


@mgp.read_proc("text_search.show_index_info",
               results=[("index_name", "STRING"), ("documents", "INTEGER"),
                        ("terms", "INTEGER")])
def show_index_info(ctx):
    from ..storage.text_index import text_indices
    for index in text_indices(ctx.storage).all():
        info = index.info()
        yield {"index_name": info["name"], "documents": info["documents"],
               "terms": info["terms"]}
