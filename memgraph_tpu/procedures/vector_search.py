"""Vector search module: brute-force/IVF kNN over node embedding properties.

Counterpart of /root/reference/query_modules/vector_search_module.cpp (which
fronts the usearch HNSW index): here search IS the index — batched MXU
matmul + top_k over a device-resident embedding matrix. The matrix is
maintained INCREMENTALLY: a storage commit hook records which vertices
changed, and only their rows are re-extracted on the next search (full
device re-upload only when rows actually changed) — the delta-maintenance
analog of usearch's in-place index updates.
"""

from __future__ import annotations

import threading
import weakref

import numpy as np

from . import mgp

_CACHE_LOCK = threading.Lock()
# storage (weak) -> {property_name: _MatrixState}
_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


class _MatrixState:
    __slots__ = ("matrix", "gids", "gid_rows", "dirty", "hooked")

    def __init__(self):
        self.matrix = None          # jnp (n, d) or None
        self.gids: list[int] = []
        self.gid_rows: dict[int, int] = {}
        self.dirty: set[int] = set()   # gids touched since last refresh
        self.hooked = False


def _get_states(storage) -> dict:
    with _CACHE_LOCK:
        states = _CACHE.get(storage)
        if states is None:
            states = {}
            _CACHE[storage] = states

            def on_commit(txn, commit_ts, _states=states):
                touched = set(txn.touched_vertices.keys())
                with _CACHE_LOCK:
                    for st in _states.values():
                        st.dirty |= touched

            storage.on_commit_hooks.append(on_commit)
        return states


def _embedding_matrix(ctx, property_name: str):
    """(matrix (n, d) jnp array, gids list) for nodes carrying the property.

    Incremental: only vertices dirtied by commits since the last call are
    re-read; unchanged states return the cached device matrix untouched.
    """
    import jax.numpy as jnp
    storage = ctx.storage
    states = _get_states(storage)
    with _CACHE_LOCK:
        state = states.get(property_name)
        if state is None:
            state = _MatrixState()
            state.dirty = None  # sentinel: full build needed
            states[property_name] = state
        dirty = state.dirty
        state.dirty = set()
    pid = storage.property_mapper.maybe_name_to_id(property_name)
    if pid is None:
        return None, []

    def read_vec(va):
        vec = va.get_property(pid, ctx.view)
        if isinstance(vec, (list, tuple)) and vec and \
                all(isinstance(x, (int, float)) and not isinstance(x, bool)
                    for x in vec):
            return [float(x) for x in vec]
        return None

    if dirty is None:
        # full build
        vectors, gids = [], []
        for va in ctx.accessor.vertices(ctx.view):
            vec = read_vec(va)
            if vec is not None:
                vectors.append(vec)
                gids.append(va.gid)
        state.gids = gids
        state.gid_rows = {g: i for i, g in enumerate(gids)}
        state.matrix = (jnp.asarray(np.asarray(vectors, dtype=np.float32))
                        if vectors else None)
        return state.matrix, state.gids

    if dirty:
        host = (np.asarray(state.matrix)
                if state.matrix is not None else np.zeros((0, 0), np.float32))
        rows = {g: host[i] for g, i in state.gid_rows.items()
                if g not in dirty}
        for gid in dirty:
            va = ctx.accessor.find_vertex(gid, ctx.view)
            if va is None:
                continue
            vec = read_vec(va)
            if vec is not None:
                rows[gid] = np.asarray(vec, dtype=np.float32)
        if rows:
            # drop rows with a deviating dimension (property was rewritten
            # with a different-length vector) — keep the dominant dim
            from collections import Counter
            dims = Counter(len(v) for v in rows.values())
            dim = dims.most_common(1)[0][0]
            rows = {g: v for g, v in rows.items() if len(v) == dim}
        gids = sorted(rows)
        state.gids = gids
        state.gid_rows = {g: i for i, g in enumerate(gids)}
        state.matrix = (jnp.asarray(np.stack([rows[g] for g in gids]))
                        if gids else None)
    return state.matrix, state.gids


@mgp.read_proc("vector_search.search",
               args=[("property", "STRING"), ("query", "LIST"),
                     ("limit", "INTEGER")],
               opt_args=[("metric", "STRING", "cosine")],
               results=[("node", "NODE"), ("similarity", "FLOAT")])
def search(ctx, property, query, limit, metric="cosine"):
    from ..ops.knn import knn
    import jax.numpy as jnp
    matrix, gids = _embedding_matrix(ctx, property)
    if matrix is None:
        return
    q = jnp.asarray(np.asarray([query], dtype=np.float32))
    k = min(int(limit), len(gids))
    scores, idx = knn(matrix, q, k=k, metric=str(metric))
    scores = np.asarray(scores[0])
    idx = np.asarray(idx[0])
    for score, i in zip(scores, idx):
        node = ctx.accessor.find_vertex(gids[int(i)], ctx.view)
        if node is not None:
            yield {"node": node, "similarity": float(score)}


@mgp.read_proc("vector_search.show_index_info",
               results=[("index_name", "STRING"), ("label", "STRING"),
                        ("property", "STRING"), ("dimension", "INTEGER"),
                        ("size", "INTEGER")])
def show_index_info(ctx):
    with _CACHE_LOCK:
        states = dict(_CACHE.get(ctx.storage) or {})
    for prop, state in sorted(states.items()):
        yield {"index_name": f"vector::{prop}", "label": "*",
               "property": prop,
               "dimension": (int(state.matrix.shape[1])
                             if state.matrix is not None else 0),
               "size": len(state.gids)}


@mgp.read_proc("knn.get",
               args=[("node", "NODE"), ("property", "STRING"),
                     ("k", "INTEGER")],
               opt_args=[("metric", "STRING", "cosine")],
               results=[("neighbor", "NODE"), ("similarity", "FLOAT")])
def knn_get(ctx, node, property, k, metric="cosine"):
    """k nearest neighbors of an existing node by embedding similarity
    (counterpart of mage/cpp/knn_module)."""
    from ..ops.knn import knn
    import jax.numpy as jnp
    matrix, gids = _embedding_matrix(ctx, property)
    if matrix is None or node is None:
        return
    try:
        row = gids.index(node.gid)
    except ValueError:
        return
    q = matrix[row:row + 1]
    kk = min(int(k) + 1, len(gids))
    scores, idx = knn(matrix, q, k=kk, metric=str(metric))
    scores = np.asarray(scores[0])
    idx = np.asarray(idx[0])
    emitted = 0
    for score, i in zip(scores, idx):
        if int(i) == row:
            continue
        if emitted >= int(k):
            break
        nb = ctx.accessor.find_vertex(gids[int(i)], ctx.view)
        if nb is not None:
            emitted += 1
            yield {"neighbor": nb, "similarity": float(score)}
