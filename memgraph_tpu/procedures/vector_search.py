"""Vector search module: brute-force/IVF kNN over node embedding properties,
with O(delta) incremental maintenance.

Counterpart of /root/reference/query_modules/vector_search_module.cpp (which
fronts the usearch HNSW index, src/storage/v2/indices/vector_index.cpp:22-73
for the update path): here search IS the index — batched MXU matmul + top_k
over a device-resident embedding matrix.

Incremental maintenance design (solves the four NOTES_ROUND2 holes):
  1. replica WAL apply bypasses commit hooks → there are NO hooks: the
     storage records changed-gid sets at every topology bump (including
     WAL apply and recovery), and the index PULLS the delta via
     storage.changes_between(entry.version, reader.version).
  2. snapshot-isolation readers could bake pre-commit values → entries
     are keyed by the READER's topology snapshot (Accessor.topology
     _snapshot), and a bounded per-property version map serves concurrent
     readers at different snapshots; all reads go through the reader's
     own MVCC accessor.
  3. rebuild errors could lose invalidations → pull-based: a failed
     build leaves no entry; the next call simply retries.
  4. dominant-dimension filtering could drop clean rows → per-dimension
     candidate counts are maintained through deltas; if the dominant
     dimension changes, the index falls back to a full rebuild.

Rows live in a capacity-padded device matrix with a validity mask; delta
refresh is one batched .at[rows].set scatter (device) + O(delta) MVCC
reads (host) instead of an O(n) full scan.
"""

from __future__ import annotations

import threading
import weakref
from collections import Counter
from dataclasses import dataclass, field

import numpy as np

from . import mgp

_CACHE_LOCK = threading.Lock()
# storage (weak) -> {property_name: {version: _IndexEntry}}
_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_KEEP_VERSIONS = 4          # concurrent readers at older snapshots
_DELTA_MAX_FRACTION = 0.5   # larger deltas rebuild outright

# observability (tests + SHOW METRICS INFO assert on these)
STATS = {"full_builds": 0, "delta_refreshes": 0}


@dataclass
class _IndexEntry:
    version: int
    pid: int | None
    dim: int | None                      # dominant dimension (rows kept)
    dim_counts: Counter                  # candidate count per dimension
    gid_to_row: dict = field(default_factory=dict)
    row_gids: list = field(default_factory=list)   # row -> gid | None
    free_rows: list = field(default_factory=list)
    offdim: dict = field(default_factory=dict)     # gid -> non-dominant dim
    matrix: object = None                # jnp (capacity, dim)
    valid: object = None                 # jnp (capacity,) f32

    @property
    def size(self) -> int:
        return len(self.gid_to_row)


def _read_vector(va, pid, view):
    """The vertex's embedding candidate, or None."""
    if va is None or not va.is_visible(view):
        return None
    vec = va.get_property(pid, view)
    if isinstance(vec, (list, tuple)) and vec and \
            all(isinstance(x, (int, float)) and not isinstance(x, bool)
                for x in vec):
        return [float(x) for x in vec]
    return None


def _full_build(ctx, pid, version) -> _IndexEntry:
    import jax.numpy as jnp
    STATS["full_builds"] += 1
    vectors, gids = [], []
    if pid is not None:
        for va in ctx.accessor.vertices(ctx.view):
            vec = _read_vector(va, pid, ctx.view)
            if vec is not None:
                vectors.append(vec)
                gids.append(va.gid)
    dim_counts = Counter(len(v) for v in vectors)
    if not vectors:
        return _IndexEntry(version, pid, None, dim_counts)
    dim = dim_counts.most_common(1)[0][0]
    kept = [(v, g) for v, g in zip(vectors, gids) if len(v) == dim]
    mat_np = np.asarray([v for v, _ in kept], dtype=np.float32)
    row_gids = [g for _, g in kept]
    entry = _IndexEntry(
        version, pid, dim, dim_counts,
        gid_to_row={g: i for i, g in enumerate(row_gids)},
        row_gids=list(row_gids),
        offdim={g: len(v) for v, g in zip(vectors, gids)
                if len(v) != dim},
        matrix=jnp.asarray(mat_np),
        valid=jnp.ones(len(row_gids), dtype=jnp.float32))
    return entry


def _delta_refresh(ctx, parent: _IndexEntry, changed, version):
    """New entry at `version` from `parent` by patching only `changed`
    gids; returns None if a full rebuild is required (dominant dimension
    flip, or parent has no matrix yet but vectors appeared)."""
    import jax.numpy as jnp
    pid, view = parent.pid, ctx.view
    dim_counts = Counter(parent.dim_counts)
    gid_to_row = dict(parent.gid_to_row)
    row_gids = list(parent.row_gids)
    free_rows = list(parent.free_rows)
    offdim = dict(parent.offdim)
    set_rows: list[int] = []
    set_vals: list[list[float]] = []
    clear_rows: list[int] = []
    new_vecs: dict = {}

    def drop_row(gid):
        row = gid_to_row.pop(gid, None)
        if row is not None:
            row_gids[row] = None
            free_rows.append(row)
            clear_rows.append(row)

    for gid in changed:
        va = ctx.accessor.find_vertex(gid, view)
        vec = _read_vector(va, pid, view)
        # retire the gid's previous candidate (row or off-dimension)
        if gid in gid_to_row:
            dim_counts[parent.dim] -= 1
        elif gid in offdim:
            dim_counts[offdim.pop(gid)] -= 1
        if vec is None:
            drop_row(gid)
        elif parent.dim is not None and len(vec) == parent.dim:
            dim_counts[parent.dim] += 1
            new_vecs[gid] = vec
        else:
            # off-dimension candidate: counted (dominance tracking,
            # NOTES_ROUND2 hole #4) but holds no row
            dim_counts[len(vec)] += 1
            offdim[gid] = len(vec)
            drop_row(gid)

    dim_counts = Counter({d: c for d, c in dim_counts.items() if c > 0})
    if parent.dim is None:
        return None if dim_counts else _IndexEntry(
            version, pid, None, dim_counts)
    if dim_counts and dim_counts.most_common(1)[0][0] != parent.dim:
        return None                      # dominant dimension flipped

    matrix, valid = parent.matrix, parent.valid
    for gid, vec in new_vecs.items():
        row = gid_to_row.get(gid)
        if row is None:
            if free_rows:
                row = free_rows.pop()
            else:
                row = len(row_gids)
                row_gids.append(None)
                if matrix is None or row >= matrix.shape[0]:
                    grow = max(16, (matrix.shape[0] if matrix is not None
                                    else 0))
                    pad = jnp.zeros((grow, parent.dim), jnp.float32)
                    matrix = (jnp.concatenate([matrix, pad])
                              if matrix is not None else pad)
                    valid = (jnp.concatenate(
                        [valid, jnp.zeros(grow, jnp.float32)])
                        if valid is not None
                        else jnp.zeros(grow, jnp.float32))
            gid_to_row[gid] = row
            row_gids[row] = gid
        set_rows.append(row)
        set_vals.append(vec)

    # clears BEFORE sets: a freed row reused for a new vector in this
    # same refresh must end up valid
    if clear_rows:
        rows = jnp.asarray(np.asarray(clear_rows, dtype=np.int32))
        valid = valid.at[rows].set(0.0)
    if set_rows:
        rows = jnp.asarray(np.asarray(set_rows, dtype=np.int32))
        vals = jnp.asarray(np.asarray(set_vals, dtype=np.float32))
        matrix = matrix.at[rows].set(vals)
        valid = valid.at[rows].set(1.0)

    STATS["delta_refreshes"] += 1
    return _IndexEntry(version, pid, parent.dim, dim_counts,
                       gid_to_row=gid_to_row, row_gids=row_gids,
                       free_rows=free_rows, offdim=offdim,
                       matrix=matrix, valid=valid)


def _get_index(ctx, property_name: str) -> _IndexEntry:
    storage = ctx.storage
    version = getattr(ctx.accessor, "topology_snapshot",
                      storage.topology_version)
    # a transaction with its OWN writes sees state no other reader at
    # this version sees: serve it a PRIVATE entry (parent + own touched
    # gids as extra delta) and never store it — read-your-own-writes
    # without poisoning the shared version map
    own_writes = frozenset(
        getattr(getattr(ctx.accessor, "txn", None), "touched_vertices",
                None) or ())
    with _CACHE_LOCK:
        per = _CACHE.get(storage) or {}
        by_version = dict(per.get(property_name) or {})
    entry = by_version.get(version)
    if entry is not None and not own_writes:
        return entry

    parent = entry
    if parent is None:
        candidates = [e for v, e in by_version.items() if v < version]
        if candidates:
            parent = max(candidates, key=lambda e: e.version)

    entry = None
    if parent is not None:
        from ..storage.storage import ChangeLogUnknowable
        changed = storage.changes_between(parent.version, version)
        if isinstance(changed, ChangeLogUnknowable):
            # typed wrap verdict: the gap is unreconstructable — fall
            # through to the full rebuild below (a partial delta would
            # leave the index silently missing rows)
            changed = None
        else:
            changed = changed | own_writes
        if changed is not None and not changed:
            # nothing relevant changed: alias the parent at this version
            entry = parent
        elif changed is not None and (
                parent.size == 0
                or len(changed) <= max(64,
                                       _DELTA_MAX_FRACTION * parent.size)):
            entry = _delta_refresh(ctx, parent, changed, version)
    if entry is None:
        pid = storage.property_mapper.maybe_name_to_id(property_name)
        entry = _full_build(ctx, pid, version)

    if own_writes:
        return entry                   # private view: never cached

    with _CACHE_LOCK:
        per = _CACHE.get(storage)
        if per is None:
            per = {}
        by_version = per.setdefault(property_name, {})
        by_version[version] = entry
        # keep only the newest few versions (older concurrent readers)
        for v in sorted(by_version)[:-_KEEP_VERSIONS]:
            del by_version[v]
        per[property_name] = by_version
        _CACHE[storage] = per
    return entry


def _search_entry(entry: _IndexEntry, query_rows, k: int, metric: str):
    """(scores (q, k'), row indices (q, k')) over live rows."""
    from ..ops.knn import knn
    k = min(k, entry.size)
    if k <= 0 or entry.matrix is None:
        return None, None
    return knn(entry.matrix, query_rows, k=k, metric=metric,
               valid_mask=entry.valid)


@mgp.read_proc("vector_search.search",
               args=[("property", "STRING"), ("query", "LIST"),
                     ("limit", "INTEGER")],
               opt_args=[("metric", "STRING", "cosine")],
               results=[("node", "NODE"), ("similarity", "FLOAT")])
def search(ctx, property, query, limit, metric="cosine"):
    import jax.numpy as jnp
    entry = _get_index(ctx, property)
    q = jnp.asarray(np.asarray([query], dtype=np.float32))
    scores, idx = _search_entry(entry, q, int(limit), str(metric))
    if scores is None:
        return
    for score, i in zip(np.asarray(scores[0]), np.asarray(idx[0])):
        gid = entry.row_gids[int(i)]
        if gid is None:
            continue
        node = ctx.accessor.find_vertex(gid, ctx.view)
        if node is not None:
            yield {"node": node, "similarity": float(score)}


@mgp.read_proc("vector_search.show_index_info",
               results=[("index_name", "STRING"), ("label", "STRING"),
                        ("property", "STRING"), ("dimension", "INTEGER"),
                        ("size", "INTEGER")])
def show_index_info(ctx):
    with _CACHE_LOCK:
        per = {prop: dict(bv)
               for prop, bv in (_CACHE.get(ctx.storage) or {}).items()}
    for prop, by_version in sorted(per.items()):
        if not by_version:
            continue
        entry = by_version[max(by_version)]
        yield {"index_name": f"vector::{prop}", "label": "*",
               "property": prop,
               "dimension": int(entry.dim or 0),
               "size": entry.size}


@mgp.read_proc("vector_search.ppr_search",
               args=[("property", "STRING"), ("query", "LIST"),
                     ("k_seeds", "INTEGER"), ("limit", "INTEGER")],
               opt_args=[("damping", "FLOAT", 0.85),
                         ("metric", "STRING", "cosine")],
               results=[("node", "NODE"), ("score", "FLOAT"),
                        ("seed_similarity", "FLOAT")])
def ppr_search(ctx, property, query, k_seeds, limit, damping=0.85,
               metric="cosine"):
    """ANN seed → coalesced PPR expansion → rerank.

    The serving-plane sibling of plain ``search``: the k nearest
    embedding rows seed a personalized-PageRank restart, so results
    rank by graph proximity to the semantic matches instead of raw
    cosine alone. With a resident kernel server configured the PPR leg
    is ONE coalesced round trip (batched with every concurrent caller,
    top-k extracted on device, result cache consulted); otherwise it
    runs in-process."""
    import jax.numpy as jnp
    from ..ops.pagerank import personalized_pagerank
    from .graph_algorithms import _kernel_server_ppr

    entry = _get_index(ctx, str(property))
    if entry.matrix is None:
        return
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    q = jnp.asarray(np.asarray([query], dtype=np.float32))
    sims, idx = _search_entry(entry, q, int(k_seeds), str(metric))
    if sims is None:
        return
    seed_sim: dict[int, float] = {}
    seed_indices: list[int] = []
    for sim, i in zip(np.asarray(sims[0]), np.asarray(idx[0])):
        gid = entry.row_gids[int(i)]
        di = graph.gid_to_idx.get(gid) if gid is not None else None
        if di is not None:
            seed_indices.append(di)
            seed_sim[di] = float(sim)
    if not seed_indices:
        return

    served = _kernel_server_ppr(ctx, graph, seed_indices, float(damping),
                                100, 1e-6, top_k=int(limit))
    if served is not None:
        _h, out = served
        pairs = zip(out["topk_val"], out["topk_idx"])
    else:
        ranks, _, _ = personalized_pagerank(graph, seed_indices,
                                            damping=float(damping),
                                            max_iterations=100)
        ranks = np.asarray(ranks)
        order = np.argsort(-ranks)[:int(limit)]
        pairs = ((ranks[i], i) for i in order)
    for score, i in pairs:
        if score <= 0:
            break
        node = ctx.vertex_by_index(graph, int(i))
        if node is not None:
            yield {"node": node, "score": float(score),
                   "seed_similarity": seed_sim.get(int(i), 0.0)}


@mgp.read_proc("knn.get",
               args=[("node", "NODE"), ("property", "STRING"),
                     ("k", "INTEGER")],
               opt_args=[("metric", "STRING", "cosine")],
               results=[("neighbor", "NODE"), ("similarity", "FLOAT")])
def knn_get(ctx, node, property, k, metric="cosine"):
    """k nearest neighbors of an existing node by embedding similarity
    (counterpart of mage/cpp/knn_module)."""
    entry = _get_index(ctx, property)
    if node is None or entry.matrix is None:
        return
    row = entry.gid_to_row.get(node.gid)
    if row is None:
        return
    q = entry.matrix[row:row + 1]
    scores, idx = _search_entry(entry, q, int(k) + 1, str(metric))
    if scores is None:
        return
    emitted = 0
    for score, i in zip(np.asarray(scores[0]), np.asarray(idx[0])):
        if int(i) == row:
            continue
        if emitted >= int(k):
            break
        gid = entry.row_gids[int(i)]
        if gid is None:
            continue
        nb = ctx.accessor.find_vertex(gid, ctx.view)
        if nb is not None:
            emitted += 1
            yield {"neighbor": nb, "similarity": float(score)}
