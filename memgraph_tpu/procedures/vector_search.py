"""Vector search module: brute-force/IVF kNN over node embedding properties.

Counterpart of /root/reference/query_modules/vector_search_module.cpp (which
fronts the usearch HNSW index): here search IS the index — batched MXU
matmul + top_k over a device-resident embedding matrix, cached per
(storage, topology_version, property) and rebuilt from the reader's own
snapshot whenever committed state changed. The rebuild is O(n) host-side;
true row-level delta maintenance is a known follow-up (NOTES_ROUND2.md) —
previous attempt showed it interacts subtly with snapshot isolation and
replica WAL apply, so correctness keeps the simple design for now.
"""

from __future__ import annotations

import threading
import weakref

import numpy as np

from . import mgp

_CACHE_LOCK = threading.Lock()
# storage (weak) -> {(topology_version, property): (matrix, gids)}
_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _embedding_matrix(ctx, property_name: str):
    """(matrix (n, d) jnp array, gids list) for nodes carrying the property.

    Valid for the storage's current topology_version — any commit (and
    replica WAL apply, which bumps the version too) invalidates it. Rows
    with a deviating vector dimension are dropped to the dominant one.
    """
    import jax.numpy as jnp
    storage = ctx.storage
    key = (storage.topology_version, property_name)
    with _CACHE_LOCK:
        per = _CACHE.get(storage)
        hit = per.get(key) if per else None
    if hit is not None:
        return hit
    pid = storage.property_mapper.maybe_name_to_id(property_name)
    vectors = []
    gids = []
    if pid is not None:
        for va in ctx.accessor.vertices(ctx.view):
            vec = va.get_property(pid, ctx.view)
            if isinstance(vec, (list, tuple)) and vec and \
                    all(isinstance(x, (int, float)) and not isinstance(x, bool)
                        for x in vec):
                vectors.append([float(x) for x in vec])
                gids.append(va.gid)
    if vectors:
        from collections import Counter
        dims = Counter(len(v) for v in vectors)
        dim = dims.most_common(1)[0][0]
        kept = [(v, g) for v, g in zip(vectors, gids) if len(v) == dim]
        vectors = [v for v, _ in kept]
        gids = [g for _, g in kept]
    matrix = (jnp.asarray(np.asarray(vectors, dtype=np.float32))
              if vectors else None)
    result = (matrix, gids)
    with _CACHE_LOCK:
        per = _CACHE.get(storage) or {}
        # keep only current-version entries
        per = {k: v for k, v in per.items() if k[0] == key[0]}
        per[key] = result
        _CACHE[storage] = per
    return result


@mgp.read_proc("vector_search.search",
               args=[("property", "STRING"), ("query", "LIST"),
                     ("limit", "INTEGER")],
               opt_args=[("metric", "STRING", "cosine")],
               results=[("node", "NODE"), ("similarity", "FLOAT")])
def search(ctx, property, query, limit, metric="cosine"):
    from ..ops.knn import knn
    import jax.numpy as jnp
    matrix, gids = _embedding_matrix(ctx, property)
    if matrix is None:
        return
    q = jnp.asarray(np.asarray([query], dtype=np.float32))
    k = min(int(limit), len(gids))
    scores, idx = knn(matrix, q, k=k, metric=str(metric))
    scores = np.asarray(scores[0])
    idx = np.asarray(idx[0])
    for score, i in zip(scores, idx):
        node = ctx.accessor.find_vertex(gids[int(i)], ctx.view)
        if node is not None:
            yield {"node": node, "similarity": float(score)}


@mgp.read_proc("vector_search.show_index_info",
               results=[("index_name", "STRING"), ("label", "STRING"),
                        ("property", "STRING"), ("dimension", "INTEGER"),
                        ("size", "INTEGER")])
def show_index_info(ctx):
    with _CACHE_LOCK:
        per = dict(_CACHE.get(ctx.storage) or {})
    for (version, prop), (matrix, gids) in sorted(per.items()):
        yield {"index_name": f"vector::{prop}", "label": "*",
               "property": prop,
               "dimension": (int(matrix.shape[1])
                             if matrix is not None else 0),
               "size": len(gids)}


@mgp.read_proc("knn.get",
               args=[("node", "NODE"), ("property", "STRING"),
                     ("k", "INTEGER")],
               opt_args=[("metric", "STRING", "cosine")],
               results=[("neighbor", "NODE"), ("similarity", "FLOAT")])
def knn_get(ctx, node, property, k, metric="cosine"):
    """k nearest neighbors of an existing node by embedding similarity
    (counterpart of mage/cpp/knn_module)."""
    from ..ops.knn import knn
    import jax.numpy as jnp
    matrix, gids = _embedding_matrix(ctx, property)
    if matrix is None or node is None:
        return
    try:
        row = gids.index(node.gid)
    except ValueError:
        return
    q = matrix[row:row + 1]
    kk = min(int(k) + 1, len(gids))
    scores, idx = knn(matrix, q, k=kk, metric=str(metric))
    scores = np.asarray(scores[0])
    idx = np.asarray(idx[0])
    emitted = 0
    for score, i in zip(scores, idx):
        if int(i) == row:
            continue
        if emitted >= int(k):
            break
        nb = ctx.accessor.find_vertex(gids[int(i)], ctx.view)
        if nb is not None:
            emitted += 1
            yield {"neighbor": nb, "similarity": float(score)}
