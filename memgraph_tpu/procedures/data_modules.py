"""Data-manipulation utility modules: map, collections, create, refactor.

Compact counterparts of the reference's MAGE utility modules
(/root/reference/mage/cpp/{map,collections,create,refactor,merge,nodes}_module):
the procedure names and shapes users rely on for data wrangling.
"""

from __future__ import annotations

from . import mgp
from ..exceptions import ProcedureException


# --- map module --------------------------------------------------------------

@mgp.read_proc("map.from_pairs", args=[("pairs", "LIST")],
               results=[("map", "MAP")])
def map_from_pairs(ctx, pairs):
    out = {}
    for pair in pairs:
        if not isinstance(pair, (list, tuple)) or len(pair) != 2:
            raise ProcedureException("map.from_pairs expects [key, value] pairs")
        out[str(pair[0])] = pair[1]
    yield {"map": out}


@mgp.read_proc("map.merge", args=[("first", "MAP"), ("second", "MAP")],
               results=[("result", "MAP")])
def map_merge(ctx, first, second):
    out = dict(first or {})
    out.update(second or {})
    yield {"result": out}


@mgp.read_proc("map.remove_key", args=[("map", "MAP"), ("key", "STRING")],
               results=[("result", "MAP")])
def map_remove_key(ctx, map, key):
    out = dict(map or {})
    out.pop(key, None)
    yield {"result": out}


@mgp.read_proc("map.flatten", args=[("map", "MAP")],
               opt_args=[("delimiter", "STRING", ".")],
               results=[("result", "MAP")])
def map_flatten(ctx, map, delimiter="."):
    out = {}

    def walk(prefix, value):
        if isinstance(value, dict):
            for k, v in value.items():
                walk(f"{prefix}{delimiter}{k}" if prefix else str(k), v)
        else:
            out[prefix] = value

    walk("", map or {})
    yield {"result": out}


# --- collections module ------------------------------------------------------

@mgp.read_proc("collections.sum", args=[("values", "LIST")],
               results=[("sum", "FLOAT")])
def collections_sum(ctx, values):
    yield {"sum": float(sum(v for v in values if v is not None))}


@mgp.read_proc("collections.avg", args=[("values", "LIST")],
               results=[("avg", "FLOAT")])
def collections_avg(ctx, values):
    vals = [v for v in values if v is not None]
    yield {"avg": (sum(vals) / len(vals)) if vals else 0.0}


@mgp.read_proc("collections.contains", args=[("coll", "LIST"),
                                             ("value", "ANY")],
               results=[("output", "BOOLEAN")])
def collections_contains(ctx, coll, value):
    yield {"output": value in coll}


def _dedupe(values):
    from ..query.values import hashable_key
    seen = set()
    out = []
    for v in values:
        key = hashable_key(v)
        if key not in seen:
            seen.add(key)
            out.append(v)
    return out


@mgp.read_proc("collections.distinct", args=[("values", "LIST")],
               results=[("distinct", "LIST")])
def collections_distinct(ctx, values):
    yield {"distinct": _dedupe(values)}


@mgp.read_proc("collections.sort", args=[("values", "LIST")],
               results=[("sorted", "LIST")])
def collections_sort(ctx, values):
    from ..storage.ordering import order_key
    yield {"sorted": sorted(values, key=order_key)}


@mgp.read_proc("collections.pairs", args=[("values", "LIST")],
               results=[("pairs", "LIST")])
def collections_pairs(ctx, values):
    yield {"pairs": [[values[i], values[i + 1]]
                     for i in range(len(values) - 1)]}


@mgp.read_proc("collections.to_set", args=[("values", "LIST")],
               results=[("result", "LIST")])
def collections_to_set(ctx, values):
    yield {"result": _dedupe(values)}


@mgp.read_proc("collections.partition", args=[("values", "LIST"),
                                              ("size", "INTEGER")],
               results=[("partition", "LIST")])
def collections_partition(ctx, values, size):
    size = int(size)
    if size <= 0:
        raise ProcedureException("partition size must be positive")
    for i in range(0, len(values), size):
        yield {"partition": list(values[i:i + size])}


# --- create module -----------------------------------------------------------

def _make_node(ctx, labels, properties):
    va = ctx.accessor.create_vertex()
    for label in labels or []:
        va.add_label(ctx.storage.label_mapper.name_to_id(str(label)))
    for key, value in (properties or {}).items():
        if value is not None:
            va.set_property(ctx.storage.property_mapper.name_to_id(key),
                            value)
    return va


@mgp.write_proc("create.node",
                opt_args=[("labels", "LIST", None),
                          ("properties", "MAP", None)],
                results=[("node", "NODE")])
def create_node(ctx, labels=None, properties=None):
    yield {"node": _make_node(ctx, labels, properties)}


@mgp.write_proc("create.nodes",
                args=[("labels", "LIST"), ("props", "LIST")],
                results=[("node", "NODE")])
def create_nodes(ctx, labels, props):
    for properties in props:
        yield {"node": _make_node(ctx, labels, properties)}


@mgp.write_proc("create.relationship",
                args=[("from", "NODE"), ("relationshipType", "STRING"),
                      ("properties", "MAP"), ("to", "NODE")],
                results=[("relationship", "RELATIONSHIP")])
def create_relationship(ctx, from_, relationshipType, properties, to):
    tid = ctx.storage.edge_type_mapper.name_to_id(str(relationshipType))
    ea = ctx.accessor.create_edge(from_, to, tid)
    for key, value in (properties or {}).items():
        if value is not None:
            ea.set_property(ctx.storage.property_mapper.name_to_id(key),
                            value)
    yield {"relationship": ea}


@mgp.write_proc("create.remove_labels",
                args=[("node", "NODE"), ("labels", "LIST")],
                results=[("node", "NODE")])
def create_remove_labels(ctx, node, labels):
    for label in labels or []:
        lid = ctx.storage.label_mapper.maybe_name_to_id(str(label))
        if lid is not None:
            node.remove_label(lid)
    yield {"node": node}


# --- refactor module ---------------------------------------------------------

@mgp.write_proc("refactor.rename_label",
                args=[("old_label", "STRING"), ("new_label", "STRING")],
                results=[("nodes_changed", "INTEGER")])
def refactor_rename_label(ctx, old_label, new_label):
    old_id = ctx.storage.label_mapper.maybe_name_to_id(str(old_label))
    new_id = ctx.storage.label_mapper.name_to_id(str(new_label))
    changed = 0
    if old_id is not None:
        for va in list(ctx.accessor.vertices(ctx.view)):
            if va.has_label(old_id, ctx.view):
                va.remove_label(old_id)
                va.add_label(new_id)
                changed += 1
    yield {"nodes_changed": changed}


@mgp.write_proc("refactor.rename_node_property",
                args=[("old_property", "STRING"),
                      ("new_property", "STRING")],
                results=[("nodes_changed", "INTEGER")])
def refactor_rename_property(ctx, old_property, new_property):
    old_id = ctx.storage.property_mapper.maybe_name_to_id(str(old_property))
    new_id = ctx.storage.property_mapper.name_to_id(str(new_property))
    changed = 0
    if old_id is not None:
        for va in list(ctx.accessor.vertices(ctx.view)):
            value = va.get_property(old_id, ctx.view)
            if value is not None:
                va.set_property(new_id, value)
                va.set_property(old_id, None)
                changed += 1
    yield {"nodes_changed": changed}


@mgp.write_proc("refactor.invert",
                args=[("relationship", "RELATIONSHIP")],
                results=[("relationship", "RELATIONSHIP")])
def refactor_invert(ctx, relationship):
    props = relationship.properties(ctx.view)
    tid = relationship.edge_type
    from_v = relationship.from_vertex()
    to_v = relationship.to_vertex()
    ctx.accessor.delete_edge(relationship)
    new_edge = ctx.accessor.create_edge(to_v, from_v, tid)
    for pid, value in props.items():
        new_edge.set_property(pid, value)
    yield {"relationship": new_edge}
