"""node2vec module: device random walks + skip-gram embeddings.

Counterpart of /root/reference/mage/python/node2vec.py and
query_modules/node2vec_online_module/: walks sampled on TPU
(ops/walks.py), embeddings trained with the optax skip-gram trainer
(models/node2vec.py), streamed back as node -> vector rows.
"""

from __future__ import annotations

import numpy as np

from . import mgp


@mgp.read_proc("node2vec.get_embeddings",
               opt_args=[("dimensions", "INTEGER", 128),
                         ("walk_length", "INTEGER", 20),
                         ("walks_per_node", "INTEGER", 4),
                         ("p", "FLOAT", 1.0),
                         ("q", "FLOAT", 1.0),
                         ("window", "INTEGER", 5),
                         ("epochs", "INTEGER", 3),
                         ("learning_rate", "FLOAT", 0.01)],
               results=[("node", "NODE"), ("embedding", "LIST")])
def get_embeddings(ctx, dimensions=128, walk_length=20, walks_per_node=4,
                   p=1.0, q=1.0, window=5, epochs=3, learning_rate=0.01):
    from ..models.node2vec import Node2Vec, Node2VecConfig
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    cfg = Node2VecConfig(
        embedding_dim=int(dimensions), walk_length=int(walk_length),
        walks_per_node=int(walks_per_node), p=float(p), q=float(q),
        window=int(window), epochs=int(epochs),
        learning_rate=float(learning_rate))
    emb = Node2Vec(cfg).fit(graph)
    for i in range(graph.n_nodes):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, "embedding": [float(x) for x in emb[i]]}


@mgp.write_proc("node2vec.set_embeddings",
                opt_args=[("property", "STRING", "embedding"),
                          ("dimensions", "INTEGER", 128),
                          ("walk_length", "INTEGER", 20),
                          ("walks_per_node", "INTEGER", 4),
                          ("epochs", "INTEGER", 3)],
                results=[("nodes_updated", "INTEGER")])
def set_embeddings(ctx, property="embedding", dimensions=128, walk_length=20,
                   walks_per_node=4, epochs=3):
    from ..models.node2vec import Node2Vec, Node2VecConfig
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        yield {"nodes_updated": 0}
        return
    cfg = Node2VecConfig(embedding_dim=int(dimensions),
                         walk_length=int(walk_length),
                         walks_per_node=int(walks_per_node),
                         epochs=int(epochs))
    emb = Node2Vec(cfg).fit(graph)
    pid = ctx.storage.property_mapper.name_to_id(str(property))
    updated = 0
    for i in range(graph.n_nodes):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            node.set_property(pid, [float(x) for x in emb[i]])
            updated += 1
    yield {"nodes_updated": updated}


@mgp.read_proc("node2vec.random_walks",
               args=[("start_nodes", "LIST")],
               opt_args=[("length", "INTEGER", 10),
                         ("p", "FLOAT", 1.0), ("q", "FLOAT", 1.0),
                         ("seed", "INTEGER", 0)],
               results=[("walk", "LIST")])
def random_walks_proc(ctx, start_nodes, length=10, p=1.0, q=1.0, seed=0):
    import jax
    from ..ops.walks import random_walks
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    starts = [graph.gid_to_idx[v.gid] for v in start_nodes
              if v is not None and v.gid in graph.gid_to_idx]
    if not starts:
        return
    walks = np.asarray(random_walks(graph, starts, int(length),
                                    key=jax.random.PRNGKey(int(seed)),
                                    p=float(p), q=float(q)))
    for row in walks:
        nodes = ctx.vertices_by_indices(graph, row)
        yield {"walk": [n for n in nodes if n is not None]}
