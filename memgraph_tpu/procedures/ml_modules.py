"""GNN query modules: link prediction and node classification.

Counterparts of the reference's DGL/PyTorch modules
(mage/python/link_prediction.py — set_model_parameters / train / predict /
recommend / get_training_results / reset_parameters;
mage/python/node_classification.py — set_model_parameters / train /
predict / get_training_data / reset) with the same procedure names and
result fields. The model is the JAX GraphSAGE in ops/gnn.py (TPU MXU
matmuls + sorted segment aggregation) instead of DGL; model state lives on
the storage keyed by graph topology version, so predict() after a write
retrains lazily rather than silently serving a stale model.
"""

from __future__ import annotations

import threading

import numpy as np

from ..exceptions import QueryException
from . import mgp

_DEFAULTS = {
    "hidden_features_size": 64,
    "out_features_size": 32,
    "num_epochs": 30,
    "learning_rate": 0.01,
    "num_layers": 2,
    "node_features_property": "",
    "target_property": "",   # node_classification label property
}


class _ModelSlot:
    def __init__(self):
        self.lock = threading.Lock()
        self.config = dict(_DEFAULTS)
        self.params = None
        self.feats = None
        self.graph = None
        self.emb = None           # cached forward pass; same lifetime as params
        self.n_classes = None
        self.history = []

    def invalidate(self):
        self.params = None
        self.emb = None
        self.history = []


_SLOTS_CREATE_LOCK = threading.Lock()


def _slot(ctx, name) -> _ModelSlot:
    with _SLOTS_CREATE_LOCK:
        slots = getattr(ctx.storage, "_gnn_models", None)
        if slots is None:
            slots = ctx.storage._gnn_models = {}
        if name not in slots:
            slots[name] = _ModelSlot()
        return slots[name]


_INT_PARAMS = {"hidden_features_size", "out_features_size", "num_epochs",
               "num_layers"}


def _validate_parameters(parameters):
    unknown = set(parameters or {}) - set(_DEFAULTS)
    if unknown:
        raise QueryException(f"unknown model parameters: {sorted(unknown)}")
    for key, value in (parameters or {}).items():
        if key in _INT_PARAMS:
            if not isinstance(value, int) or isinstance(value, bool)                     or value <= 0:
                raise QueryException(
                    f"{key} must be a positive integer")
        elif key == "learning_rate":
            if not isinstance(value, (int, float))                     or isinstance(value, bool) or value <= 0:
                raise QueryException("learning_rate must be positive")
        elif not isinstance(value, str):
            raise QueryException(f"{key} must be a string")


def _features(ctx, graph, prop_name):
    """Stack a numeric list property into (n_pad, d) features, or None to
    fall back to degree/positional features."""
    if not prop_name:
        return None
    pid = ctx.storage.property_mapper.maybe_name_to_id(prop_name)
    if pid is None:
        raise QueryException(f"unknown feature property {prop_name!r}")
    rows = []
    dim = None
    for i in range(graph.n_nodes):
        v = ctx.vertex_by_index(graph, i)
        val = v.get_property(pid, ctx.view) if v is not None else None
        if not isinstance(val, (list, tuple)):
            raise QueryException(
                f"node feature property {prop_name!r} must be a numeric "
                f"list on every node")
        if dim is None:
            dim = len(val)
        if len(val) != dim:
            raise QueryException(
                f"node feature property {prop_name!r} has inconsistent "
                f"dimensions")
        rows.append([float(x) for x in val])
    import jax.numpy as jnp
    feats = np.zeros((graph.n_pad, dim), dtype=np.float32)
    if rows:
        feats[:graph.n_nodes] = np.asarray(rows, dtype=np.float32)
    return jnp.asarray(feats)


# --- link_prediction ---------------------------------------------------------


@mgp.read_proc("link_prediction.set_model_parameters",
               args=[("parameters", "MAP")],
               results=[("status", "BOOLEAN"), ("message", "STRING")])
def lp_set_model_parameters(ctx, parameters):
    slot = _slot(ctx, "link_prediction")
    _validate_parameters(parameters)
    with slot.lock:
        slot.config.update(parameters or {})
        slot.invalidate()  # stale params AND history of the old config
    yield {"status": True,
           "message": "Model parameters updated. Train to apply."}


def _ensure_lp_embeddings(ctx, slot):
    """Train if stale, then cache the full-graph forward pass — predict and
    recommend score many pairs against the same embeddings."""
    from ..ops.gnn import sage_forward
    graph = ctx.device_graph()
    if slot.params is None or slot.graph is not graph:
        _train_lp(ctx, slot)
        graph = slot.graph
    if slot.emb is None:
        slot.emb = sage_forward(slot.params, slot.feats, graph.csc_src,
                                graph.csc_dst, graph.n_pad)
    return graph


def _train_lp(ctx, slot):
    from ..ops.gnn import train_link_prediction
    graph = ctx.device_graph()
    if graph.n_edges == 0:
        raise QueryException("link_prediction.train needs at least one "
                             "edge")
    cfg = slot.config
    feats = _features(ctx, graph, cfg["node_features_property"])
    params, feats, history = train_link_prediction(
        graph, feats=feats,
        hidden_dim=int(cfg["hidden_features_size"]),
        out_dim=int(cfg["out_features_size"]),
        n_layers=int(cfg["num_layers"]),
        epochs=int(cfg["num_epochs"]),
        lr=float(cfg["learning_rate"]))
    slot.params, slot.feats, slot.graph = params, feats, graph
    slot.emb = None
    slot.history = history
    return history


@mgp.read_proc("link_prediction.train",
               results=[("training_results", "ANY"),
                        ("validation_results", "ANY")])
def lp_train(ctx):
    slot = _slot(ctx, "link_prediction")
    with slot.lock:
        history = _train_lp(ctx, slot)
    yield {"training_results": history,
           "validation_results": [history[-1]]}


@mgp.read_proc("link_prediction.predict",
               args=[("src_vertex", "NODE"), ("dest_vertex", "NODE")],
               results=[("score", "FLOAT")])
def lp_predict(ctx, src_vertex, dest_vertex):
    from ..ops.gnn import _edge_scores
    import jax
    slot = _slot(ctx, "link_prediction")
    with slot.lock:
        graph = _ensure_lp_embeddings(ctx, slot)
        src = graph.gid_to_idx.get(src_vertex.gid)
        dst = graph.gid_to_idx.get(dest_vertex.gid)
        if src is None or dst is None:
            raise QueryException("vertex is not part of the graph")
        score = jax.nn.sigmoid(_edge_scores(
            slot.emb, np.asarray([src]), np.asarray([dst])))[0]
    yield {"score": float(score)}


@mgp.read_proc("link_prediction.recommend",
               args=[("src_vertex", "NODE"), ("dest_vertexes", "LIST"),
                     ("k", "INTEGER")],
               results=[("score", "FLOAT"), ("recommendation", "NODE")])
def lp_recommend(ctx, src_vertex, dest_vertexes, k):
    from ..ops.gnn import _edge_scores
    import jax
    slot = _slot(ctx, "link_prediction")
    with slot.lock:
        graph = _ensure_lp_embeddings(ctx, slot)
        src = graph.gid_to_idx.get(src_vertex.gid)
        if src is None:
            raise QueryException("vertex is not part of the graph")
        dsts, keep = [], []
        for v in dest_vertexes:
            idx = graph.gid_to_idx.get(v.gid)
            if idx is not None:
                dsts.append(idx)
                keep.append(v)
        if not dsts:
            return
        scores = np.asarray(jax.nn.sigmoid(_edge_scores(
            slot.emb, np.full(len(dsts), src), np.asarray(dsts))))
    order = np.argsort(-scores)[:max(0, int(k))]
    for i in order:
        yield {"score": float(scores[i]), "recommendation": keep[int(i)]}


@mgp.read_proc("link_prediction.get_training_results",
               results=[("training_results", "ANY"),
                        ("validation_results", "ANY")])
def lp_get_training_results(ctx):
    slot = _slot(ctx, "link_prediction")
    with slot.lock:
        if not slot.history:
            raise QueryException("model is not trained yet")
        history = list(slot.history)
    yield {"training_results": history,
           "validation_results": [history[-1]]}


@mgp.read_proc("link_prediction.reset_parameters",
               results=[("status", "ANY")])
def lp_reset_parameters(ctx):
    slot = _slot(ctx, "link_prediction")
    with slot.lock:
        slot.config = dict(_DEFAULTS)
        slot.invalidate()
    yield {"status": "Parameters and model reset."}


# --- node_classification -----------------------------------------------------


@mgp.read_proc("node_classification.set_model_parameters",
               args=[("parameters", "MAP")],
               results=[("status", "BOOLEAN"), ("message", "STRING")])
def nc_set_model_parameters(ctx, parameters):
    slot = _slot(ctx, "node_classification")
    _validate_parameters(parameters)
    with slot.lock:
        slot.config.update(parameters or {})
        slot.invalidate()
    yield {"status": True,
           "message": "Model parameters updated. Train to apply."}


def _train_nc(ctx, slot):
    from ..ops.gnn import train_node_classification
    graph = ctx.device_graph()
    cfg = slot.config
    target = cfg["target_property"] or "label"
    pid = ctx.storage.property_mapper.maybe_name_to_id(target)
    if pid is None:
        raise QueryException(
            f"no node carries the target property {target!r}")
    label_idx, labels = [], []
    for i in range(graph.n_nodes):
        v = ctx.vertex_by_index(graph, i)
        val = v.get_property(pid, ctx.view) if v is not None else None
        if isinstance(val, int) and not isinstance(val, bool):
            label_idx.append(i)
            labels.append(val)
    if not labels:
        raise QueryException(
            f"no node carries an integer {target!r} property")
    feats = _features(ctx, graph, cfg["node_features_property"])
    params, feats, n_classes, history = train_node_classification(
        graph, label_idx, labels, feats=feats,
        hidden_dim=int(cfg["hidden_features_size"]),
        n_layers=int(cfg["num_layers"]),
        epochs=int(cfg["num_epochs"]),
        lr=float(cfg["learning_rate"]))
    slot.params, slot.feats, slot.graph = params, feats, graph
    slot.emb = None
    slot.n_classes = n_classes
    slot.history = history
    return history


@mgp.read_proc("node_classification.train",
               results=[("epoch", "INTEGER"), ("loss", "FLOAT"),
                        ("val_loss", "FLOAT"), ("train_log", "ANY"),
                        ("val_log", "ANY")])
def nc_train(ctx):
    slot = _slot(ctx, "node_classification")
    with slot.lock:
        history = _train_nc(ctx, slot)
    for entry in history:
        yield {"epoch": entry["epoch"], "loss": entry["loss"],
               "val_loss": entry["loss"],
               "train_log": entry, "val_log": entry}


@mgp.read_proc("node_classification.predict",
               args=[("vertex", "NODE")],
               results=[("predicted_class", "INTEGER"),
                        ("status", "STRING")])
def nc_predict(ctx, vertex):
    from ..ops.gnn import sage_forward
    import jax.numpy as jnp
    slot = _slot(ctx, "node_classification")
    with slot.lock:
        graph = ctx.device_graph()
        if slot.params is None or slot.graph is not graph:
            _train_nc(ctx, slot)
            graph = slot.graph
        if slot.emb is None:
            slot.emb = sage_forward(slot.params, slot.feats,
                                    graph.csc_src, graph.csc_dst,
                                    graph.n_pad)
        idx = graph.gid_to_idx.get(vertex.gid)
        if idx is None:
            raise QueryException("vertex is not part of the graph")
        cls = int(jnp.argmax(slot.emb[idx]))
    yield {"predicted_class": cls, "status": "ok"}


@mgp.read_proc("node_classification.get_training_data",
               results=[("epoch", "INTEGER"), ("loss", "FLOAT"),
                        ("val_loss", "FLOAT"), ("train_log", "ANY"),
                        ("val_log", "ANY")])
def nc_get_training_data(ctx):
    slot = _slot(ctx, "node_classification")
    with slot.lock:
        if not slot.history:
            raise QueryException("model is not trained yet")
        history = list(slot.history)
    for entry in history:
        yield {"epoch": entry["epoch"], "loss": entry["loss"],
               "val_loss": entry["loss"],
               "train_log": entry, "val_log": entry}


@mgp.read_proc("node_classification.reset", results=[("status", "STRING")])
def nc_reset(ctx):
    slot = _slot(ctx, "node_classification")
    with slot.lock:
        slot.config = dict(_DEFAULTS)
        slot.invalidate()
    yield {"status": "Model reset."}
