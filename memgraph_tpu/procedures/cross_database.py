"""cross_database.* — stream query results from OTHER graph databases.

Counterpart of /root/reference/mage/python/cross_database.py: the
bolt/neo4j sources connect to a remote Bolt endpoint and stream records
as `row` maps for UNWIND/CREATE composition. The Bolt transport is THIS
repo's own client (server/client.py) — no external driver needed, and
it speaks to any Bolt 4.x/5.x server (memgraph, neo4j, another
memgraph_tpu). Relational sources live in migrate.* (migrate_modules);
`cross_database.sqlite` aliases there for surface parity.
"""

from __future__ import annotations

from ..exceptions import QueryException
from . import mgp


def _label_or_query(text: str) -> str:
    """A bare label/relationship name becomes a full-row MATCH, anything
    else is passed through as Cypher (reference:
    cross_database._formulate_cypher_query)."""
    t = text.strip()
    if t and all(c.isalnum() or c in "_:" for c in t):
        if t.upper().startswith("REL:"):
            rel = t.split(":", 1)[1]
            return (f"MATCH (a)-[r:{rel}]->(b) "
                    "RETURN properties(a) AS from_props, "
                    "properties(r) AS edge_props, "
                    "properties(b) AS to_props")
        label = t.lstrip(":")
        return f"MATCH (n:{label}) RETURN properties(n) AS props"
    return t


def _bolt_rows(config, query, params):
    from ..server.client import BoltClient, BoltClientError
    host = (config or {}).get("host", "127.0.0.1")
    port = int((config or {}).get("port", 7687))
    try:
        client = BoltClient(host=host, port=port,
                            username=(config or {}).get("username", ""),
                            password=(config or {}).get("password", ""))
    except (OSError, BoltClientError) as e:
        raise QueryException(
            f"cross_database: cannot connect to bolt://{host}:{port}: {e}"
        ) from e
    try:
        columns, rows, _summary = client.execute(query, params or {})
        for rec in rows:
            yield {"row": dict(zip(columns, rec))}
    except BoltClientError as e:
        raise QueryException(f"cross_database: remote error: {e}") from e
    finally:
        client.close()


@mgp.read_proc("cross_database.bolt",
               args=[("label_or_query", "STRING"), ("config", "MAP")],
               opt_args=[("params", "MAP", None)],
               results=[("row", "MAP")])
def bolt(ctx, label_or_query, config, params=None):
    """Stream rows from any Bolt server; config: {host, port,
    username, password}."""
    yield from _bolt_rows(config, _label_or_query(label_or_query), params)


@mgp.read_proc("cross_database.neo4j",
               args=[("label_or_query", "STRING"), ("config", "MAP")],
               opt_args=[("params", "MAP", None)],
               results=[("row", "MAP")])
def neo4j(ctx, label_or_query, config, params=None):
    """Neo4j flavor of cross_database.bolt (same wire protocol)."""
    yield from _bolt_rows(config, _label_or_query(label_or_query), params)


@mgp.read_proc("cross_database.sqlite",
               args=[("table_or_sql", "STRING"), ("config", "MAP")],
               opt_args=[("params", "LIST", None)],
               results=[("row", "MAP")])
def sqlite(ctx, table_or_sql, config, params=None):
    from .migrate_modules import migrate_sqlite
    yield from migrate_sqlite(ctx, table_or_sql, config, params)
