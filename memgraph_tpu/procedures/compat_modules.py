"""Compatibility and introspection query modules.

Counterparts of the reference's in-tree query_modules:
  mgps.py         — mgps.components / await_indexes / validate (Spark and
                    Neo4j-connector compatibility shims)
  graph_analyzer.py — graph_analyzer.analyze / analyze_subgraph / help
  schema.cpp      — schema.node_type_properties / rel_type_properties /
                    schema.assert
  mage/python/meta_util.py — meta_util.schema
Same procedure names, arguments, and result fields; the analyzer rides the
TPU kernels (WCC, bridges) instead of NetworkX.
"""

from __future__ import annotations

import collections

from ..exceptions import QueryException
from . import mgp

_SERVER_VERSION = "5.9.0"  # Neo4j-compatible version string (mgps.py:9)


# --- mgps --------------------------------------------------------------------


@mgp.read_proc("mgps.components",
               results=[("versions", "LIST"), ("edition", "STRING"),
                        ("name", "STRING")])
def mgps_components(ctx):
    yield {"versions": [_SERVER_VERSION], "edition": "community",
           "name": "Memgraph"}
    yield {"versions": [_SERVER_VERSION], "edition": "community",
           "name": "Neo4j Kernel"}


@mgp.read_proc("mgps.await_indexes",
               args=[("seconds", "INTEGER")], results=[])
def mgps_await_indexes(ctx, seconds):
    # index creation is synchronous here; compatibility no-op
    return
    yield  # pragma: no cover — makes this a generator


@mgp.read_proc("mgps.validate",
               args=[("predicate", "BOOLEAN"), ("message", "STRING"),
                     ("params", "LIST")],
               results=[])
def mgps_validate(ctx, predicate, message, params):
    if predicate:
        raise QueryException(message % tuple(params))
    return
    yield  # pragma: no cover


# --- graph_analyzer ----------------------------------------------------------


def _build_nx(ctx, vertices=None, edges=None):
    """networkx MultiDiGraph over the visible graph or an explicit
    node/edge subset (the analyzer is delegation territory, like the
    reference's NetworkX-backed graph_analyzer.py)."""
    import networkx as nx
    g = nx.MultiDiGraph()
    if vertices is not None:
        for v in vertices:
            g.add_node(v.gid)
        for e in edges or []:
            if e.from_vertex().gid in g and e.to_vertex().gid in g:
                g.add_edge(e.from_vertex().gid, e.to_vertex().gid)
        return g
    for v in ctx.accessor.vertices(ctx.view):
        g.add_node(v.gid)
        for e in v.out_edges(ctx.view):
            g.add_edge(v.gid, e.to_vertex().gid)
    return g


def _analyses(g):
    """Reference analysis names -> callables over a networkx MultiDiGraph
    (graph_analyzer.py _get_analysis_mapping)."""
    import networkx as nx

    def und():
        return nx.Graph(g)

    return collections.OrderedDict([
        ("nodes", g.number_of_nodes),
        ("edges", g.number_of_edges),
        ("bridges", lambda: sum(1 for _ in nx.bridges(und()))),
        ("articulation_points",
         lambda: sum(1 for _ in nx.articulation_points(und()))),
        ("avg_degree",
         lambda: (2.0 * g.number_of_edges() / g.number_of_nodes())
         if g.number_of_nodes() else 0.0),
        ("self_loops", lambda: nx.number_of_selfloops(g)),
        ("is_bipartite", lambda: nx.is_bipartite(und())),
        ("is_weakly_connected",
         lambda: g.number_of_nodes() > 0 and nx.is_weakly_connected(g)),
        ("number_of_weakly_components",
         lambda: nx.number_weakly_connected_components(g)),
        ("is_strongly_connected",
         lambda: g.number_of_nodes() > 0 and nx.is_strongly_connected(g)),
        ("strongly_components",
         lambda: nx.number_strongly_connected_components(g)),
        ("is_dag", lambda: nx.is_directed_acyclic_graph(g)),
        ("is_eulerian",
         lambda: g.number_of_nodes() > 0 and nx.is_eulerian(g)),
        ("is_forest", lambda: nx.is_forest(und())
         if g.number_of_nodes() else False),
        ("is_tree", lambda: nx.is_tree(und())
         if g.number_of_nodes() else False),
    ])


def _run_analyses(g, analyses):
    available = _analyses(g)
    wanted = list(available) if analyses is None else analyses
    for name in wanted:
        fn = available.get(name)
        if fn is None:
            raise QueryException(
                f"unknown analysis {name!r}; available: "
                f"{sorted(available)}")
        try:
            value = fn()
        except Exception as exc:  # e.g. is_eulerian on disconnected graphs
            value = f"unavailable ({exc})"
        yield {"name": name, "value": str(value)}


@mgp.read_proc("graph_analyzer.analyze",
               opt_args=[("analyses", "LIST", None)],
               results=[("name", "STRING"), ("value", "STRING")])
def graph_analyzer_analyze(ctx, analyses=None):
    yield from _run_analyses(_build_nx(ctx), analyses)


@mgp.read_proc("graph_analyzer.analyze_subgraph",
               args=[("vertices", "LIST"), ("edges", "LIST")],
               opt_args=[("analyses", "LIST", None)],
               results=[("name", "STRING"), ("value", "STRING")])
def graph_analyzer_analyze_subgraph(ctx, vertices, edges, analyses=None):
    yield from _run_analyses(_build_nx(ctx, vertices, edges), analyses)


@mgp.read_proc("graph_analyzer.help",
               results=[("name", "STRING"), ("value", "STRING")])
def graph_analyzer_help(ctx):
    yield {"name": "Procedure 'analyze'",
           "value": "CALL graph_analyzer.analyze([analyses]) YIELD *"}
    yield {"name": "Procedure 'analyze_subgraph'",
           "value": "CALL graph_analyzer.analyze_subgraph(nodes, edges) "
                    "YIELD *"}
    for name in _analyses(_build_nx(ctx)):
        yield {"name": f"Analysis '{name}'", "value": name}


# --- schema ------------------------------------------------------------------


def _type_name(v):
    from ..query.values import type_name
    return type_name(v)


@mgp.read_proc("schema.node_type_properties",
               results=[("nodeType", "STRING"), ("nodeLabels", "LIST"),
                        ("mandatory", "BOOLEAN"),
                        ("propertyName", "STRING"),
                        ("propertyTypes", "LIST")])
def schema_node_type_properties(ctx):
    """One row per (label set, property) with observed value types
    (reference schema.cpp node_type_properties)."""
    label_mapper = ctx.storage.label_mapper
    prop_mapper = ctx.storage.property_mapper
    # (labels tuple) -> {prop name -> set(type names)}, plus per-group count
    groups: dict = {}
    for v in ctx.accessor.vertices(ctx.view):
        labels = tuple(sorted(label_mapper.id_to_name(l)
                              for l in v.labels(ctx.view)))
        g = groups.setdefault(labels, {"count": 0, "props": {}})
        g["count"] += 1
        for pid, val in v.properties(ctx.view).items():
            name = prop_mapper.id_to_name(pid)
            entry = g["props"].setdefault(name, {"types": set(), "seen": 0})
            entry["types"].add(_type_name(val))
            entry["seen"] += 1
    for labels in sorted(groups):
        g = groups[labels]
        node_type = ":" + ":".join(f"`{l}`" for l in labels) if labels \
            else ""
        if not g["props"]:
            yield {"nodeType": node_type, "nodeLabels": list(labels),
                   "mandatory": False, "propertyName": "",
                   "propertyTypes": []}
            continue
        for name in sorted(g["props"]):
            entry = g["props"][name]
            yield {"nodeType": node_type, "nodeLabels": list(labels),
                   "mandatory": entry["seen"] == g["count"],
                   "propertyName": name,
                   "propertyTypes": sorted(entry["types"])}


@mgp.read_proc("schema.rel_type_properties",
               results=[("relType", "STRING"),
                        ("sourceNodeLabels", "LIST"),
                        ("targetNodeLabels", "LIST"),
                        ("mandatory", "BOOLEAN"),
                        ("propertyName", "STRING"),
                        ("propertyTypes", "LIST")])
def schema_rel_type_properties(ctx):
    label_mapper = ctx.storage.label_mapper
    type_mapper = ctx.storage.edge_type_mapper
    prop_mapper = ctx.storage.property_mapper
    groups: dict = {}
    for v in ctx.accessor.vertices(ctx.view):
        for e in v.out_edges(ctx.view):
            src_labels = tuple(sorted(label_mapper.id_to_name(l)
                                      for l in v.labels(ctx.view)))
            dst_labels = tuple(sorted(
                label_mapper.id_to_name(l)
                for l in e.to_vertex().labels(ctx.view)))
            key = (type_mapper.id_to_name(e.edge_type), src_labels,
                   dst_labels)
            g = groups.setdefault(key, {"count": 0, "props": {}})
            g["count"] += 1
            for pid, val in e.properties(ctx.view).items():
                name = prop_mapper.id_to_name(pid)
                entry = g["props"].setdefault(
                    name, {"types": set(), "seen": 0})
                entry["types"].add(_type_name(val))
                entry["seen"] += 1
    for key in sorted(groups):
        type_name_, src_labels, dst_labels = key
        g = groups[key]
        rel_type = f":`{type_name_}`"
        if not g["props"]:
            yield {"relType": rel_type,
                   "sourceNodeLabels": list(src_labels),
                   "targetNodeLabels": list(dst_labels),
                   "mandatory": False, "propertyName": "",
                   "propertyTypes": []}
            continue
        for name in sorted(g["props"]):
            entry = g["props"][name]
            yield {"relType": rel_type,
                   "sourceNodeLabels": list(src_labels),
                   "targetNodeLabels": list(dst_labels),
                   "mandatory": entry["seen"] == g["count"],
                   "propertyName": name,
                   "propertyTypes": sorted(entry["types"])}


def _esc(name):
    # Cypher escapes backticks by doubling them inside a quoted identifier
    return str(name).replace("`", "``")


def _constraint_lists(props):
    """Normalize a constraint spec to a list of property tuples: the
    reference shape is a list of property LISTS (schema.cpp
    CreateUniqueConstraintsForLabel); a flat list of strings is accepted
    as one single-property constraint per entry."""
    out = []
    for item in props or []:
        if isinstance(item, (list, tuple)):
            out.append(tuple(str(p) for p in item))
        else:
            out.append((str(item),))
    return out


@mgp.read_proc("schema.assert",
               args=[("indices", "MAP"), ("unique_constraints", "MAP"),
                     ("existence_constraints", "MAP")],
               opt_args=[("drop_existing", "BOOLEAN", True)],
               results=[("action", "STRING"), ("key", "STRING"),
                        ("keys", "LIST"), ("label", "STRING"),
                        ("unique", "BOOLEAN")])
def schema_assert(ctx, indices, unique_constraints, existence_constraints,
                  drop_existing=True):
    """Reconcile indexes/constraints to the asserted state (reference
    schema.cpp Assert): create what's missing, report 'Kept' for what
    already matches, and with drop_existing drop indexes AND constraints
    that exist but weren't asserted. indices maps label -> list of
    properties ([] or [""] asserts a label index); unique_constraints maps
    label -> list of property lists."""
    from .apoc_modules import _sub_interpreter
    interp = _sub_interpreter(ctx)
    storage = ctx.storage

    asserted_label = set()
    asserted_prop = set()
    for label, props in (indices or {}).items():
        for prop in (props if props else [""]):
            if prop:
                asserted_prop.add((label, str(prop)))
            else:
                asserted_label.add(label)
    existing_label = {storage.label_mapper.id_to_name(l)
                      for l in storage.indices.label.labels()}
    existing_prop = {
        (storage.label_mapper.id_to_name(lid),
         ", ".join(storage.property_mapper.id_to_name(p) for p in pids))
        for lid, pids in storage.indices.label_property.keys()}

    asserted_unique = {
        (label, key) for label, props in (unique_constraints or {}).items()
        for key in _constraint_lists(props)}
    asserted_exist = {
        (label, str(p)) for label, props in
        (existence_constraints or {}).items()
        for key in _constraint_lists(props) for p in key}
    existing_unique = {
        (storage.label_mapper.id_to_name(lid),
         tuple(storage.property_mapper.id_to_name(p) for p in pids))
        for lid, pids in storage.constraints.unique.all()}
    existing_exist = {
        (storage.label_mapper.id_to_name(lid),
         storage.property_mapper.id_to_name(pid))
        for lid, pid in storage.constraints.existence.all()}

    for label in sorted(asserted_label):
        if label in existing_label:
            yield {"action": "Kept", "key": label, "keys": [],
                   "label": label, "unique": False}
        else:
            interp.execute(f"CREATE INDEX ON :`{_esc(label)}`")
            yield {"action": "Created", "key": label, "keys": [],
                   "label": label, "unique": False}
    for label, prop in sorted(asserted_prop):
        if (label, prop) in existing_prop:
            yield {"action": "Kept", "key": prop, "keys": [prop],
                   "label": label, "unique": False}
        else:
            interp.execute(
                f"CREATE INDEX ON :`{_esc(label)}`(`{_esc(prop)}`)")
            yield {"action": "Created", "key": prop, "keys": [prop],
                   "label": label, "unique": False}
    for label, key in sorted(asserted_unique):
        if (label, key) in existing_unique:
            yield {"action": "Kept", "key": ", ".join(key),
                   "keys": list(key), "label": label, "unique": True}
        else:
            plist = ", ".join(f"n.`{_esc(p)}`" for p in key)
            interp.execute(
                f"CREATE CONSTRAINT ON (n:`{_esc(label)}`) "
                f"ASSERT {plist} IS UNIQUE")
            yield {"action": "Created", "key": ", ".join(key),
                   "keys": list(key), "label": label, "unique": True}
    for label, prop in sorted(asserted_exist):
        if (label, prop) in existing_exist:
            yield {"action": "Kept", "key": prop, "keys": [prop],
                   "label": label, "unique": False}
        else:
            interp.execute(
                f"CREATE CONSTRAINT ON (n:`{_esc(label)}`) "
                f"ASSERT EXISTS (n.`{_esc(prop)}`)")
            yield {"action": "Created", "key": prop, "keys": [prop],
                   "label": label, "unique": False}
    if drop_existing:
        for label in sorted(existing_label - asserted_label):
            interp.execute(f"DROP INDEX ON :`{_esc(label)}`")
            yield {"action": "Dropped", "key": label, "keys": [],
                   "label": label, "unique": False}
        for label, prop in sorted(existing_prop - asserted_prop):
            props = f"`{'`, `'.join(_esc(p.strip()) for p in prop.split(','))}`"
            interp.execute(f"DROP INDEX ON :`{_esc(label)}`({props})")
            yield {"action": "Dropped", "key": prop,
                   "keys": [p.strip() for p in prop.split(",")],
                   "label": label, "unique": False}
        for label, key in sorted(existing_unique - asserted_unique):
            plist = ", ".join(f"n.`{_esc(p)}`" for p in key)
            interp.execute(
                f"DROP CONSTRAINT ON (n:`{_esc(label)}`) "
                f"ASSERT {plist} IS UNIQUE")
            yield {"action": "Dropped", "key": ", ".join(key),
                   "keys": list(key), "label": label, "unique": True}
        for label, prop in sorted(existing_exist - asserted_exist):
            interp.execute(
                f"DROP CONSTRAINT ON (n:`{_esc(label)}`) "
                f"ASSERT EXISTS (n.`{_esc(prop)}`)")
            yield {"action": "Dropped", "key": prop, "keys": [prop],
                   "label": label, "unique": False}


# --- meta_util ---------------------------------------------------------------


@mgp.read_proc("meta_util.schema",
               opt_args=[("include_properties", "BOOLEAN", False)],
               results=[("nodes", "LIST"), ("relationships", "LIST")])
def meta_util_schema(ctx, include_properties=False):
    """Graph schema as node/relationship descriptor maps with the
    reference's field shapes (mage/python/meta_util.py +
    mage/meta_util/parameters.py): nodes carry {id, labels,
    properties: {count[, properties_count]}, type: "node"}; relationships
    carry {id, start, end, label, properties, type: "relationship"}.
    Raises on an empty database like the reference."""
    label_mapper = ctx.storage.label_mapper
    type_mapper = ctx.storage.edge_type_mapper
    prop_mapper = ctx.storage.property_mapper
    node_groups: dict = {}
    rel_groups: dict = {}
    empty = True
    for v in ctx.accessor.vertices(ctx.view):
        empty = False
        labels = tuple(sorted(label_mapper.id_to_name(l)
                              for l in v.labels(ctx.view)))
        g = node_groups.setdefault(
            labels, {"count": 0, "properties": collections.Counter()})
        g["count"] += 1
        if include_properties:
            for pid in v.properties(ctx.view):
                g["properties"][prop_mapper.id_to_name(pid)] += 1
        for e in v.out_edges(ctx.view):
            dst_labels = tuple(sorted(
                label_mapper.id_to_name(l)
                for l in e.to_vertex().labels(ctx.view)))
            key = (labels, type_mapper.id_to_name(e.edge_type), dst_labels)
            rg = rel_groups.setdefault(
                key, {"count": 0, "properties": collections.Counter()})
            rg["count"] += 1
            if include_properties:
                for pid in e.properties(ctx.view):
                    rg["properties"][prop_mapper.id_to_name(pid)] += 1
    if empty:
        raise QueryException(
            "Can't generate a graph schema since there is no data in the "
            "database.")

    def props_map(g):
        if include_properties:
            return {"count": g["count"],
                    "properties_count": dict(g["properties"])}
        return {"count": g["count"]}

    nodes = []
    node_id = {}
    for i, labels in enumerate(sorted(node_groups)):
        node_id[labels] = i
        nodes.append({"id": i, "labels": list(labels),
                      "properties": props_map(node_groups[labels]),
                      "type": "node"})
    relationships = []
    for i, key in enumerate(sorted(rel_groups)):
        src, type_name_, dst = key
        relationships.append({
            "id": i, "start": node_id[src], "end": node_id[dst],
            "label": type_name_,
            "properties": props_map(rel_groups[key]),
            "type": "relationship"})
    yield {"nodes": nodes, "relationships": relationships}
