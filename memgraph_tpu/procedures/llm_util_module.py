"""llm_util.* — graph schema rendered for LLM prompts.

Counterpart of /root/reference/mage/python/llm_util.py: `schema()`
returns either a prompt-ready natural-language schema description or
the raw structure, assembled from the live schema info
(storage/schema_info.py) instead of a fresh full scan.
"""

from __future__ import annotations

import json

from ..exceptions import QueryException
from . import mgp


def _raw_schema(accessor) -> list:
    from ..storage.common import View
    from ..storage.schema_info import schema_info_json
    doc = json.loads(schema_info_json(accessor, View.OLD))
    out = []
    for node in doc.get("nodes", []):
        labels = ":".join(node.get("labels", []))
        props = {p["key"]: [t["type"] for t in p.get("types", [])]
                 for p in node.get("properties", [])}
        out.append({"kind": "node", "labels": labels, "properties": props,
                    "count": node.get("count", 0)})
    for edge in doc.get("edges", []):
        props = {p["key"]: [t["type"] for t in p.get("types", [])]
                 for p in edge.get("properties", [])}
        out.append({
            "kind": "relationship", "type": edge.get("type", ""),
            "start": ":".join(edge.get("start_node_labels", [])),
            "end": ":".join(edge.get("end_node_labels", [])),
            "properties": props, "count": edge.get("count", 0)})
    return out


def _prompt_ready(raw: list) -> str:
    lines = ["Node properties are the following:"]
    for item in raw:
        if item["kind"] != "node":
            continue
        props = ", ".join(f"{k}: {'/'.join(v) or 'Any'}"
                          for k, v in sorted(item["properties"].items()))
        lines.append(f'Node name: "{item["labels"] or "(no label)"}", '
                     f"Node properties: [{props}]")
    lines.append("Relationship properties are the following:")
    for item in raw:
        if item["kind"] != "relationship" or not item["properties"]:
            continue
        props = ", ".join(f"{k}: {'/'.join(v) or 'Any'}"
                          for k, v in sorted(item["properties"].items()))
        lines.append(f'Relationship name: "{item["type"]}", '
                     f"Relationship properties: [{props}]")
    lines.append("The relationships are the following:")
    for item in raw:
        if item["kind"] != "relationship":
            continue
        lines.append(f'(:{item["start"]})-[:{item["type"]}]->'
                     f'(:{item["end"]})')
    return "\n".join(lines)


@mgp.read_proc("llm_util.schema",
               opt_args=[("output_type", "STRING", "prompt_ready")],
               results=[("schema", "ANY")])
def schema(ctx, output_type="prompt_ready"):
    if not any(True for _ in ctx.accessor.vertices()):
        raise QueryException("can't generate a graph schema since there "
                             "is no data in the database")
    raw = _raw_schema(ctx.accessor)
    if output_type == "raw":
        yield {"schema": raw}
    elif output_type == "prompt_ready":
        yield {"schema": _prompt_ready(raw)}
    else:
        raise QueryException(
            "llm_util.schema: output_type must be 'prompt_ready' or 'raw'")
