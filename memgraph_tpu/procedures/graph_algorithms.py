"""Centrality / community / component / path modules on TPU.

API parity with the reference's modules:
  pagerank.get            (query_modules/pagerank_module/pagerank_online_module.cpp)
  pagerank.stream-free static variant (mage/cpp/pagerank_module)
  katz_centrality.get     (query_modules/katz_centrality_module/)
  community_detection.get (query_modules/community_detection_module/)
  weakly_connected_components.get / wcc.get (mage/cpp/connectivity_module)
  strongly_connected_components.get
  degree_centrality.get   (mage/cpp/degree_centrality_module)
  betweenness_centrality.get (sampled Brandes via multi-source BFS)
  hits.get                (cugraph_module/algorithms/hits.cu analog)
  bfs.get / sssp.get path utilities

All `*_tpu` aliases expose the same procedures for explicit dispatch.
"""

from __future__ import annotations

import logging
import os
import threading

import numpy as np

from . import mgp

log = logging.getLogger(__name__)

#: per-(socket, graph_key) serving-plane sync state: the last
#: (topology_version, node_gids) this process pushed to the daemon, so
#: the next request ships the change-log DELTA covering the gap —
#: the PPR plane invalidates only the cached sources it touches, and
#: the analytics ops (r19 mgdelta) refresh the resident generation
#: O(delta) and warm-start from its previous solution
_PPR_PUSHED: dict = {}
_PPR_PUSHED_LOCK = threading.Lock()


def _rank_results(ctx, graph, values, field_name):
    for i in range(graph.n_nodes):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, field_name: float(values[i])}


def _kernel_route_socket(ctx) -> str | None:
    """The resident-kernel-server socket analytics should route through,
    or None for the in-process path. Config key ``kernel_server_socket``
    (the server entry point sets it) or the
    MEMGRAPH_TPU_ANALYTICS_KERNEL_SERVER env var; the value "1" means
    the default socket."""
    ictx = getattr(ctx.exec_ctx, "interpreter_context", None)
    cfg = getattr(ictx, "config", None) or {}
    sock = cfg.get("kernel_server_socket") or os.environ.get(
        "MEMGRAPH_TPU_ANALYTICS_KERNEL_SERVER")
    if not sock:
        return None
    if sock in ("1", "default"):
        from ..server.kernel_server import DEFAULT_SOCKET
        return DEFAULT_SOCKET
    return str(sock)


def _kernel_client(sock: str, spawn: bool):
    from ..server.kernel_server import shared_client
    return shared_client(sock, spawn=spawn)


def _graph_coo(graph):
    """Host COO arrays of the true edges (weights only when real)."""
    if graph.host_coo is not None:
        src, dst, w = graph.host_coo
        return (np.asarray(src, dtype=np.int64),
                np.asarray(dst, dtype=np.int64),
                None if w is None else np.asarray(w, dtype=np.float32))
    n = graph.n_edges
    return (np.asarray(graph.src_idx, dtype=np.int64)[:n],
            np.asarray(graph.col_idx, dtype=np.int64)[:n],
            np.asarray(graph.weights, dtype=np.float32)[:n])


def _serving_delta_meta(ctx, graph, sock: str, graph_key: str):
    """Shared serving-plane sync envelope (the `_ppr_serving_meta`
    pattern promoted to ALL analytics ops, r19 mgdelta): a stable
    per-storage graph_key, the reader's topology version, and — when
    this process already pushed an earlier version — the change-log
    delta payload covering the gap (dense changed indices PLUS those
    vertices' current incident edges), so the server refreshes its
    resident generation O(delta) and never needs the full edge list
    re-shipped. ``send_graph`` says whether the edge arrays must ride
    along (server behind with no usable delta, or never fed)."""
    from ..ops.delta import incident_edges
    storage = ctx.storage
    version = getattr(ctx.accessor, "topology_snapshot",
                      storage.topology_version)
    meta = {"graph_key": graph_key, "graph_version": version,
            "base_version": None, "ids_stable": True,
            "send_graph": True}
    with _PPR_PUSHED_LOCK:
        prev = _PPR_PUSHED.get((sock, graph_key))
    if prev is None:
        return meta
    prev_version, prev_gids = prev
    ids_stable = prev_gids is graph.node_gids or \
        np.array_equal(prev_gids, graph.node_gids)
    meta["ids_stable"] = ids_stable
    if not ids_stable:
        return meta
    if prev_version == version:
        meta["send_graph"] = False
        meta["base_version"] = version
        return meta
    if prev_version < version and graph.host_coo is not None:
        gids = storage.changes_between(prev_version, version)
        # typed wrap verdict (ChangeLogUnknowable) → full re-ship: the
        # gap is unreconstructable and a partial delta would corrupt
        # the resident generation
        if isinstance(gids, frozenset):
            changed_idx = [graph.gid_to_idx[g] for g in gids
                           if g in graph.gid_to_idx]
            bitmap = np.zeros(graph.n_nodes, dtype=bool)
            if changed_idx:
                bitmap[np.asarray(changed_idx, dtype=np.int64)] = True
            inc_src, inc_dst, inc_w = incident_edges(
                *graph.host_coo, bitmap)
            meta.update(base_version=prev_version, changed=changed_idx,
                        inc_src=inc_src, inc_dst=inc_dst, inc_w=inc_w,
                        send_graph=False)
    return meta


def _drop_pushed(sock: str, graph_key: str) -> None:
    """Forget the pushed version after a kernel-plane failure: the next
    request re-ships the full graph instead of a delta the (possibly
    respawned) server cannot anchor."""
    with _PPR_PUSHED_LOCK:
        _PPR_PUSHED.pop((sock, graph_key), None)


def _kernel_server_pagerank(ctx, graph, damping, max_iterations, tol):
    """Route pagerank through the resident kernel server when one is
    configured; returns ranks or None (→ caller runs in-process).

    Rides the resident-generation layer (r19 mgdelta): the graph_key is
    stable per storage, commits ship the change-log delta instead of
    the full edge list, and the server warm-starts the fixpoint from
    its previous solution — commit-then-CALL costs O(delta) apply plus
    the few iterations the perturbation needs.

    The dispatch's device attribution (transfer/compile/iterate splits)
    ships home in the reply and lands in the active stage accumulator,
    so PROFILE on the routed query still shows where HBM-seconds went.
    A kernel-plane failure falls back to the in-process path LOUDLY —
    analytics availability beats routing purity."""
    sock = _kernel_route_socket(ctx)
    if sock is None:
        return None
    from ..observability.metrics import global_metrics
    from ..server.kernel_server import KernelServerError
    graph_key = f"analytics:{hex(id(ctx.storage))}"
    meta = _serving_delta_meta(ctx, graph, sock, graph_key)
    kwargs = {}
    if meta.pop("send_graph"):
        src, dst, weights = _graph_coo(graph)
        kwargs.update(src=src, dst=dst, weights=weights)
    try:
        client = _kernel_client(sock, spawn=False)
        ranks, _err, _iters = client.pagerank(
            n_nodes=graph.n_nodes,
            damping=float(damping), max_iterations=int(max_iterations),
            tol=float(tol), **meta, **kwargs)
        _note_ppr_pushed(sock, graph_key, meta["graph_version"],
                         graph.node_gids)
        global_metrics.increment("analytics.kernel_routed_total")
        return np.asarray(ranks)[:graph.n_nodes]
    except (KernelServerError, ConnectionError, OSError) as e:
        _drop_pushed(sock, graph_key)
        global_metrics.increment("analytics.kernel_route_fallback_total")
        log.warning("kernel-server pagerank route failed (%s: %s); "
                    "falling back to the in-process path",
                    type(e).__name__, e)
        return None


def _ppr_serving_meta(ctx, graph, sock: str):
    """The PPR serving-plane sync envelope: the shared
    :func:`_serving_delta_meta` layer under the PPR graph_key. Since
    r19 the delta payload carries the changed vertices' current
    incident edges too, so the server's resident snapshot refreshes
    O(delta) (and the result cache demotes off that SAME shipped delta)
    instead of the client re-shipping the full edge list after every
    commit."""
    return _serving_delta_meta(ctx, graph, sock,
                               f"ppr:{hex(id(ctx.storage))}")


def _note_ppr_pushed(sock: str, graph_key: str, version, node_gids):
    with _PPR_PUSHED_LOCK:
        _PPR_PUSHED[(sock, graph_key)] = (version, node_gids)


def _kernel_server_ppr(ctx, graph, sources, damping, max_iterations,
                       tol, top_k=0):
    """Route one PPR through the resident server's COALESCING plane.
    Concurrent Cypher queries batch into one multi-source SpMM fixpoint
    and repeats ride the change-log-invalidated result cache. Returns
    the (reply_header, arrays) pair or None (→ in-process fallback,
    LOUD)."""
    sock = _kernel_route_socket(ctx)
    if sock is None:
        return None
    from ..observability.metrics import global_metrics
    from ..server.kernel_server import KernelServerError
    meta = _ppr_serving_meta(ctx, graph, sock)
    kwargs = {}
    if meta.pop("send_graph"):
        src, dst, weights = _graph_coo(graph)
        kwargs.update(src=src, dst=dst, weights=weights)
    try:
        client = _kernel_client(sock, spawn=False)
        h, out = client.ppr(
            sources=np.asarray(sources, dtype=np.int32),
            n_nodes=graph.n_nodes, damping=float(damping),
            max_iterations=int(max_iterations), tol=float(tol),
            top_k=int(top_k), **meta, **kwargs)
        _note_ppr_pushed(sock, meta["graph_key"], meta["graph_version"],
                         graph.node_gids)
        global_metrics.increment("analytics.kernel_routed_total")
        return h, out
    except (KernelServerError, ConnectionError, OSError) as e:
        _drop_pushed(sock, meta["graph_key"])
        global_metrics.increment("analytics.kernel_route_fallback_total")
        log.warning("kernel-server PPR route failed (%s: %s); "
                    "falling back to the in-process path",
                    type(e).__name__, e)
        return None


def _warm_prepare(ctx, graph, algo: str, params_key: tuple):
    """In-process commit-then-CALL state without a kernel server
    (ops/delta.py LocalWarmPool): (cached_result | None, x0 | None,
    store_fn). A non-None cached_result is the UNCHANGED graph's stored
    solution, served verbatim (identical repeated CALLs must return
    identical bytes); x0 seeds the fixpoint after a commit."""
    from ..observability import stats as mgstats
    from ..ops import delta as mgdelta
    storage = ctx.storage
    version = getattr(ctx.accessor, "topology_snapshot",
                      storage.topology_version)
    cached, x0 = mgdelta.GLOBAL_WARM_POOL.prepare(storage, graph,
                                                  version, algo,
                                                  params_key)
    if cached is not None and mgstats.stages_active():
        # PROFILE-d CALL: a verbatim cache hit would attribute zero
        # device stages — exactly what the profile exists to measure.
        # Demote the hit to a warm seed (the fixpoint re-converges in
        # O(1) iterations) and DON'T store the re-iterated bytes: the
        # stored solution stays the cache of record, so unprofiled
        # repeated CALLs keep returning identical bytes.
        return None, np.asarray(cached), (lambda x, iters=None: None)

    def store(x, iters=None):
        mgdelta.GLOBAL_WARM_POOL.store(storage, graph, version, algo,
                                       params_key, np.asarray(x))
        if x0 is not None and iters is not None:
            mgdelta.record_warm_start(algo, int(iters))

    return cached, x0, store


def _pagerank_impl(ctx, max_iterations=100, damping_factor=0.85,
                   stop_epsilon=1e-5, weight_property=None):
    from ..ops.pagerank import pagerank
    graph = ctx.device_graph(weight_property=weight_property)
    if graph.n_nodes == 0:
        return
    ranks = _kernel_server_pagerank(ctx, graph, damping_factor,
                                    max_iterations, stop_epsilon)
    if ranks is None:
        cached, x0, store = _warm_prepare(
            ctx, graph, "pagerank",
            ("pagerank", float(damping_factor), float(stop_epsilon),
             int(max_iterations), weight_property))
        if cached is not None:
            ranks = cached
        else:
            ranks, _, iters = pagerank(
                graph, damping=float(damping_factor),
                max_iterations=int(max_iterations),
                tol=float(stop_epsilon), x0=x0)
            store(ranks, iters)
    ranks = np.asarray(ranks)
    yield from _rank_results(ctx, graph, ranks, "rank")


for _name in ("pagerank.get", "pagerank_tpu.get", "pagerank_online.get"):
    mgp.read_proc(_name,
                  opt_args=[("max_iterations", "INTEGER", 100),
                            ("damping_factor", "FLOAT", 0.85),
                            ("stop_epsilon", "FLOAT", 1e-5),
                            ("weight_property", "STRING", None)],
                  results=[("node", "NODE"), ("rank", "FLOAT")])(_pagerank_impl)


@mgp.read_proc("pagerank.personalized",
               args=[("source_nodes", "LIST")],
               opt_args=[("max_iterations", "INTEGER", 100),
                         ("damping_factor", "FLOAT", 0.85)],
               results=[("node", "NODE"), ("rank", "FLOAT")])
def personalized_pagerank(ctx, source_nodes, max_iterations=100,
                          damping_factor=0.85):
    from ..ops.pagerank import personalized_pagerank as ppr
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    sources = [graph.gid_to_idx[v.gid] for v in source_nodes
               if v is not None and v.gid in graph.gid_to_idx]
    if not sources:
        return
    served = _kernel_server_ppr(ctx, graph, sources,
                                float(damping_factor),
                                int(max_iterations), 1e-6)
    if served is not None:
        _h, out = served
        ranks = np.asarray(out["ranks"])[:graph.n_nodes]
    else:
        ranks, _, _ = ppr(graph, sources, damping=float(damping_factor),
                          max_iterations=int(max_iterations))
    yield from _rank_results(ctx, graph, np.asarray(ranks), "rank")


def _katz_impl(ctx, alpha=0.2, epsilon=1e-2):
    from ..ops.katz import katz_centrality
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    cached, x0, store = _warm_prepare(
        ctx, graph, "katz", ("katz", float(alpha), float(epsilon)))
    if cached is not None:
        xs = cached
    else:
        xs, _, iters = katz_centrality(graph, alpha=float(alpha),
                                       tol=float(epsilon),
                                       max_iterations=500, x0=x0)
        store(xs, iters)
    yield from _rank_results(ctx, graph, np.asarray(xs), "rank")


for _name in ("katz_centrality.get", "katz_centrality_tpu.get",
              "katz_centrality_online.get"):
    mgp.read_proc(_name,
                  opt_args=[("alpha", "FLOAT", 0.2),
                            ("epsilon", "FLOAT", 1e-2)],
                  results=[("node", "NODE"), ("rank", "FLOAT")])(_katz_impl)


def _community_impl(ctx, max_iterations=30, weight_property=None):
    from ..ops.labelprop import label_propagation
    graph = ctx.device_graph(weight_property=weight_property)
    if graph.n_nodes == 0:
        return
    # warm seed only over monotone (adds-only) deltas — the pool
    # verifies against the real edge diff and cold-starts LOUDLY else
    cached, labels0, store = _warm_prepare(
        ctx, graph, "labelprop",
        ("labelprop", int(max_iterations), weight_property))
    if cached is not None:
        labels = cached
    else:
        labels, iters = label_propagation(
            graph, max_iterations=int(max_iterations), labels0=labels0)
        store(labels, iters)
    labels = np.asarray(labels)
    # compact community ids to 1..k (reference convention: ids start at 1)
    uniq = {int(l): i + 1 for i, l in enumerate(sorted(set(labels.tolist())))}
    for i in range(graph.n_nodes):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, "community_id": uniq[int(labels[i])]}


for _name in ("community_detection.get", "community_detection_tpu.get",
              "community_detection_online.get", "label_propagation.get"):
    mgp.read_proc(_name,
                  opt_args=[("max_iterations", "INTEGER", 30),
                            ("weight_property", "STRING", None)],
                  results=[("node", "NODE"),
                           ("community_id", "INTEGER")])(_community_impl)


def _wcc_impl(ctx):
    from ..ops.components import weakly_connected_components
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    # warm seed only over monotone (adds-only) deltas — min-labels can
    # merge components but never split; removals cold-start LOUDLY
    cached, comp0, store = _warm_prepare(ctx, graph, "wcc", ("wcc",))
    if cached is not None:
        comp = cached
    else:
        comp, iters = weakly_connected_components(graph, comp0=comp0)
        store(comp, iters)
    comp = np.asarray(comp)
    for i in range(graph.n_nodes):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, "component_id": int(comp[i])}


for _name in ("weakly_connected_components.get", "wcc.get",
              "connectivity.get", "wcc_tpu.get"):
    mgp.read_proc(_name,
                  results=[("node", "NODE"),
                           ("component_id", "INTEGER")])(_wcc_impl)


@mgp.read_proc("strongly_connected_components.get",
               results=[("node", "NODE"), ("component_id", "INTEGER")])
def scc_get(ctx):
    from ..ops.components import strongly_connected_components
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    comp = np.asarray(strongly_connected_components(graph))
    for i in range(graph.n_nodes):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, "component_id": int(comp[i])}


@mgp.read_proc("degree_centrality.get",
               opt_args=[("type", "STRING", "undirected")],
               results=[("node", "NODE"), ("degree", "FLOAT")])
def degree_get(ctx, type="undirected"):
    from ..ops.katz import degree_centrality
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    direction = {"in": "in", "out": "out"}.get(str(type).lower(), "total")
    degs = np.asarray(degree_centrality(graph, direction))
    yield from _rank_results(ctx, graph, degs, "degree")


@mgp.read_proc("hits.get",
               opt_args=[("max_iterations", "INTEGER", 100),
                         ("tolerance", "FLOAT", 1e-6)],
               results=[("node", "NODE"), ("hub", "FLOAT"),
                        ("authority", "FLOAT")])
def hits_get(ctx, max_iterations=100, tolerance=1e-6):
    from ..ops.katz import hits
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    hub, auth, _, _ = hits(graph, max_iterations=int(max_iterations),
                           tol=float(tolerance))
    hub, auth = np.asarray(hub), np.asarray(auth)
    for i in range(graph.n_nodes):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, "hub": float(hub[i]),
                   "authority": float(auth[i])}


@mgp.read_proc("betweenness_centrality.get",
               opt_args=[("normalized", "BOOLEAN", True),
                         ("directed", "BOOLEAN", True),
                         ("num_samples", "INTEGER", 64)],
               results=[("node", "NODE"),
                        ("betweenness_centrality", "FLOAT")])
def betweenness_get(ctx, normalized=True, directed=True, num_samples=64):
    """Sampled Brandes: pivots' BFS distances on device, dependency
    accumulation per pivot (reference: mage/cpp/betweenness_centrality_module;
    the sampling approach matches its online variant's spirit)."""
    from ..ops.traversal import multi_source_sssp
    graph = ctx.device_graph()
    n = graph.n_nodes
    if n == 0:
        return
    rng = np.random.default_rng(0)
    k = min(int(num_samples), n)
    pivots = rng.choice(n, size=k, replace=False)
    dist = np.asarray(multi_source_sssp(graph, pivots, weighted=False,
                                        directed=bool(directed)))
    # host-side dependency accumulation over the (small) pivot set
    src = np.asarray(graph.src_idx)[:graph.n_edges]
    dst = np.asarray(graph.col_idx)[:graph.n_edges]
    bc = np.zeros(n, dtype=np.float64)
    for pi in range(k):
        d = dist[pi]
        finite = np.isfinite(d)
        # count shortest paths via BFS layers
        sigma = np.zeros(n)
        sigma[pivots[pi]] = 1.0
        maxd = int(d[finite].max()) if finite.any() else 0
        for level in range(1, maxd + 1):
            on_edge = finite[src] & finite[dst] & \
                (d[src] == level - 1) & (d[dst] == level)
            np.add.at(sigma, dst[on_edge], sigma[src[on_edge]])
        delta = np.zeros(n)
        for level in range(maxd, 0, -1):
            on_edge = finite[src] & finite[dst] & \
                (d[src] == level - 1) & (d[dst] == level)
            with np.errstate(divide="ignore", invalid="ignore"):
                contrib = np.where(sigma[dst[on_edge]] > 0,
                                   sigma[src[on_edge]] / sigma[dst[on_edge]]
                                   * (1.0 + delta[dst[on_edge]]), 0.0)
            np.add.at(delta, src[on_edge], contrib)
        delta[pivots[pi]] = 0.0
        bc += delta
    bc *= n / max(k, 1)  # scale sample to population
    if normalized and n > 2:
        scale = 1.0 / ((n - 1) * (n - 2))
        if not directed:
            scale *= 2.0
        bc *= scale
    if not directed:
        bc /= 2.0
    for i in range(n):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            yield {"node": node, "betweenness_centrality": float(bc[i])}


@mgp.read_proc("bfs.get",
               args=[("source", "NODE")],
               opt_args=[("directed", "BOOLEAN", True)],
               results=[("node", "NODE"), ("level", "INTEGER")])
def bfs_get(ctx, source, directed=True):
    from ..ops.traversal import bfs_levels
    graph = ctx.device_graph()
    if graph.n_nodes == 0 or source is None:
        return
    sidx = graph.gid_to_idx.get(source.gid)
    if sidx is None:
        return
    levels, _ = bfs_levels(graph, sidx, directed=bool(directed))
    levels = np.asarray(levels)
    for i in range(graph.n_nodes):
        if levels[i] >= 0:
            node = ctx.vertex_by_index(graph, i)
            if node is not None:
                yield {"node": node, "level": int(levels[i])}


@mgp.read_proc("sssp.get",
               args=[("source", "NODE")],
               opt_args=[("weight_property", "STRING", "weight")],
               results=[("node", "NODE"), ("distance", "FLOAT")])
def sssp_get(ctx, source, weight_property="weight"):
    from ..ops.traversal import sssp
    graph = ctx.device_graph(weight_property=weight_property)
    if graph.n_nodes == 0 or source is None:
        return
    sidx = graph.gid_to_idx.get(source.gid)
    if sidx is None:
        return
    dist, _ = sssp(graph, sidx, weighted=True, directed=True)
    dist = np.asarray(dist)
    for i in range(graph.n_nodes):
        if np.isfinite(dist[i]):
            node = ctx.vertex_by_index(graph, i)
            if node is not None:
                yield {"node": node, "distance": float(dist[i])}


@mgp.read_proc("graph_util.khop",
               args=[("sources", "LIST"), ("hops", "INTEGER")],
               opt_args=[("directed", "BOOLEAN", False)],
               results=[("node", "NODE")])
def khop_get(ctx, sources, hops, directed=False):
    from ..ops.traversal import khop_neighborhood
    graph = ctx.device_graph()
    if graph.n_nodes == 0:
        return
    idxs = [graph.gid_to_idx[v.gid] for v in sources
            if v is not None and v.gid in graph.gid_to_idx]
    if not idxs:
        return
    mask = np.asarray(khop_neighborhood(graph, idxs, int(hops),
                                        directed=bool(directed)))
    for i in np.nonzero(mask)[0]:
        node = ctx.vertex_by_index(graph, int(i))
        if node is not None:
            yield {"node": node}
