"""Combinatorial-optimization query modules.

Counterparts of the reference's MAGE modules
  mage/python/max_flow.py        — max_flow.get_flow / get_paths
  mage/python/union_find.py      — union_find.connected
  mage/python/graph_coloring.py  — graph_coloring.color_graph/color_subgraph
  mage/python/tsp.py             — tsp.solve
  mage/python/vrp.py             — vrp.route
  mage/python/set_cover.py       — set_cover.cp_solve / greedy
  mage/python/temporal.py        — temporal.format
  mage/cpp/bipartite_matching_module — bipartite_matching.max
  mage/cpp/leiden_community_detection_module — leiden_community_detection.get

Same procedure names, argument lists, and result fields. Deviations from the
reference are algorithmic, not behavioral: set_cover.cp_solve uses the greedy
ln(n)-approximation instead of a constraint-programming solver (no ortools in
this build), tsp's "1.5-approx" falls back to the MST 2-approximation (no
perfect-matching solver), and vrp.route uses Clarke-Wright savings instead of
a CP solver. Connectivity for union_find rides the TPU WCC kernel
(ops/components.py) through the version-keyed device-graph cache, so repeated
calls on an unchanged graph are O(1) lookups.
"""

from __future__ import annotations

import collections
import math

import numpy as np

from ..exceptions import QueryException
from . import mgp

_EARTH_RADIUS_M = 6_371_000.0


# --- max_flow ----------------------------------------------------------------


def _capacity_network(ctx, edge_property: str):
    """{u_gid: {v_gid: capacity}} over the MVCC-visible directed graph."""
    pid = ctx.storage.property_mapper.maybe_name_to_id(edge_property)
    cap: dict = collections.defaultdict(lambda: collections.defaultdict(float))
    edge_of: dict = {}
    for v in ctx.accessor.vertices(ctx.view):
        for e in v.out_edges(ctx.view):
            c = e.get_property(pid, ctx.view) if pid is not None else None
            if c is None:
                continue
            try:
                c = float(c)
            except (TypeError, ValueError):
                continue
            if c <= 0:
                continue
            cap[v.gid][e.to_vertex().gid] += c
            edge_of.setdefault((v.gid, e.to_vertex().gid), e)
    return cap, edge_of


def _bfs_augment(cap, residual, source, sink):
    """Shortest augmenting path in the residual network (Edmonds-Karp)."""
    parent = {source: None}
    queue = collections.deque([source])
    while queue:
        u = queue.popleft()
        if u == sink:
            break
        for v, c in residual[u].items():
            if c > 1e-12 and v not in parent:
                parent[v] = u
                queue.append(v)
    if sink not in parent:
        return None, 0.0
    path = [sink]
    while parent[path[-1]] is not None:
        path.append(parent[path[-1]])
    path.reverse()
    bottleneck = min(residual[path[i]][path[i + 1]]
                     for i in range(len(path) - 1))
    return path, bottleneck


def max_flow_on(cap, source, sink):
    """Edmonds-Karp over a prebuilt {u: {v: capacity}} network. Returns
    (net-flow {(u,v): f>0}, total, final residual)."""
    residual: dict = collections.defaultdict(
        lambda: collections.defaultdict(float))
    for u, outs in cap.items():
        for v, c in outs.items():
            residual[u][v] += c
            residual[v][u] += 0.0
    total = 0.0
    while True:
        path, flow = _bfs_augment(cap, residual, source, sink)
        if path is None:
            break
        for i in range(len(path) - 1):
            residual[path[i]][path[i + 1]] -= flow
            residual[path[i + 1]][path[i]] += flow
        total += flow
    net = {}
    for u, outs in cap.items():
        for v, c in outs.items():
            f = c - residual[u][v]
            if f > 1e-12:
                net[(u, v)] = f
    return net, total, residual


def undirect_capacities(cap):
    """Each directed capacity also usable in reverse (igraph convention)."""
    out = collections.defaultdict(lambda: collections.defaultdict(float))
    for u, outs in cap.items():
        for v, c in outs.items():
            out[u][v] += c
            out[v][u] += c
    return out


def residual_reachable(residual, source_gid):
    """Gids on the source side of the min cut: BFS over leftover capacity
    in the solver's final residual."""
    reachable = {source_gid}
    queue = collections.deque([source_gid])
    while queue:
        u = queue.popleft()
        for v, c in residual.get(u, {}).items():
            if c > 1e-12 and v not in reachable:
                reachable.add(v)
                queue.append(v)
    return reachable


def _solve_max_flow(ctx, start_v, end_v, edge_property, directed=True):
    """Edmonds-Karp over the MVCC-visible capacity network. Returns
    (net-flow {(u,v): f>0}, total, edge_of). With directed=False each edge
    contributes capacity both ways (the igraph undirected convention)."""
    cap, edge_of = _capacity_network(ctx, edge_property)
    if not directed:
        for (u, v) in list(edge_of):
            edge_of.setdefault((v, u), edge_of[(u, v)])
        cap = undirect_capacities(cap)
    net, total, _ = max_flow_on(cap, start_v.gid, end_v.gid)
    return net, total, edge_of


def _decompose_flow(net, source, sink):
    """Split a net flow into forward-only source->sink paths: each walk
    follows positive-flow arcs and subtracts its bottleneck, so the yielded
    flows sum to the max flow (reverse residual arcs cancel in the net)."""
    outs = collections.defaultdict(dict)
    for (u, v), f in net.items():
        outs[u][v] = f
    paths = []
    while outs[source]:
        path = [source]
        seen = {source}
        while path[-1] != sink:
            nxts = outs[path[-1]]
            nxt = next((v for v in nxts if v not in seen), None)
            if nxt is None:
                break
            seen.add(nxt)
            path.append(nxt)
        if path[-1] != sink:
            break  # leftover circulation that never reaches the sink
        bottleneck = min(outs[path[i]][path[i + 1]]
                         for i in range(len(path) - 1))
        for i in range(len(path) - 1):
            u, v = path[i], path[i + 1]
            outs[u][v] -= bottleneck
            if outs[u][v] <= 1e-12:
                del outs[u][v]
        paths.append((path, bottleneck))
    return paths


@mgp.read_proc("max_flow.get_flow",
               args=[("start_v", "NODE"), ("end_v", "NODE")],
               opt_args=[("edge_property", "STRING", "weight")],
               results=[("max_flow", "FLOAT")])
def max_flow_get_flow(ctx, start_v, end_v, edge_property="weight"):
    _, total, _ = _solve_max_flow(ctx, start_v, end_v, edge_property)
    yield {"max_flow": float(total)}


@mgp.read_proc("max_flow.get_paths",
               args=[("start_v", "NODE"), ("end_v", "NODE")],
               opt_args=[("edge_property", "STRING", "weight")],
               results=[("path", "PATH"), ("flow", "FLOAT")])
def max_flow_get_paths(ctx, start_v, end_v, edge_property="weight"):
    from ..query.values import Path
    net, _, edge_of = _solve_max_flow(ctx, start_v, end_v, edge_property)
    for gids, flow in _decompose_flow(net, start_v.gid, end_v.gid):
        items = [ctx.accessor.find_vertex(gids[0], ctx.view)]
        ok = items[0] is not None
        for i in range(len(gids) - 1):
            edge = edge_of.get((gids[i], gids[i + 1]))
            nxt = ctx.accessor.find_vertex(gids[i + 1], ctx.view)
            if edge is None or nxt is None:
                ok = False
                break
            items.extend([edge, nxt])
        if ok:
            yield {"path": Path(items), "flow": float(flow)}


# --- union_find --------------------------------------------------------------


def _wcc_labels(ctx, update: bool):
    """gid -> component label, via the TPU WCC kernel; cached on storage."""
    cached = getattr(ctx.storage, "_union_find_labels", None)
    if not update and cached is not None:
        return cached
    from ..ops.components import weakly_connected_components
    graph = ctx.device_graph()
    labels = {}
    if graph.n_nodes:
        comp = np.asarray(weakly_connected_components(graph)[0])
        gids = np.asarray(graph.node_gids[:graph.n_nodes])
        labels = {int(g): int(c) for g, c in zip(gids, comp[:graph.n_nodes])}
    ctx.storage._union_find_labels = labels
    return labels


@mgp.read_proc("union_find.connected",
               args=[("nodes1", "ANY"), ("nodes2", "ANY")],
               opt_args=[("mode", "STRING", "pairwise"),
                         ("update", "BOOLEAN", True)],
               results=[("node1", "NODE"), ("node2", "NODE"),
                        ("connected", "BOOLEAN")])
def union_find_connected(ctx, nodes1, nodes2, mode="pairwise", update=True):
    labels = _wcc_labels(ctx, update)
    lhs = nodes1 if isinstance(nodes1, (list, tuple)) else [nodes1]
    rhs = nodes2 if isinstance(nodes2, (list, tuple)) else [nodes2]
    if mode == "pairwise":
        if len(lhs) != len(rhs):
            raise QueryException(
                "union_find.connected pairwise mode needs equal-length lists")
        pairs = zip(lhs, rhs)
    elif mode == "cartesian":
        pairs = ((a, b) for a in lhs for b in rhs)
    else:
        raise QueryException(f"unknown union_find mode {mode!r}")
    for a, b in pairs:
        same = (labels.get(a.gid) is not None
                and labels.get(a.gid) == labels.get(b.gid))
        yield {"node1": a, "node2": b, "connected": same}


# --- graph_coloring ----------------------------------------------------------


def _undirected_adjacency(ctx, vertices=None, edges=None):
    """gid -> set(gid). Whole visible graph, or an explicit subgraph."""
    adj = collections.defaultdict(set)
    if vertices is not None:
        for v in vertices:
            adj[v.gid]  # ensure isolated vertices appear
        for e in edges or []:
            a, b = e.from_vertex().gid, e.to_vertex().gid
            adj[a].add(b)
            adj[b].add(a)
        return adj
    for v in ctx.accessor.vertices(ctx.view):
        adj[v.gid]
        for e in v.out_edges(ctx.view):
            adj[v.gid].add(e.to_vertex().gid)
            adj[e.to_vertex().gid].add(v.gid)
    return adj


def _dsatur(adj, no_of_colors=None):
    """DSATUR greedy coloring: highest saturation first, ties by degree.

    With no_of_colors set, assignment is clamped into [0, k): a node whose
    neighbors already use every color takes the least-conflicting one (the
    reference's metaheuristic also minimizes conflicts at a fixed k rather
    than guaranteeing a proper coloring, graph_coloring.py parameters)."""
    colors: dict[int, int] = {}
    saturation = {g: set() for g in adj}
    uncolored = set(adj)
    while uncolored:
        g = max(uncolored,
                key=lambda x: (len(saturation[x]), len(adj[x]), -x))
        used = saturation[g]
        color = 0
        while color in used:
            color += 1
        if no_of_colors is not None and color >= no_of_colors:
            counts = collections.Counter(
                colors[nb] for nb in adj[g] if nb in colors)
            color = min(range(no_of_colors), key=lambda c: counts.get(c, 0))
        colors[g] = color
        uncolored.discard(g)
        for nb in adj[g]:
            saturation[nb].add(color)
    return colors


def _coloring_budget(parameters):
    if not parameters:
        return None
    k = parameters.get("no_of_colors")
    if k is None:
        return None
    if not isinstance(k, int) or isinstance(k, bool) or k <= 0:
        raise QueryException("no_of_colors must be a positive integer")
    return k


@mgp.read_proc("graph_coloring.color_graph",
               opt_args=[("parameters", "MAP", None),
                         ("edge_property", "STRING", "weight")],
               results=[("node", "NODE"), ("color", "INTEGER")])
def graph_coloring_color_graph(ctx, parameters=None, edge_property="weight"):
    colors = _dsatur(_undirected_adjacency(ctx), _coloring_budget(parameters))
    for gid, color in sorted(colors.items()):
        node = ctx.accessor.find_vertex(gid, ctx.view)
        if node is not None:
            yield {"node": node, "color": int(color)}


@mgp.read_proc("graph_coloring.color_subgraph",
               args=[("vertices", "LIST"), ("edges", "LIST")],
               opt_args=[("parameters", "MAP", None),
                         ("edge_property", "STRING", "weight")],
               results=[("node", "NODE"), ("color", "INTEGER")])
def graph_coloring_color_subgraph(ctx, vertices, edges, parameters=None,
                                  edge_property="weight"):
    colors = _dsatur(_undirected_adjacency(ctx, vertices, edges),
                     _coloring_budget(parameters))
    by_gid = {v.gid: v for v in vertices}
    for gid, color in sorted(colors.items()):
        if gid in by_gid:
            yield {"node": by_gid[gid], "color": int(color)}


# --- tsp / vrp ---------------------------------------------------------------


def _latlng(v, ctx):
    lat = _prop(ctx, v, "lat")
    lng = _prop(ctx, v, "lng")
    if lat is None or lng is None:
        raise QueryException(
            "tsp/vrp nodes need numeric 'lat' and 'lng' properties")
    return float(lat), float(lng)


def _prop(ctx, v, name):
    pid = ctx.storage.property_mapper.maybe_name_to_id(name)
    return None if pid is None else v.get_property(pid, ctx.view)


def _haversine_matrix(coords):
    """All-pairs great-circle distance (meters) via one vectorized pass."""
    arr = np.radians(np.asarray(coords, dtype=np.float64))
    lat, lng = arr[:, 0:1], arr[:, 1:2]
    dlat = lat - lat.T
    dlng = lng - lng.T
    a = (np.sin(dlat / 2) ** 2
         + np.cos(lat) * np.cos(lat.T) * np.sin(dlng / 2) ** 2)
    return 2 * _EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0.0, 1.0)))


def _tour_greedy(dist):
    n = dist.shape[0]
    seen = {0}
    tour = [0]
    while len(tour) < n:
        cur = tour[-1]
        order = np.argsort(dist[cur])
        nxt = next(int(i) for i in order if int(i) not in seen)
        seen.add(nxt)
        tour.append(nxt)
    return tour


def _tour_mst(dist):
    """MST preorder walk — the classic 2-approximation."""
    from scipy.sparse.csgraph import minimum_spanning_tree
    n = dist.shape[0]
    mst = minimum_spanning_tree(dist).toarray()
    adj = collections.defaultdict(list)
    for i in range(n):
        for j in range(n):
            if mst[i, j] > 0:
                adj[i].append(j)
                adj[j].append(i)
    tour, stack, seen = [], [0], set()
    while stack:
        u = stack.pop()
        if u in seen:
            continue
        seen.add(u)
        tour.append(u)
        for nb in sorted(adj[u], reverse=True):
            stack.append(nb)
    return tour


def _two_opt(tour, dist, max_rounds=8):
    n = len(tour)
    for _ in range(max_rounds):
        improved = False
        for i in range(n - 2):
            for j in range(i + 2, n - (0 if i else 1)):
                a, b = tour[i], tour[i + 1]
                c, d = tour[j], tour[(j + 1) % n]
                if dist[a, b] + dist[c, d] > dist[a, c] + dist[b, d] + 1e-12:
                    tour[i + 1:j + 1] = reversed(tour[i + 1:j + 1])
                    improved = True
        if not improved:
            break
    return tour


@mgp.read_proc("tsp.solve",
               args=[("points", "LIST")],
               opt_args=[("method", "STRING", "greedy")],
               results=[("sources", "LIST"), ("destinations", "LIST")])
def tsp_solve(ctx, points, method="greedy"):
    if not points:
        yield {"sources": None, "destinations": None}
        return
    coords = [_latlng(v, ctx) for v in points]
    dist = _haversine_matrix(coords)
    # reference accepts '2_approx'/'1.5_approx' (lowercased) and silently
    # falls back to greedy on unknown names (mage/python/tsp.py)
    method = str(method).lower().replace("-", "_")
    if method in ("2_approx", "1.5_approx"):
        tour = _tour_mst(dist)
    else:
        tour = _two_opt(_tour_greedy(dist), dist)
    cycle = tour + [tour[0]]
    yield {"sources": [points[i] for i in cycle[:-1]],
           "destinations": [points[i] for i in cycle[1:]]}


@mgp.read_proc("vrp.route",
               args=[("depot_node", "NODE")],
               opt_args=[("number_of_vehicles", "INTEGER", None)],
               results=[("from_vertex", "NODE"), ("to_vertex", "NODE")])
def vrp_route(ctx, depot_node, number_of_vehicles=None):
    """Clarke-Wright savings: start with depot->i->depot routes, merge the
    pairs with the largest savings until the vehicle budget is met."""
    if number_of_vehicles is not None and number_of_vehicles <= 0:
        raise QueryException("Number of vehicles must be greater than 0.")
    stops = [v for v in ctx.accessor.vertices(ctx.view)
             if v.gid != depot_node.gid
             and _prop(ctx, v, "lat") is not None
             and _prop(ctx, v, "lng") is not None]
    if not stops:
        return
    coords = [_latlng(depot_node, ctx)] + [_latlng(v, ctx) for v in stops]
    dist = _haversine_matrix(coords)
    n = len(stops)
    target = min(number_of_vehicles or 1, n)
    routes = {i: [i] for i in range(1, n + 1)}   # route-id -> stop indices
    owner = {i: i for i in range(1, n + 1)}      # stop index -> route-id
    savings = sorted(
        ((dist[0, i] + dist[0, j] - dist[i, j], i, j)
         for i in range(1, n + 1) for j in range(i + 1, n + 1)),
        reverse=True)
    for s, i, j in savings:
        if len(routes) <= target:
            break
        ri, rj = owner[i], owner[j]
        if ri == rj:
            continue
        a, b = routes[ri], routes[rj]
        # merge only at route endpoints (classic CW interior rule)
        if a[-1] == i and b[0] == j:
            merged = a + b
        elif b[-1] == j and a[0] == i:
            merged = b + a
        elif a[0] == i and b[0] == j:
            merged = list(reversed(a)) + b
        elif a[-1] == i and b[-1] == j:
            merged = a + list(reversed(b))
        else:
            continue
        del routes[rj]
        routes[ri] = merged
        for idx in merged:
            owner[idx] = ri
    for route in routes.values():
        hops = [0] + route + [0]
        for k in range(len(hops) - 1):
            frm = depot_node if hops[k] == 0 else stops[hops[k] - 1]
            to = depot_node if hops[k + 1] == 0 else stops[hops[k + 1] - 1]
            yield {"from_vertex": frm, "to_vertex": to}


# --- set_cover ---------------------------------------------------------------


def _set_cover_greedy(element_vertexes, set_vertexes):
    if len(element_vertexes) != len(set_vertexes):
        raise QueryException(
            "set_cover inputs must be equal-length element/set lists")
    members = collections.defaultdict(set)
    by_gid = {}
    for el, st in zip(element_vertexes, set_vertexes):
        members[st.gid].add(el.gid)
        by_gid[st.gid] = st
    uncovered = set()
    for el in element_vertexes:
        uncovered.add(el.gid)
    chosen = []
    while uncovered:
        best = max(members, key=lambda g: len(members[g] & uncovered))
        gain = members[best] & uncovered
        if not gain:
            break
        uncovered -= gain
        chosen.append(by_gid[best])
        del members[best]
    return chosen


@mgp.read_proc("set_cover.cp_solve",
               args=[("element_vertexes", "LIST"), ("set_vertexes", "LIST")],
               results=[("containing_set", "NODE")])
def set_cover_cp_solve(ctx, element_vertexes, set_vertexes):
    for st in _set_cover_greedy(element_vertexes, set_vertexes):
        yield {"containing_set": st}


@mgp.read_proc("set_cover.greedy",
               args=[("element_vertexes", "LIST"), ("set_vertexes", "LIST")],
               results=[("containing_set", "NODE")])
def set_cover_greedy(ctx, element_vertexes, set_vertexes):
    for st in _set_cover_greedy(element_vertexes, set_vertexes):
        yield {"containing_set": st}


# --- bipartite_matching ------------------------------------------------------


@mgp.read_proc("bipartite_matching.max",
               results=[("maximum_bipartite_matching", "INTEGER")])
def bipartite_matching_max(ctx):
    """2-color the graph; if bipartite, run Hopcroft-Karp. Non-bipartite
    graphs report 0, matching the reference's is_graph_bipartite gate
    (mage/cpp/bipartite_matching_module/algorithm/bipartite_matching.cpp)."""
    adj = _undirected_adjacency(ctx)
    side = {}
    for start in adj:
        if start in side:
            continue
        side[start] = 0
        queue = collections.deque([start])
        while queue:
            u = queue.popleft()
            for v in adj[u]:
                if v not in side:
                    side[v] = side[u] ^ 1
                    queue.append(v)
                elif side[v] == side[u]:
                    yield {"maximum_bipartite_matching": 0}
                    return
    left = [g for g, s in side.items() if s == 0]
    matching = _hopcroft_karp(adj, left)
    yield {"maximum_bipartite_matching": int(matching)}


def _hopcroft_karp(adj, left):
    INF = math.inf
    match_l: dict = {u: None for u in left}
    match_r: dict = {}
    total = 0
    while True:
        dist = {}
        queue = collections.deque()
        for u in left:
            if match_l[u] is None:
                dist[u] = 0
                queue.append(u)
            else:
                dist[u] = INF
        found = False
        while queue:
            u = queue.popleft()
            for v in adj[u]:
                w = match_r.get(v)
                if w is None:
                    found = True
                elif dist.get(w, INF) is INF:
                    dist[w] = dist[u] + 1
                    queue.append(w)

        def dfs(root):
            # explicit stack of (u, iterator over u's neighbors) frames —
            # augmenting paths can be thousands of vertices long, past
            # Python's recursion limit
            stack = [(root, iter(adj[root]))]
            trail = []  # (u, v) edges taken downward
            while stack:
                u, it = stack[-1]
                advanced = False
                for v in it:
                    w = match_r.get(v)
                    if w is None:
                        # free right vertex: flip the whole trail
                        for pu, pv in trail:
                            match_l[pu] = pv
                            match_r[pv] = pu
                        match_l[u] = v
                        match_r[v] = u
                        return True
                    if dist.get(w) == dist[u] + 1:
                        trail.append((u, v))
                        stack.append((w, iter(adj[w])))
                        advanced = True
                        break
                if not advanced:
                    dist[u] = INF
                    stack.pop()
                    if trail:
                        trail.pop()
            return False

        if not found:
            return total
        for u in left:
            if match_l[u] is None and dfs(u):
                total += 1


# --- leiden ------------------------------------------------------------------


@mgp.read_proc("leiden_community_detection.get",
               opt_args=[("weight_property", "STRING", None)],
               results=[("node", "NODE"), ("community_id", "INTEGER"),
                        ("communities", "LIST")])
def leiden_get(ctx, weight_property=None):
    """Louvain TPU kernel + a host refinement sweep (the Leiden move: each
    node may only stay or move to a strictly modularity-improving neighbor
    community, splitting badly-connected merges)."""
    from ..ops.louvain import louvain
    graph = ctx.device_graph(weight_property=weight_property)
    if graph.n_nodes == 0:
        return
    comm, _ = louvain(graph)
    comm = _refine_communities(graph, np.asarray(comm).copy())
    for i in range(graph.n_nodes):
        node = ctx.vertex_by_index(graph, i)
        if node is not None:
            cid = int(comm[i])
            yield {"node": node, "community_id": cid, "communities": [cid]}


def _refine_communities(graph, comm):
    """One constrained local-move sweep over the host CSR arrays.

    DeviceGraph stores each edge once, directed — symmetrize here (as
    ops/louvain.py does) so per-community link weights see the full
    undirected adjacency, not just out-edges."""
    n, m = graph.n_nodes, graph.n_edges
    e_src = np.asarray(graph.src_idx[:m])
    e_dst = np.asarray(graph.col_idx[:m])
    e_w = np.asarray(graph.weights[:m], dtype=np.float64)
    src = np.concatenate([e_src, e_dst])
    dst = np.concatenate([e_dst, e_src])
    w = np.concatenate([e_w, e_w])
    order_idx = np.argsort(src, kind="stable")
    src, dst, w = src[order_idx], dst[order_idx], w[order_idx]
    deg = np.zeros(n)
    np.add.at(deg, src, w)
    two_m = max(deg.sum(), 1e-12)
    comm_deg = np.zeros(comm.max() + 2)
    np.add.at(comm_deg, comm, deg)
    order = np.argsort(-deg[:n])
    starts = np.searchsorted(src, np.arange(n))
    ends = np.searchsorted(src, np.arange(n) + 1)
    for u in order:
        u = int(u)
        links = collections.defaultdict(float)
        for k in range(int(starts[u]), int(ends[u])):
            links[int(comm[dst[k]])] += float(w[k])
        cur = int(comm[u])
        best, best_gain = cur, 0.0
        for c, l_uc in links.items():
            if c == cur:
                continue
            gain = (l_uc - links.get(cur, 0.0)
                    - deg[u] * (comm_deg[c] - comm_deg[cur] + deg[u]) / two_m)
            if gain > best_gain + 1e-12:
                best, best_gain = c, gain
        if best != cur:
            comm_deg[cur] -= deg[u]
            comm_deg[best] += deg[u]
            comm[u] = best
    return comm


# --- temporal ----------------------------------------------------------------


@mgp.read_proc("temporal.format",
               args=[("temporal", "ANY")],
               opt_args=[("format", "STRING", "ISO")],
               results=[("formatted", "STRING")])
def temporal_format(ctx, temporal, format="ISO"):
    """Non-temporal values fall through to str(); a Duration with a custom
    format is strftime'd via the Unix epoch — both matching the reference
    (mage/python/temporal.py)."""
    import datetime as _dt
    from ..utils.temporal import (Date, Duration, LocalDateTime, LocalTime,
                                  ZonedDateTime)
    if isinstance(temporal, Duration):
        if format == "ISO":
            yield {"formatted": str(temporal)}
        else:
            epoch = _dt.datetime(1970, 1, 1) \
                + _dt.timedelta(microseconds=temporal.micros)
            yield {"formatted": epoch.strftime(format)}
        return
    if not isinstance(temporal, (Date, LocalTime, LocalDateTime,
                                 ZonedDateTime)):
        yield {"formatted": str(temporal)}
        return
    inner = getattr(temporal, "d", None) or getattr(temporal, "t", None) \
        or getattr(temporal, "dt", None)
    if format == "ISO":
        yield {"formatted": inner.isoformat()}
    else:
        yield {"formatted": inner.strftime(format)}
