"""Raft consensus (from scratch; control-plane sized).

Implements the core Raft protocol (Ongaro & Ousterhout): follower/candidate/
leader roles, randomized election timeouts, RequestVote and AppendEntries
RPCs over TCP (JSON payloads on the replication framing), log replication
with per-peer nextIndex/matchIndex, commit on majority, and application of
committed entries to a pluggable state machine.

Reference analog: the NuRaft integration in
/root/reference/src/coordination/raft_state.cpp — same role in the system,
re-implemented because this environment ships no consensus library.
"""

from __future__ import annotations

import json
import logging
import os
import random
import socket
import threading
import time
import zlib
from dataclasses import dataclass, field

from ..observability import trace as mgtrace
from ..replication import protocol as P
from ..utils.locks import tracked_lock, tracked_rlock

log = logging.getLogger(__name__)

MSG_RAFT = 0x20  # JSON raft message on the shared framing


@dataclass
class LogEntry:
    term: int
    command: dict

    def to_json(self):
        return {"term": self.term, "command": self.command}

    @staticmethod
    def from_json(obj):
        return LogEntry(obj["term"], obj["command"])


@dataclass(frozen=True)
class ProposeResult:
    """Typed outcome of ``RaftNode.propose`` — callers must distinguish
    retryable rejections from fatal ones (a bare bool collapsed "not the
    leader, go elsewhere" and "timed out, maybe committed" into the same
    silent False).

    outcome:
      committed        entry committed on a majority and applied
      not_leader       this node cannot propose; retry against the leader
      timeout          commit not observed in time — the entry MAY still
                       commit later (ambiguous; retries must be
                       idempotent)
      lost_leadership  leadership changed under the proposal; the entry
                       was superseded or its fate belongs to the new
                       leader
    """

    outcome: str
    index: int | None = None
    term: int | None = None

    def __bool__(self) -> bool:
        return self.outcome == "committed"

    @property
    def retryable(self) -> bool:
        """Safe to re-propose (for idempotent commands): the entry was
        rejected or its commit is unresolved, not superseded."""
        return self.outcome in ("not_leader", "timeout")

    COMMITTED = "committed"
    NOT_LEADER = "not_leader"
    TIMEOUT = "timeout"
    LOST_LEADERSHIP = "lost_leadership"


class RaftNode:
    """One Raft participant listening on (host, port).

    peers: {node_id: (host, port)} for the OTHER nodes.
    apply_fn(command: dict) is invoked exactly once per committed entry,
    in log order, on every node.
    """

    ELECTION_TIMEOUT = (0.6, 1.2)   # seconds, randomized
    HEARTBEAT_INTERVAL = 0.15
    COMPACTION_THRESHOLD = 256      # applied entries kept before snapshot
    # leader lease: a leader that cannot reach a majority within this
    # window steps down instead of acting on stale authority (a
    # minority-partitioned leader would otherwise keep serving reads and
    # accepting doomed proposals until something ELSE noticed). Shorter
    # than the minimum election timeout so the old leader abdicates
    # before a partition-side majority can crown a successor.
    LEADER_LEASE = 0.6

    def __init__(self, node_id: str, host: str, port: int,
                 peers: dict[str, tuple[str, int]], apply_fn=None,
                 kvstore=None, snapshot_fn=None, restore_fn=None,
                 compaction_threshold: int | None = None,
                 election_seed: int | None = None):
        self.node_id = node_id
        self.host = host
        self.port = port
        self.peers = dict(peers)
        self.apply_fn = apply_fn or (lambda cmd: None)
        # seedable election jitter: fault-injection cluster tests need the
        # timeout schedule to replay exactly (MEMGRAPH_TPU_RAFT_SEED as
        # env fallback; the node_id keeps same-seed nodes from tying)
        if election_seed is None:
            env_seed = os.environ.get("MEMGRAPH_TPU_RAFT_SEED")
            if env_seed is not None:
                # crc32, not hash(): per-node derivation must replay
                # across processes (PYTHONHASHSEED salts str hashing)
                election_seed = int(env_seed) ^ zlib.crc32(
                    node_id.encode("utf-8"))
        self._rng = random.Random(election_seed)
        # log compaction (Raft §7; reference: coordinator_log_store.cpp +
        # raft_state.cpp:370 install-snapshot): snapshot_fn() returns a
        # JSON-able state-machine snapshot, restore_fn(state) replaces the
        # state machine wholesale. Without them the log grows unboundedly.
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.compaction_threshold = (compaction_threshold
                                     or self.COMPACTION_THRESHOLD)

        # persistent state (Raft §5.1: currentTerm, votedFor, log[] must
        # survive restarts — reference: coordinator_log_store.cpp); durable
        # through the kvstore when one is provided
        self._kv = kvstore
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[LogEntry] = []
        # entries [0, log_start) live in the snapshot; log[0] has absolute
        # index log_start
        self.log_start = 0
        self.snapshot_last_term = 0
        self.snapshot_state = None
        if kvstore is not None:
            self._restore_persistent_state()

        # volatile (a restored snapshot means everything up to log_start-1
        # is already committed and applied into the state machine)
        self.commit_index = self.log_start - 1
        self.last_applied = self.log_start - 1
        self.role = "follower"
        self.leader_id: str | None = None
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}

        self._lock = tracked_rlock("RaftNode._lock")
        self._stop = threading.Event()
        self._last_heartbeat = time.monotonic()
        self._election_deadline = self._new_deadline()
        # pre-vote (Raft §9.6 / the thesis' "PreVote" extension): a node
        # that heard from a live leader within the minimum election
        # timeout refuses pre-votes, so a flapping partitioned node
        # cannot inflate terms and depose a healthy leader on heal.
        # 0.0 = "never heard from a leader" so bootstrap elections work.
        self._last_leader_contact = 0.0
        # leader lease bookkeeping: last time each peer answered any RPC
        self._peer_ack_at: dict[str, float] = {}
        self._lease_started = 0.0
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._commit_events: dict[int, threading.Event] = {}
        # persistent per-peer RPC connections: with cluster TLS on, a
        # handshake per heartbeat would eat the 0.5s RPC deadline and
        # destabilize leadership; the server loop handles many frames per
        # connection, so reuse one socket per peer (fresh on error)
        self._peer_conns: dict[str, socket.socket] = {}
        self._peer_conns_lock = tracked_lock("RaftNode._peer_conns_lock")

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self._sock.listen(8)
        for target in (self._accept_loop, self._timer_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._peer_conns_lock:
            conns, self._peer_conns = dict(self._peer_conns), {}
        for sock in conns.values():
            try:
                sock.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2)

    def _new_deadline(self) -> float:
        return time.monotonic() + self._rng.uniform(*self.ELECTION_TIMEOUT)

    # --- durability (Raft persistent state) ---------------------------------

    def _restore_persistent_state(self) -> None:
        term = self._kv.get_str("raft:term")
        if term is not None:
            self.current_term = int(term)
        self.voted_for = self._kv.get_str("raft:voted_for") or None
        snap_raw = self._kv.get_str("raft:snapshot")
        if snap_raw:
            snap = json.loads(snap_raw)
            self.log_start = snap["index"] + 1
            self.snapshot_last_term = snap["term"]
            self.snapshot_state = snap["state"]
            if self.restore_fn is not None and snap["state"] is not None:
                self.restore_fn(snap["state"])
        for key, raw in self._kv.items_with_prefix("raft:log:"):
            idx = int(key.rsplit(":", 1)[1])
            if idx < self.log_start:  # already folded into the snapshot
                self._kv.delete(key)
                continue
            self.log.append(LogEntry.from_json(
                json.loads(raw.decode("utf-8"))))

    def _persist_term_vote(self) -> None:
        # caller holds lock
        if self._kv is not None:
            self._kv.put("raft:term", str(self.current_term))
            self._kv.put("raft:voted_for", self.voted_for or "")

    def _persist_log_from(self, start_abs: int) -> None:
        # caller holds lock; rewrite entries with ABSOLUTE index >= start
        # (truncation-safe keys are zero-padded so prefix iteration
        # returns them in order)
        if self._kv is None:
            return
        for idx in range(max(start_abs, self.log_start), self._abs_len()):
            self._kv.put(f"raft:log:{idx:012d}",
                         json.dumps(self.log[idx - self.log_start]
                                    .to_json()))
        # drop stale tail entries beyond the new log length
        for key, _ in list(self._kv.items_with_prefix("raft:log:")):
            if int(key.rsplit(":", 1)[1]) >= self._abs_len():
                self._kv.delete(key)

    def _persist_snapshot(self) -> None:
        # caller holds lock
        if self._kv is None:
            return
        self._kv.put("raft:snapshot", json.dumps({
            "index": self.log_start - 1,
            "term": self.snapshot_last_term,
            "state": self.snapshot_state}))
        for key, _ in list(self._kv.items_with_prefix("raft:log:")):
            if int(key.rsplit(":", 1)[1]) < self.log_start:
                self._kv.delete(key)

    # --- log index translation (absolute <-> in-memory) ---------------------

    def _abs_len(self) -> int:
        return self.log_start + len(self.log)

    def _entry(self, idx_abs: int) -> LogEntry:
        return self.log[idx_abs - self.log_start]

    def _term_at(self, idx_abs: int) -> int:
        if idx_abs == self.log_start - 1:
            return self.snapshot_last_term
        if idx_abs < self.log_start - 1:
            return -1  # compacted away; only reachable on stale RPCs
        return self.log[idx_abs - self.log_start].term

    def _maybe_compact(self) -> None:
        """Caller holds lock: fold applied entries into a state-machine
        snapshot once enough accumulate (Raft §7)."""
        if self.snapshot_fn is None:
            return
        applied_in_log = self.last_applied - self.log_start + 1
        if applied_in_log < self.compaction_threshold:
            return
        try:
            state = self.snapshot_fn()
        except Exception:
            log.exception("raft snapshot_fn failed; skipping compaction")
            return
        self.snapshot_last_term = self._term_at(self.last_applied)
        del self.log[:applied_in_log]
        self.log_start = self.last_applied + 1
        self.snapshot_state = state
        self._persist_snapshot()
        log.info("raft %s compacted log through %d", self.node_id,
                 self.log_start - 1)

    # --- public API ---------------------------------------------------------

    def is_leader(self) -> bool:
        with self._lock:
            return self.role == "leader"

    def propose(self, command: dict, timeout: float = 5.0) -> ProposeResult:
        """Leader-only: append a command; block until committed (majority).

        Returns a :class:`ProposeResult` (truthy iff committed) so
        callers can tell "retry elsewhere" from "may have committed"
        from "superseded by a new leader"."""
        with self._lock:
            if self.role != "leader":
                return ProposeResult(ProposeResult.NOT_LEADER,
                                     term=self.current_term)
            term = self.current_term
            entry = LogEntry(term, command)
            self.log.append(entry)
            index = self._abs_len() - 1
            self._persist_log_from(index)
            event = threading.Event()
            self._commit_events[index] = event
            # a single-node cluster (or one whose peers are all caught up)
            # can commit immediately — majority may already be satisfied
            self._advance_commit()
        self._broadcast_append()
        ok = event.wait(timeout)
        with self._lock:
            self._commit_events.pop(index, None)
            # commit events are keyed by INDEX: a successor leader's
            # entry at the same index also fires ours, so verify the
            # committed entry carries OUR term. A committed entry that
            # was already compacted away is still ours iff leadership
            # never changed (an overwrite needs a higher-term leader).
            if index >= self.log_start - 1:
                ours = self._term_at(index) == term
            else:
                ours = self.current_term == term and self.role == "leader"
            committed = ok and self.commit_index >= index and ours
            if committed:
                return ProposeResult(ProposeResult.COMMITTED,
                                     index=index, term=term)
            if self.current_term != term or self.role != "leader":
                # a new leader took over mid-proposal; our entry was (or
                # will be) overwritten — re-proposing here could double-
                # apply, the caller must re-evaluate against new state
                return ProposeResult(ProposeResult.LOST_LEADERSHIP,
                                     index=index, term=term)
            return ProposeResult(ProposeResult.TIMEOUT,
                                 index=index, term=term)

    # --- networking ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            # TLS handshake here, NOT in the accept loop: a silent peer
            # must only pin this thread (bounded by the handshake timeout)
            from ..utils.tls import wrap_cluster_server
            conn = wrap_cluster_server(conn)
            while not self._stop.is_set():
                msg_type, payload = P.recv_frame(conn)
                if msg_type != MSG_RAFT:
                    break
                request = json.loads(payload.decode("utf-8"))
                response = self._handle(request)
                P.send_frame(conn, MSG_RAFT,
                             json.dumps(response).encode("utf-8"))
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass
        finally:
            conn.close()

    def _call_peer(self, peer_id: str, request: dict,
                   timeout: float = 0.5) -> dict | None:
        from ..utils import faultinject as FI
        carrier = mgtrace.inject()
        if carrier is not None:
            # the raft wire is JSON: RPCs issued while a trace is active
            # (e.g. a coordinator action inside a traced query) carry it
            request = {**request, "trace": carrier}
        with mgtrace.span("raft.rpc") as sp:
            if sp:
                sp.set(peer=peer_id, kind=str(request.get("kind")))
            return self._call_peer_guarded(peer_id, request, timeout, FI)

    def _call_peer_guarded(self, peer_id: str, request: dict,
                           timeout: float, FI) -> dict | None:
        try:
            if FI.fire("raft.rpc") == "drop":
                return None  # RPC lost on the wire
        except FI.FaultInjected:
            return None      # injected network fault == unreachable peer
        # nemesis link model, request direction: a dropped request never
        # reaches the peer; "duplicate" delivers the (idempotent) RPC
        # twice, exercising dedup/at-least-once handling
        net = FI.net_fire(self.node_id, peer_id)
        if net == "drop":
            return None
        response = self._call_peer_once(peer_id, request, timeout)
        if net == "duplicate" and response is not None:
            dup = self._call_peer_once(peer_id, request, timeout)
            response = dup if dup is not None else response
        if response is not None:
            # ack direction: an asymmetric peer→us partition means the
            # peer DID execute the RPC but we never learn the outcome
            if FI.net_fire(peer_id, self.node_id) == "drop":
                return None
        return response

    def _call_peer_once(self, peer_id: str, request: dict,
                        timeout: float = 0.5) -> dict | None:
        host, port = self.peers[peer_id]
        data = json.dumps(request).encode("utf-8")
        # first attempt reuses the pooled connection (may be stale if the
        # peer restarted); second attempt always dials fresh. Both share
        # ONE deadline so a black-holed peer costs at most `timeout`, not
        # 2x — election rounds poll peers sequentially and a doubled stall
        # per dead peer would eat the election deadline.
        deadline = time.monotonic() + timeout
        for attempt in (0, 1):
            budget = deadline - time.monotonic()
            if budget <= 0.0:
                return None
            with self._peer_conns_lock:
                sock = self._peer_conns.pop(peer_id, None)
            try:
                if sock is None:
                    if attempt == 0:
                        continue
                    from ..utils.tls import wrap_cluster_client
                    raw = socket.create_connection((host, port),
                                                   timeout=budget)
                    sock = wrap_cluster_client(raw, server_hostname=host)
                sock.settimeout(budget)
                P.send_frame(sock, MSG_RAFT, data)
                msg_type, payload = P.recv_frame(sock)
                if msg_type != MSG_RAFT:
                    raise ConnectionError("unexpected frame type")
                response = json.loads(payload.decode("utf-8"))
                with self._peer_conns_lock:
                    displaced = self._peer_conns.get(peer_id)
                    self._peer_conns[peer_id] = sock
                if displaced is not None:  # concurrent caller raced us
                    try:
                        displaced.close()
                    except OSError:
                        pass
                return response
            except (ConnectionError, OSError, json.JSONDecodeError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
        return None

    # --- RPC handlers -------------------------------------------------------

    def _handle(self, req: dict) -> dict:
        carrier = req.pop("trace", None)
        if carrier is not None:
            with mgtrace.adopt(carrier, retain=True):
                with mgtrace.span("raft.handle",
                                  kind=str(req.get("kind")),
                                  node=self.node_id):
                    return self._handle_inner(req)
        return self._handle_inner(req)

    def _handle_inner(self, req: dict) -> dict:
        kind = req.get("kind")
        if kind == "request_vote":
            return self._on_request_vote(req)
        if kind == "pre_vote":
            return self._on_pre_vote(req)
        if kind == "append_entries":
            return self._on_append_entries(req)
        if kind == "install_snapshot":
            return self._on_install_snapshot(req)
        return {"ok": False}

    def _maybe_step_down(self, term: int) -> None:
        # caller holds lock
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self.role = "follower"
            self._persist_term_vote()

    def _on_pre_vote(self, req: dict) -> dict:
        """Pre-vote (Raft §9.6): answer "would I vote for you?" WITHOUT
        touching persistent state. Refused while a live leader is heard
        from, so a node returning from a partition cannot force a real
        election (term inflation) against a healthy cluster."""
        with self._lock:
            my_last_index = self._abs_len() - 1
            my_last_term = self._term_at(my_last_index) \
                if my_last_index >= 0 else 0
            up_to_date = (req["last_log_term"] > my_last_term
                          or (req["last_log_term"] == my_last_term
                              and req["last_log_index"] >= my_last_index))
            leader_is_live = (
                self.role == "leader"
                or (self._last_leader_contact > 0.0
                    and time.monotonic() - self._last_leader_contact
                    < self.ELECTION_TIMEOUT[0]))
            grant = (req["term"] >= self.current_term and up_to_date
                     and not leader_is_live)
            return {"kind": "pre_vote_ack", "term": self.current_term,
                    "granted": grant}

    def _on_request_vote(self, req: dict) -> dict:
        with self._lock:
            self._maybe_step_down(req["term"])
            grant = False
            if req["term"] >= self.current_term and \
                    self.voted_for in (None, req["candidate"]):
                my_last_index = self._abs_len() - 1
                my_last_term = self._term_at(my_last_index) \
                    if my_last_index >= 0 else 0
                up_to_date = (req["last_log_term"] > my_last_term
                              or (req["last_log_term"] == my_last_term
                                  and req["last_log_index"] >= my_last_index))
                if up_to_date:
                    grant = True
                    self.voted_for = req["candidate"]
                    self._persist_term_vote()
                    self._election_deadline = self._new_deadline()
            return {"kind": "vote", "term": self.current_term,
                    "granted": grant}

    def _on_append_entries(self, req: dict) -> dict:
        with self._lock:
            self._maybe_step_down(req["term"])
            if req["term"] < self.current_term:
                return {"kind": "append_ack", "term": self.current_term,
                        "success": False}
            self.role = "follower"
            self.leader_id = req["leader"]
            self._election_deadline = self._new_deadline()
            self._last_leader_contact = time.monotonic()

            prev_index = req["prev_log_index"]
            prev_term = req["prev_log_term"]
            if prev_index < self.log_start - 1:
                # the leader's window precedes our snapshot: everything
                # there is committed state already — ack up to the snapshot
                return {"kind": "append_ack", "term": self.current_term,
                        "success": True,
                        "match_index": self.log_start - 1}
            if prev_index >= 0:
                if prev_index >= self._abs_len() or \
                        self._term_at(prev_index) != prev_term:
                    return {"kind": "append_ack",
                            "term": self.current_term, "success": False}
            # append/overwrite entries
            insert_at = prev_index + 1
            changed_from = None
            for i, obj in enumerate(req.get("entries", [])):
                entry = LogEntry.from_json(obj)
                idx = insert_at + i
                if idx < self.log_start:
                    continue  # already folded into our snapshot
                if idx < self._abs_len():
                    if self._term_at(idx) != entry.term:
                        del self.log[idx - self.log_start:]
                        self.log.append(entry)
                        changed_from = idx if changed_from is None \
                            else min(changed_from, idx)
                else:
                    self.log.append(entry)
                    changed_from = idx if changed_from is None \
                        else min(changed_from, idx)
            if changed_from is not None:
                self._persist_log_from(changed_from)
            # advance commit
            leader_commit = req["leader_commit"]
            if leader_commit > self.commit_index:
                self.commit_index = min(leader_commit, self._abs_len() - 1)
            self._apply_committed()
            return {"kind": "append_ack", "term": self.current_term,
                    "success": True,
                    "match_index": prev_index + len(req.get("entries", []))}

    def _on_install_snapshot(self, req: dict) -> dict:
        """Replace log+state with the leader's snapshot (Raft §7.1;
        reference analog: raft_state.cpp:370)."""
        with self._lock:
            self._maybe_step_down(req["term"])
            if req["term"] < self.current_term:
                return {"kind": "snapshot_ack", "term": self.current_term,
                        "success": False}
            self.role = "follower"
            self.leader_id = req["leader"]
            self._election_deadline = self._new_deadline()
            self._last_leader_contact = time.monotonic()
            idx = req["last_included_index"]
            trm = req["last_included_term"]
            if idx <= self.log_start - 1:
                # stale/duplicate snapshot: we already cover it
                return {"kind": "snapshot_ack", "term": self.current_term,
                        "success": True,
                        "match_index": self.log_start - 1}
            if idx < self._abs_len() and self._term_at(idx) == trm:
                # retain the suffix that extends past the snapshot
                del self.log[:idx + 1 - self.log_start]
            else:
                self.log = []
            self.log_start = idx + 1
            self.snapshot_last_term = trm
            self.snapshot_state = req.get("state")
            if self.restore_fn is not None and \
                    self.snapshot_state is not None:
                try:
                    self.restore_fn(self.snapshot_state)
                except Exception:
                    log.exception("raft restore_fn failed")
            self.commit_index = max(self.commit_index, idx)
            self.last_applied = idx
            self._persist_snapshot()
            self._persist_log_from(self.log_start)
            self._apply_committed()
            return {"kind": "snapshot_ack", "term": self.current_term,
                    "success": True, "match_index": idx}

    def _apply_committed(self) -> None:
        # caller holds lock
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self._entry(self.last_applied)
            if not entry.command.get("_noop"):
                try:
                    self.apply_fn(entry.command)
                except Exception:
                    log.exception("state machine apply failed at %d",
                                  self.last_applied)
            event = self._commit_events.get(self.last_applied)
            if event is not None:
                event.set()
        self._maybe_compact()

    # --- timers / elections -------------------------------------------------

    def _timer_loop(self) -> None:
        while not self._stop.wait(0.05):
            with self._lock:
                role = self.role
                deadline = self._election_deadline
            now = time.monotonic()
            if role == "leader":
                if self._lease_expired(now):
                    with self._lock:
                        if self.role == "leader":
                            log.warning(
                                "raft %s: leader lease expired (no "
                                "majority contact for %.1fs) — stepping "
                                "down", self.node_id, self.LEADER_LEASE)
                            self.role = "follower"
                            self.leader_id = None
                            self._election_deadline = self._new_deadline()
                    continue
                self._broadcast_append()
                time.sleep(self.HEARTBEAT_INTERVAL)
            elif now >= deadline:
                self._run_election()

    def _lease_expired(self, now: float) -> bool:
        """True when this leader has not heard from a majority (self
        included) within LEADER_LEASE — i.e. it may be on the minority
        side of a partition and must stop acting on its authority."""
        if not self.peers:
            return False     # single-node cluster: self IS the majority
        with self._lock:
            acks = sorted((self._peer_ack_at.get(p, self._lease_started)
                           for p in self.peers), reverse=True)
        majority = (len(self.peers) + 1) // 2 + 1
        # self always counts; the (majority-1)-th freshest peer ack must
        # still be inside the lease window
        freshest_needed = acks[majority - 2]
        return now - freshest_needed > self.LEADER_LEASE

    def _run_election(self) -> None:
        if not self._pre_vote():
            return
        with self._lock:
            self.role = "candidate"
            self.current_term += 1
            term = self.current_term
            self.voted_for = self.node_id
            self._persist_term_vote()
            self._election_deadline = self._new_deadline()
            last_index = self._abs_len() - 1
            last_term = self._term_at(last_index) if last_index >= 0 else 0
        votes = 1
        for peer_id in list(self.peers):
            resp = self._call_peer(peer_id, {
                "kind": "request_vote", "term": term,
                "candidate": self.node_id,
                "last_log_index": last_index, "last_log_term": last_term})
            if resp is None:
                continue
            with self._lock:
                if resp["term"] > self.current_term:
                    self._maybe_step_down(resp["term"])
                    return
            if resp.get("granted"):
                votes += 1
        majority = (len(self.peers) + 1) // 2 + 1
        with self._lock:
            if self.role != "candidate" or self.current_term != term:
                return
            if votes >= majority:
                self.role = "leader"
                self.leader_id = self.node_id
                self.next_index = {p: self._abs_len() for p in self.peers}
                self.match_index = {p: -1 for p in self.peers}
                # fresh lease: the election itself just proved majority
                # contact, so the clock starts now
                self._lease_started = time.monotonic()
                self._peer_ack_at = {}
                # Raft §5.4.2: entries from PREVIOUS terms can only be
                # committed alongside a current-term entry — append a
                # no-op immediately so a committed-but-unapplied tail
                # (e.g. the old leader died right after majority ack)
                # becomes visible without waiting for a client write
                self.log.append(LogEntry(term, {"_noop": True}))
                self._persist_log_from(self._abs_len() - 1)
                self._advance_commit()
                log.info("raft %s became leader (term %d)", self.node_id,
                         term)
        if self.is_leader():
            self._broadcast_append()

    def _pre_vote(self) -> bool:
        """Canvass the cluster WITHOUT incrementing the term; only a
        majority of pre-votes (self included) starts a real election."""
        with self._lock:
            if self.role == "leader":
                return False
            term = self.current_term + 1
            last_index = self._abs_len() - 1
            last_term = self._term_at(last_index) if last_index >= 0 else 0
            # re-arm the deadline so a failed canvass retries later
            # instead of spinning the timer loop
            self._election_deadline = self._new_deadline()
        granted = 1
        for peer_id in list(self.peers):
            resp = self._call_peer(peer_id, {
                "kind": "pre_vote", "term": term,
                "candidate": self.node_id,
                "last_log_index": last_index, "last_log_term": last_term})
            if resp is None:
                continue
            with self._lock:
                if resp["term"] > self.current_term:
                    self._maybe_step_down(resp["term"])
                    return False
            if resp.get("granted"):
                granted += 1
        majority = (len(self.peers) + 1) // 2 + 1
        return granted >= majority

    # --- leader replication -------------------------------------------------

    def _broadcast_append(self) -> None:
        for peer_id in list(self.peers):
            threading.Thread(target=self._replicate_to, args=(peer_id,),
                             daemon=True).start()

    def _replicate_to(self, peer_id: str) -> None:
        with self._lock:
            if self.role != "leader":
                return
            term = self.current_term
            next_idx = self.next_index.get(peer_id, self._abs_len())
            if next_idx < self.log_start:
                # peer is behind our compacted window: ship the snapshot
                request = {
                    "kind": "install_snapshot", "term": term,
                    "leader": self.node_id,
                    "last_included_index": self.log_start - 1,
                    "last_included_term": self.snapshot_last_term,
                    "state": self.snapshot_state}
            else:
                prev_index = next_idx - 1
                prev_term = self._term_at(prev_index) \
                    if prev_index >= 0 else 0
                entries = [e.to_json()
                           for e in self.log[next_idx - self.log_start:]]
                request = {
                    "kind": "append_entries", "term": term,
                    "leader": self.node_id,
                    "prev_log_index": prev_index,
                    "prev_log_term": prev_term,
                    "entries": entries,
                    "leader_commit": self.commit_index}
        resp = self._call_peer(peer_id, request)
        if resp is None:
            return
        with self._lock:
            # any response proves the link is alive — feed the lease
            self._peer_ack_at[peer_id] = time.monotonic()
            if resp["term"] > self.current_term:
                self._maybe_step_down(resp["term"])
                return
            if self.role != "leader" or self.current_term != term:
                return
            if resp.get("success"):
                match = resp.get("match_index", next_idx - 1)
                self.match_index[peer_id] = max(
                    self.match_index.get(peer_id, -1), match)
                self.next_index[peer_id] = self.match_index[peer_id] + 1
                self._advance_commit()
            else:
                self.next_index[peer_id] = max(0, next_idx - 1)

    def _advance_commit(self) -> None:
        # caller holds lock; commit entries from the CURRENT term replicated
        # on a majority (Raft §5.4.2 safety rule)
        for idx in range(self._abs_len() - 1, self.commit_index, -1):
            if self._term_at(idx) != self.current_term:
                continue
            replicated = 1 + sum(
                1 for p in self.peers if self.match_index.get(p, -1) >= idx)
            if replicated >= (len(self.peers) + 1) // 2 + 1:
                self.commit_index = idx
                self._apply_committed()
                break
