"""Raft consensus (from scratch; control-plane sized).

Implements the core Raft protocol (Ongaro & Ousterhout): follower/candidate/
leader roles, randomized election timeouts, RequestVote and AppendEntries
RPCs over TCP (JSON payloads on the replication framing), log replication
with per-peer nextIndex/matchIndex, commit on majority, and application of
committed entries to a pluggable state machine.

Reference analog: the NuRaft integration in
/root/reference/src/coordination/raft_state.cpp — same role in the system,
re-implemented because this environment ships no consensus library.
"""

from __future__ import annotations

import json
import logging
import random
import socket
import threading
import time
from dataclasses import dataclass, field

from ..replication import protocol as P

log = logging.getLogger(__name__)

MSG_RAFT = 0x20  # JSON raft message on the shared framing


@dataclass
class LogEntry:
    term: int
    command: dict

    def to_json(self):
        return {"term": self.term, "command": self.command}

    @staticmethod
    def from_json(obj):
        return LogEntry(obj["term"], obj["command"])


class RaftNode:
    """One Raft participant listening on (host, port).

    peers: {node_id: (host, port)} for the OTHER nodes.
    apply_fn(command: dict) is invoked exactly once per committed entry,
    in log order, on every node.
    """

    ELECTION_TIMEOUT = (0.6, 1.2)   # seconds, randomized
    HEARTBEAT_INTERVAL = 0.15

    def __init__(self, node_id: str, host: str, port: int,
                 peers: dict[str, tuple[str, int]], apply_fn=None,
                 kvstore=None):
        self.node_id = node_id
        self.host = host
        self.port = port
        self.peers = dict(peers)
        self.apply_fn = apply_fn or (lambda cmd: None)

        # persistent state (Raft §5.1: currentTerm, votedFor, log[] must
        # survive restarts — reference: coordinator_log_store.cpp); durable
        # through the kvstore when one is provided
        self._kv = kvstore
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[LogEntry] = []
        if kvstore is not None:
            self._restore_persistent_state()

        # volatile
        self.commit_index = -1
        self.last_applied = -1
        self.role = "follower"
        self.leader_id: str | None = None
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}

        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._last_heartbeat = time.monotonic()
        self._election_deadline = self._new_deadline()
        self._sock: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._commit_events: dict[int, threading.Event] = {}
        # persistent per-peer RPC connections: with cluster TLS on, a
        # handshake per heartbeat would eat the 0.5s RPC deadline and
        # destabilize leadership; the server loop handles many frames per
        # connection, so reuse one socket per peer (fresh on error)
        self._peer_conns: dict[str, socket.socket] = {}
        self._peer_conns_lock = threading.Lock()

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self._sock.listen(8)
        for target in (self._accept_loop, self._timer_loop):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._peer_conns_lock:
            conns, self._peer_conns = dict(self._peer_conns), {}
        for sock in conns.values():
            try:
                sock.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2)

    def _new_deadline(self) -> float:
        return time.monotonic() + random.uniform(*self.ELECTION_TIMEOUT)

    # --- durability (Raft persistent state) ---------------------------------

    def _restore_persistent_state(self) -> None:
        term = self._kv.get_str("raft:term")
        if term is not None:
            self.current_term = int(term)
        self.voted_for = self._kv.get_str("raft:voted_for") or None
        for key, raw in self._kv.items_with_prefix("raft:log:"):
            self.log.append(LogEntry.from_json(
                json.loads(raw.decode("utf-8"))))

    def _persist_term_vote(self) -> None:
        # caller holds lock
        if self._kv is not None:
            self._kv.put("raft:term", str(self.current_term))
            self._kv.put("raft:voted_for", self.voted_for or "")

    def _persist_log_from(self, start: int) -> None:
        # caller holds lock; rewrite entries >= start (truncation-safe keys
        # are zero-padded so prefix iteration returns them in order)
        if self._kv is None:
            return
        for idx in range(start, len(self.log)):
            self._kv.put(f"raft:log:{idx:012d}",
                         json.dumps(self.log[idx].to_json()))
        # drop stale tail entries beyond the new log length
        for key, _ in list(self._kv.items_with_prefix("raft:log:")):
            if int(key.rsplit(":", 1)[1]) >= len(self.log):
                self._kv.delete(key)

    # --- public API ---------------------------------------------------------

    def is_leader(self) -> bool:
        with self._lock:
            return self.role == "leader"

    def propose(self, command: dict, timeout: float = 5.0) -> bool:
        """Leader-only: append a command; block until committed (majority)."""
        with self._lock:
            if self.role != "leader":
                return False
            entry = LogEntry(self.current_term, command)
            self.log.append(entry)
            index = len(self.log) - 1
            self._persist_log_from(index)
            event = threading.Event()
            self._commit_events[index] = event
            # a single-node cluster (or one whose peers are all caught up)
            # can commit immediately — majority may already be satisfied
            self._advance_commit()
        self._broadcast_append()
        ok = event.wait(timeout)
        with self._lock:
            self._commit_events.pop(index, None)
        return ok and self.commit_index >= index

    # --- networking ---------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            # TLS handshake here, NOT in the accept loop: a silent peer
            # must only pin this thread (bounded by the handshake timeout)
            from ..utils.tls import wrap_cluster_server
            conn = wrap_cluster_server(conn)
            while not self._stop.is_set():
                msg_type, payload = P.recv_frame(conn)
                if msg_type != MSG_RAFT:
                    break
                request = json.loads(payload.decode("utf-8"))
                response = self._handle(request)
                P.send_frame(conn, MSG_RAFT,
                             json.dumps(response).encode("utf-8"))
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass
        finally:
            conn.close()

    def _call_peer(self, peer_id: str, request: dict,
                   timeout: float = 0.5) -> dict | None:
        host, port = self.peers[peer_id]
        data = json.dumps(request).encode("utf-8")
        # first attempt reuses the pooled connection (may be stale if the
        # peer restarted); second attempt always dials fresh. Both share
        # ONE deadline so a black-holed peer costs at most `timeout`, not
        # 2x — election rounds poll peers sequentially and a doubled stall
        # per dead peer would eat the election deadline.
        deadline = time.monotonic() + timeout
        for attempt in (0, 1):
            budget = deadline - time.monotonic()
            if budget <= 0.0:
                return None
            with self._peer_conns_lock:
                sock = self._peer_conns.pop(peer_id, None)
            try:
                if sock is None:
                    if attempt == 0:
                        continue
                    from ..utils.tls import wrap_cluster_client
                    raw = socket.create_connection((host, port),
                                                   timeout=budget)
                    sock = wrap_cluster_client(raw, server_hostname=host)
                sock.settimeout(budget)
                P.send_frame(sock, MSG_RAFT, data)
                msg_type, payload = P.recv_frame(sock)
                if msg_type != MSG_RAFT:
                    raise ConnectionError("unexpected frame type")
                response = json.loads(payload.decode("utf-8"))
                with self._peer_conns_lock:
                    displaced = self._peer_conns.get(peer_id)
                    self._peer_conns[peer_id] = sock
                if displaced is not None:  # concurrent caller raced us
                    try:
                        displaced.close()
                    except OSError:
                        pass
                return response
            except (ConnectionError, OSError, json.JSONDecodeError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
        return None

    # --- RPC handlers -------------------------------------------------------

    def _handle(self, req: dict) -> dict:
        kind = req.get("kind")
        if kind == "request_vote":
            return self._on_request_vote(req)
        if kind == "append_entries":
            return self._on_append_entries(req)
        return {"ok": False}

    def _maybe_step_down(self, term: int) -> None:
        # caller holds lock
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self.role = "follower"
            self._persist_term_vote()

    def _on_request_vote(self, req: dict) -> dict:
        with self._lock:
            self._maybe_step_down(req["term"])
            grant = False
            if req["term"] >= self.current_term and \
                    self.voted_for in (None, req["candidate"]):
                my_last_term = self.log[-1].term if self.log else 0
                my_last_index = len(self.log) - 1
                up_to_date = (req["last_log_term"] > my_last_term
                              or (req["last_log_term"] == my_last_term
                                  and req["last_log_index"] >= my_last_index))
                if up_to_date:
                    grant = True
                    self.voted_for = req["candidate"]
                    self._persist_term_vote()
                    self._election_deadline = self._new_deadline()
            return {"kind": "vote", "term": self.current_term,
                    "granted": grant}

    def _on_append_entries(self, req: dict) -> dict:
        with self._lock:
            self._maybe_step_down(req["term"])
            if req["term"] < self.current_term:
                return {"kind": "append_ack", "term": self.current_term,
                        "success": False}
            self.role = "follower"
            self.leader_id = req["leader"]
            self._election_deadline = self._new_deadline()

            prev_index = req["prev_log_index"]
            prev_term = req["prev_log_term"]
            if prev_index >= 0:
                if prev_index >= len(self.log) or \
                        self.log[prev_index].term != prev_term:
                    return {"kind": "append_ack",
                            "term": self.current_term, "success": False}
            # append/overwrite entries
            insert_at = prev_index + 1
            changed_from = None
            for i, obj in enumerate(req.get("entries", [])):
                entry = LogEntry.from_json(obj)
                idx = insert_at + i
                if idx < len(self.log):
                    if self.log[idx].term != entry.term:
                        del self.log[idx:]
                        self.log.append(entry)
                        changed_from = idx if changed_from is None \
                            else min(changed_from, idx)
                else:
                    self.log.append(entry)
                    changed_from = idx if changed_from is None \
                        else min(changed_from, idx)
            if changed_from is not None:
                self._persist_log_from(changed_from)
            # advance commit
            leader_commit = req["leader_commit"]
            if leader_commit > self.commit_index:
                self.commit_index = min(leader_commit, len(self.log) - 1)
            self._apply_committed()
            return {"kind": "append_ack", "term": self.current_term,
                    "success": True,
                    "match_index": prev_index + len(req.get("entries", []))}

    def _apply_committed(self) -> None:
        # caller holds lock
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self.log[self.last_applied]
            try:
                self.apply_fn(entry.command)
            except Exception:
                log.exception("state machine apply failed at %d",
                              self.last_applied)
            event = self._commit_events.get(self.last_applied)
            if event is not None:
                event.set()

    # --- timers / elections -------------------------------------------------

    def _timer_loop(self) -> None:
        while not self._stop.wait(0.05):
            with self._lock:
                role = self.role
                deadline = self._election_deadline
            now = time.monotonic()
            if role == "leader":
                self._broadcast_append()
                time.sleep(self.HEARTBEAT_INTERVAL)
            elif now >= deadline:
                self._run_election()

    def _run_election(self) -> None:
        with self._lock:
            self.role = "candidate"
            self.current_term += 1
            term = self.current_term
            self.voted_for = self.node_id
            self._persist_term_vote()
            self._election_deadline = self._new_deadline()
            last_index = len(self.log) - 1
            last_term = self.log[-1].term if self.log else 0
        votes = 1
        for peer_id in list(self.peers):
            resp = self._call_peer(peer_id, {
                "kind": "request_vote", "term": term,
                "candidate": self.node_id,
                "last_log_index": last_index, "last_log_term": last_term})
            if resp is None:
                continue
            with self._lock:
                if resp["term"] > self.current_term:
                    self._maybe_step_down(resp["term"])
                    return
            if resp.get("granted"):
                votes += 1
        majority = (len(self.peers) + 1) // 2 + 1
        with self._lock:
            if self.role != "candidate" or self.current_term != term:
                return
            if votes >= majority:
                self.role = "leader"
                self.leader_id = self.node_id
                self.next_index = {p: len(self.log) for p in self.peers}
                self.match_index = {p: -1 for p in self.peers}
                log.info("raft %s became leader (term %d)", self.node_id,
                         term)
        if self.is_leader():
            self._broadcast_append()

    # --- leader replication -------------------------------------------------

    def _broadcast_append(self) -> None:
        for peer_id in list(self.peers):
            threading.Thread(target=self._replicate_to, args=(peer_id,),
                             daemon=True).start()

    def _replicate_to(self, peer_id: str) -> None:
        with self._lock:
            if self.role != "leader":
                return
            term = self.current_term
            next_idx = self.next_index.get(peer_id, len(self.log))
            prev_index = next_idx - 1
            prev_term = self.log[prev_index].term if prev_index >= 0 else 0
            entries = [e.to_json() for e in self.log[next_idx:]]
            commit = self.commit_index
        resp = self._call_peer(peer_id, {
            "kind": "append_entries", "term": term, "leader": self.node_id,
            "prev_log_index": prev_index, "prev_log_term": prev_term,
            "entries": entries, "leader_commit": commit})
        if resp is None:
            return
        with self._lock:
            if resp["term"] > self.current_term:
                self._maybe_step_down(resp["term"])
                return
            if self.role != "leader" or self.current_term != term:
                return
            if resp.get("success"):
                match = resp.get("match_index", prev_index)
                self.match_index[peer_id] = max(
                    self.match_index.get(peer_id, -1), match)
                self.next_index[peer_id] = self.match_index[peer_id] + 1
                self._advance_commit()
            else:
                self.next_index[peer_id] = max(0, next_idx - 1)

    def _advance_commit(self) -> None:
        # caller holds lock; commit entries from the CURRENT term replicated
        # on a majority (Raft §5.4.2 safety rule)
        for idx in range(len(self.log) - 1, self.commit_index, -1):
            if self.log[idx].term != self.current_term:
                continue
            replicated = 1 + sum(
                1 for p in self.peers if self.match_index.get(p, -1) >= idx)
            if replicated >= (len(self.peers) + 1) // 2 + 1:
                self.commit_index = idx
                self._apply_committed()
                break
