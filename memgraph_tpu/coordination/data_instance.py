"""Management server running on each data instance.

Counterpart of the reference's DataInstanceManagementServer
(/root/reference/src/coordination/data_instance_management_server.cpp,
registered at memgraph.cpp:964-970): answers coordinator health checks
(STATE_CHECK) and executes promote/demote RPCs during failover.
"""

from __future__ import annotations

import json
import logging
import socket
import threading

from ..replication import protocol as P

log = logging.getLogger(__name__)

MSG_MGMT = 0x30


class DataInstanceManagementServer:
    def __init__(self, interpreter_context, host="127.0.0.1", port=12000,
                 node_name: str | None = None):
        self.ictx = interpreter_context
        self.host = host
        self.port = port
        # logical node name for the nemesis network model; threaded into
        # the lazily created ReplicationState
        self.node_name = node_name
        self._sock: socket.socket | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self._sock.listen(4)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _replication(self):
        from ..replication.main_role import ReplicationState
        if getattr(self.ictx, "replication", None) is None:
            self.ictx.replication = ReplicationState(
                self.ictx.storage, ictx=self.ictx,
                node_name=self.node_name)
        return self.ictx.replication

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn) -> None:
        try:
            from ..utils.tls import wrap_cluster_server
            conn = wrap_cluster_server(conn)
            while not self._stop.is_set():
                msg_type, payload = P.recv_frame(conn)
                if msg_type != MSG_MGMT:
                    break
                req = json.loads(payload.decode("utf-8"))
                resp = self._handle(req)
                P.send_frame(conn, MSG_MGMT,
                             json.dumps(resp).encode("utf-8"))
        except (ConnectionError, OSError, json.JSONDecodeError):
            pass
        finally:
            conn.close()

    def _handle(self, req: dict) -> dict:
        kind = req.get("kind")
        replication = self._replication()
        if kind == "state_check":
            # role/epoch/replicas let the coordinator RECONCILE divergent
            # topology (a healed old main, a restarted node) instead of
            # only counting health misses
            epoch, fenced = replication.fencing_info()
            return {"ok": True, "role": replication.role,
                    "fencing_epoch": epoch,
                    "fenced": fenced,
                    "replicas": replication.replica_names(),
                    "last_commit_ts": self.ictx.storage.latest_commit_ts()}
        if kind == "promote":
            # become MAIN (fencing epoch minted through Raft) and adopt
            # the given replicas
            from ..exceptions import FencedException
            from ..replication.main_role import ReplicationMode
            try:
                replication.set_role_main(epoch=req.get("epoch"))
            except FencedException as e:
                return {"ok": False, "fenced": True, "errors": [str(e)]}
            if req.get("no_strict_degradation"):
                replication.allow_strict_degradation = False
            errors = []
            for rep in req.get("replicas", []):
                try:
                    replication.register_replica(
                        rep["name"], rep["address"],
                        ReplicationMode[rep.get("mode", "SYNC")])
                except Exception as e:
                    errors.append(f"{rep['name']}: {e}")
            return {"ok": not errors, "errors": errors,
                    "fencing_epoch": replication.current_epoch()}
        if kind == "demote":
            port = req.get("replication_port", 10000)
            try:
                replication.set_role_replica("0.0.0.0", port,
                                             epoch=req.get("epoch"))
            except Exception as e:
                return {"ok": False, "errors": [str(e)]}
            return {"ok": True,
                    "fencing_epoch": replication.current_epoch()}
        if kind == "metrics":
            # scrape federation (r14, mgstat): the coordinator pulls
            # every instance's exposition through the mgmt channel and
            # serves one labeled payload. When a resident kernel daemon
            # is reachable its health counters ride along as a separate
            # exposition, so the accelerator plane appears as its own
            # federated instance.
            from ..observability.metrics import global_metrics
            resp = {"ok": True, "role": replication.role,
                    "text": global_metrics.prometheus_text()}
            daemon = self._kernel_daemon_exposition()
            if daemon:
                resp["daemon_text"] = daemon
            return resp
        return {"ok": False, "errors": [f"unknown request {kind}"]}

    def _kernel_daemon_exposition(self) -> str | None:
        """The local kernel daemon's counters as an exposition, or None
        when no daemon socket is configured/answering."""
        sock = (getattr(self.ictx, "config", {}) or {}).get(
            "kernel_server_socket")
        if not sock:
            return None
        from ..observability import stats as mgstats
        from ..server.kernel_server import SupervisedKernelClient
        client = SupervisedKernelClient(sock, spawn=False)
        try:
            health = client.health(timeout=1.0)
        finally:
            client.close()
        if health is None:
            return None
        return mgstats.counters_exposition(
            health.get("counters"),
            {"kernel_server.daemon.in_flight":
                 float(health.get("in_flight", 0)),
             "kernel_server.daemon.wedged":
                 1.0 if health.get("wedged") else 0.0,
             "kernel_server.daemon.uptime_s":
                 float(health.get("uptime_s", 0.0))})


def mgmt_call(address: str, request: dict, timeout: float = 2.0,
              src: str | None = None, dst: str | None = None
              ) -> dict | None:
    """One management RPC. ``src``/``dst`` are logical node names for
    the nemesis network model (the coordinator passes its raft id and
    the instance name, so chaos tests can partition exactly the
    coordinator↔instance link)."""
    from ..utils import faultinject as FI
    try:
        if FI.fire("mgmt.rpc") == "drop":
            return None  # RPC lost on the wire
    except FI.FaultInjected:
        return None      # injected fault == unreachable instance
    if FI.net_fire(src, dst) == "drop":
        return None      # request direction partitioned
    host, _, port = address.rpartition(":")
    try:
        from ..utils.tls import wrap_cluster_client
        with socket.create_connection((host, int(port)),
                                      timeout=timeout) as raw:
            with wrap_cluster_client(raw, server_hostname=host) as sock:
                P.send_frame(sock, MSG_MGMT,
                             json.dumps(request).encode("utf-8"))
                msg_type, payload = P.recv_frame(sock)
                if msg_type != MSG_MGMT:
                    return None
                response = json.loads(payload.decode("utf-8"))
    except (ConnectionError, OSError, ValueError,
            json.JSONDecodeError):
        return None
    if FI.net_fire(dst, src) == "drop":
        return None      # asymmetric link: executed, but the ack is lost
    return response
