"""Coordinator instance: Raft-replicated cluster state + failover.

Counterpart of the reference's CoordinatorInstance
(/root/reference/src/coordination/coordinator_instance.cpp): the Raft
leader health-checks every data instance (StateCheck RPC analog, :478-502);
after `FAILOVER_MISS_THRESHOLD` consecutive misses of the MAIN it runs
TryFailover (:542-585): pick the most up-to-date alive replica, commit the
new topology through Raft, then promote/demote the data instances.

Fencing: every committed ``set_main`` mints a monotonically increasing
**fencing epoch** inside the replicated state machine (all coordinators
agree on it by construction). Promote/demote RPCs carry the epoch, data
instances attach it to their state, and replicas refuse registration from
a lower epoch — so a deposed MAIN that was partitioned away from the
coordinator can never feed replicas (or keep collecting strict votes)
after its successor exists. The health loop additionally RECONCILES
divergent topology: a healed stale main is demoted, a restarted current
main gets its replica set re-registered — both idempotent and safe to
re-run, which is what makes failover itself retryable.
"""

from __future__ import annotations

import logging
import threading
import time

from ..observability.metrics import global_metrics
from ..utils.locks import tracked_lock
from ..utils.retry import RetryPolicy
from .data_instance import mgmt_call
from .raft import RaftNode

log = logging.getLogger(__name__)


class CoordinatorInstance:
    HEALTH_CHECK_INTERVAL = 0.5
    FAILOVER_MISS_THRESHOLD = 3

    def __init__(self, node_id: str, host: str, raft_port: int,
                 peers: dict[str, tuple[str, int]], kvstore=None,
                 routers: list[str] | None = None,
                 repl_mode: str = "SYNC",
                 election_seed: int | None = None):
        # bolt addresses of ALL coordinators (config-derived), served in
        # the ROUTE role so drivers survive losing their bootstrap router
        self.routers = list(routers or [])
        # replication mode used when (re)wiring data instances. The
        # split-brain-proof profile is STRICT_SYNC: commits wait for
        # every replica's 2PC vote and degradation is disabled, so an
        # isolated MAIN can never ack a write its successor won't have.
        self.repl_mode = repl_mode
        # replicated cluster state: name -> instance descriptor
        # (initialized BEFORE RaftNode: restoring a persisted snapshot
        # calls _restore during RaftNode.__init__)
        self.instances: dict[str, dict] = {}
        self.main_name: str | None = None
        self.epoch = 0        # fencing epoch; bumped by every set_main
        # shard placement (r18, mgshard): shard_id -> owner endpoint.
        # Reassignment mints the SAME fencing epoch inside the
        # replicated apply, so a stale shard map can never route an
        # acked write — one epoch chain fences MAIN role AND shard
        # ownership.
        self.shard_map: dict[int, str] = {}
        self._lock = tracked_lock("Coordinator._lock")
        self.raft = RaftNode(node_id, host, raft_port, peers,
                             apply_fn=self._apply, kvstore=kvstore,
                             snapshot_fn=self._snapshot,
                             restore_fn=self._restore,
                             election_seed=election_seed)
        # failover raft-commit retries: transient outcomes (timeout /
        # lost quorum mid-commit) back off and re-propose; set_main is
        # idempotent so an ambiguous timeout is safe to retry
        self.failover_retry = RetryPolicy(base_delay=0.1, max_delay=1.0,
                                          max_retries=3)
        self._miss_counts: dict[str, int] = {}
        self._stop = threading.Event()
        self._health_thread: threading.Thread | None = None

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.raft.start()
        self._health_thread = threading.Thread(target=self._health_loop,
                                               daemon=True)
        self._health_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.raft.stop()

    # --- replicated state machine -------------------------------------------

    def _apply(self, command: dict) -> None:
        """Applied on EVERY coordinator for each committed Raft entry."""
        op = command.get("op")
        with self._lock:
            if op == "register_instance":
                self.instances[command["name"]] = {
                    "name": command["name"],
                    "mgmt_address": command["mgmt_address"],
                    "replication_address": command["replication_address"],
                    "bolt_address": command.get("bolt_address"),
                    "role": "replica",
                }
            elif op == "unregister_instance":
                self.instances.pop(command["name"], None)
                if self.main_name == command["name"]:
                    self.main_name = None
            elif op == "set_main":
                name = command["name"]
                # mint the fencing epoch HERE, inside the replicated
                # apply: every coordinator derives the identical,
                # strictly monotonic value from the log order alone
                self.epoch += 1
                for inst in self.instances.values():
                    inst["role"] = "replica"
                if name in self.instances:
                    self.instances[name]["role"] = "main"
                    self.main_name = name
                global_metrics.set_gauge("coordination.current_epoch",
                                         float(self.epoch))
            elif op == "set_shard_owner":
                # minted HERE, inside the replicated apply: every
                # coordinator derives the identical (epoch, owner) pair
                # from log order alone — the shard-ownership fencing
                # proof rides the same chain as set_main
                self.epoch += 1
                self.shard_map[int(command["shard"])] = command["owner"]
                global_metrics.set_gauge("coordination.current_epoch",
                                         float(self.epoch))

    def _snapshot(self) -> dict:
        """State-machine snapshot for Raft log compaction."""
        with self._lock:
            return {"instances": {k: dict(v)
                                  for k, v in self.instances.items()},
                    "main_name": self.main_name,
                    "epoch": self.epoch,
                    "shard_map": {str(k): v
                                  for k, v in self.shard_map.items()}}

    def _restore(self, state: dict) -> None:
        """Replace the state machine from a Raft snapshot (restart replay
        or leader install-snapshot for a lagging coordinator)."""
        with self._lock:
            self.instances = {k: dict(v)
                              for k, v in state.get("instances",
                                                    {}).items()}
            self.main_name = state.get("main_name")
            self.epoch = int(state.get("epoch") or 0)
            self.shard_map = {int(k): v
                              for k, v in (state.get("shard_map")
                                           or {}).items()}

    # --- client operations (leader only) ------------------------------------

    def register_instance(self, name: str, mgmt_address: str,
                          replication_address: str,
                          bolt_address: str | None = None) -> bool:
        return bool(self.raft.propose({
            "op": "register_instance", "name": name,
            "mgmt_address": mgmt_address,
            "replication_address": replication_address,
            "bolt_address": bolt_address}))

    def route_table(self) -> dict:
        """Bolt ROUTE table from LIVE replicated cluster state (reference:
        coordinator_instance.cpp routing): MAIN serves writes, replicas
        serve reads; this coordinator serves further ROUTE requests. The
        fencing epoch rides along so clients can reject acks from a main
        this table already superseded."""
        with self._lock:
            writers = [i["bolt_address"] for i in self.instances.values()
                       if i["role"] == "main" and i.get("bolt_address")]
            readers = [i["bolt_address"] for i in self.instances.values()
                       if i["role"] == "replica" and i.get("bolt_address")]
            epoch = self.epoch
            shards = {str(k): v for k, v in self.shard_map.items()}
        table = {"writers": writers, "readers": readers or writers,
                 "epoch": epoch}
        if shards:
            # shard topology rides the same ROUTE payload (and the same
            # epoch) so shard-aware clients refresh both in one fetch
            table["shards"] = shards
        return table

    def assign_shard(self, shard_id: int, owner: str) -> bool:
        """Commit a shard-ownership change through Raft; the fencing
        epoch for the new owner is minted inside the apply."""
        return bool(self.raft.propose({"op": "set_shard_owner",
                                       "shard": int(shard_id),
                                       "owner": owner}))

    def shard_map_view(self) -> dict:
        """The epoch-versioned shard map from replicated state."""
        with self._lock:
            return {"epoch": self.epoch,
                    "owners": dict(self.shard_map)}

    def unregister_instance(self, name: str) -> bool:
        return bool(self.raft.propose({"op": "unregister_instance",
                                       "name": name}))

    def set_instance_to_main(self, name: str) -> bool:
        """Explicit promotion: commit through Raft, then reconfigure."""
        with self._lock:
            if name not in self.instances:
                return False
        if not self.raft.propose({"op": "set_main", "name": name}):
            return False
        self._reconfigure_data_instances(name)
        return True

    def federated_prometheus_text(self) -> str:
        """One labeled exposition for the whole cluster (r14, mgstat).

        Scrapes every registered data instance's metrics through the
        mgmt channel (main + replicas), plus this coordinator's own
        registry; instances exposing a resident kernel daemon contribute
        it as a separate ``<name>-kernel-daemon`` series. Unreachable
        instances are simply absent — the scrape must degrade, not
        fail, under partitions."""
        from ..observability import stats as mgstats
        global_metrics.increment("coordination.federation_scrapes_total")
        parts: dict[str, str] = {
            self.raft.node_id: global_metrics.prometheus_text()}
        with self._lock:
            instances = [dict(i) for i in self.instances.values()]
        for inst in instances:
            resp = mgmt_call(inst["mgmt_address"], {"kind": "metrics"},
                             timeout=2.0, src=self.raft.node_id,
                             dst=inst["name"])
            if resp is None or not resp.get("ok"):
                continue
            parts[inst["name"]] = resp.get("text", "")
            daemon = resp.get("daemon_text")
            if daemon:
                parts[f"{inst['name']}-kernel-daemon"] = daemon
        return mgstats.federate_expositions(parts)

    def show_instances(self) -> list[list]:
        with self._lock:
            instances = [dict(i) for i in self.instances.values()]
        rows = []
        is_leader = self.raft.is_leader()
        for inst in sorted(instances, key=lambda i: i["name"]):
            health = "unknown"
            if is_leader:
                misses = self._miss_counts.get(inst["name"], 0)
                health = "up" if misses == 0 else (
                    "down" if misses >= self.FAILOVER_MISS_THRESHOLD
                    else "degraded")
            rows.append([inst["name"], inst["mgmt_address"],
                         inst["role"], health])
        rows.append([self.raft.node_id, f"raft:{self.raft.port}",
                     "leader" if is_leader else "coordinator", "up"])
        return rows

    # --- health checks + failover (leader) ----------------------------------

    def _health_loop(self) -> None:
        while not self._stop.wait(self.HEALTH_CHECK_INTERVAL):
            if not self.raft.is_leader():
                continue
            with self._lock:
                instances = [dict(i) for i in self.instances.values()]
                main_name = self.main_name
                epoch = self.epoch
            states: dict[str, dict | None] = {}
            for inst in instances:
                resp = mgmt_call(inst["mgmt_address"],
                                 {"kind": "state_check"}, timeout=1.0,
                                 src=self.raft.node_id, dst=inst["name"])
                name = inst["name"]
                states[name] = resp
                if resp is None or not resp.get("ok"):
                    self._miss_counts[name] = \
                        self._miss_counts.get(name, 0) + 1
                else:
                    self._miss_counts[name] = 0
            if main_name is not None and \
                    self._miss_counts.get(main_name, 0) >= \
                    self.FAILOVER_MISS_THRESHOLD:
                self._try_failover(main_name)
                continue
            self._reconcile(instances, main_name, epoch, states)

    def _reconcile(self, instances: list[dict], main_name: str | None,
                   epoch: int, states: dict) -> None:
        """Idempotent topology repair, run every healthy round: a healed
        deposed MAIN is demoted (with the current fencing epoch), and a
        current MAIN whose replica registry diverged from the replicated
        state (restart, promote that half-failed, replica that just
        returned) gets exactly the missing replicas re-registered. Safe
        to re-run — which is what makes failover interruption-tolerant."""
        if main_name is None:
            return
        main_state = states.get(main_name)
        for inst in instances:
            name = inst["name"]
            resp = states.get(name)
            if name == main_name or resp is None or not resp.get("ok"):
                continue
            if resp.get("role") == "main":
                # a deposed main returned from its partition: fence it
                port = int(inst["replication_address"].rpartition(":")[2])
                log.warning("reconcile: demoting stale main %s "
                            "(fencing epoch %d)", name, epoch)
                mgmt_call(inst["mgmt_address"],
                          {"kind": "demote", "replication_port": port,
                           "epoch": epoch},
                          timeout=2.0, src=self.raft.node_id, dst=name)
        if main_state is None or not main_state.get("ok"):
            return
        expected = sorted(i["name"] for i in instances
                          if i["name"] != main_name)
        reported = sorted(main_state.get("replicas", []))
        if main_state.get("role") == "main" and reported == expected \
                and not main_state.get("fenced"):
            return
        missing = [n for n in expected if n not in reported]
        # only re-register replicas that are alive AND already demoted;
        # the stale-main branch above demotes first, next round registers
        ready = []
        for inst in instances:
            name = inst["name"]
            resp = states.get(name)
            if name == main_name or name not in missing:
                continue
            if resp is None or not resp.get("ok") or \
                    resp.get("role") != "replica":
                continue
            ready.append({"name": name,
                          "address": inst["replication_address"],
                          "mode": self.repl_mode})
        if not ready and main_state.get("role") == "main" and \
                not main_state.get("fenced"):
            return
        log.warning("reconcile: refreshing main %s (role=%s, missing "
                    "replicas %s, epoch %d)", main_name,
                    main_state.get("role"), missing, epoch)
        main_inst = next(i for i in instances if i["name"] == main_name)
        mgmt_call(main_inst["mgmt_address"],
                  {"kind": "promote", "replicas": ready, "epoch": epoch,
                   "no_strict_degradation":
                       self.repl_mode == "STRICT_SYNC"},
                  timeout=10.0, src=self.raft.node_id, dst=main_name)

    def _try_failover(self, failed_main: str) -> None:
        """Choose the most up-to-date alive replica and promote it.

        Raft-commit retries ride the shared RetryPolicy; the whole
        procedure is idempotent (reconciliation repairs a crash between
        the commit and the promote RPCs), so every exit path is safe."""
        global_metrics.increment("coordination.failover_attempts")
        with self._lock:
            candidates = [dict(i) for i in self.instances.values()
                          if i["name"] != failed_main]
        best_name, best_ts = None, -1
        for inst in candidates:
            resp = mgmt_call(inst["mgmt_address"], {"kind": "state_check"},
                             timeout=1.0, src=self.raft.node_id,
                             dst=inst["name"])
            if resp is None or not resp.get("ok"):
                continue
            ts = resp.get("last_commit_ts", 0)
            if ts > best_ts:
                best_name, best_ts = inst["name"], ts
        if best_name is None:
            log.error("failover: no alive replica to promote")
            return
        log.warning("failover: promoting %s (last_commit_ts=%d) to MAIN",
                    best_name, best_ts)
        committed = False
        for attempt in range(self.failover_retry.max_retries + 1):
            result = self.raft.propose({"op": "set_main",
                                        "name": best_name})
            if result:
                committed = True
                break
            if not result.retryable:
                # not_leader/lost_leadership: the NEW raft leader owns
                # this failover now — do not fight it
                log.error("failover: raft commit failed (%s); yielding "
                          "to the current leader", result.outcome)
                return
            log.warning("failover: raft commit %s (attempt %d); "
                        "retrying with backoff", result.outcome, attempt)
            time.sleep(self.failover_retry.delay_for(attempt))
        if not committed:
            log.error("failover: raft commit retries exhausted")
            return
        with self._lock:
            epoch = self.epoch
        global_metrics.increment("coordination.failovers_total")
        global_metrics.set_gauge("coordination.current_epoch",
                                 float(epoch))
        log.warning("failover: %s is MAIN at fencing epoch %d",
                    best_name, epoch)
        self._reconfigure_data_instances(best_name)

    def _reconfigure_data_instances(self, new_main: str) -> None:
        with self._lock:
            instances = [dict(i) for i in self.instances.values()]
            epoch = self.epoch
        replicas = []
        for inst in instances:
            if inst["name"] == new_main:
                continue
            # demote (best effort — the failed MAIN may be unreachable;
            # reconciliation fences it with this epoch when it returns)
            port = int(inst["replication_address"].rpartition(":")[2])
            mgmt_call(inst["mgmt_address"],
                      {"kind": "demote", "replication_port": port,
                       "epoch": epoch},
                      timeout=2.0, src=self.raft.node_id,
                      dst=inst["name"])
            replicas.append({"name": inst["name"],
                             "address": inst["replication_address"],
                             "mode": self.repl_mode})
        resp = mgmt_call(
            next(i["mgmt_address"] for i in instances
                 if i["name"] == new_main),
            {"kind": "promote", "replicas": replicas, "epoch": epoch,
             "no_strict_degradation": self.repl_mode == "STRICT_SYNC"},
            timeout=10.0, src=self.raft.node_id, dst=new_main)
        if resp is None or not resp.get("ok"):
            log.error("failover: promote of %s reported %s (reconcile "
                      "will retry)", new_main, resp)
