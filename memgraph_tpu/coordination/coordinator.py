"""Coordinator instance: Raft-replicated cluster state + failover.

Counterpart of the reference's CoordinatorInstance
(/root/reference/src/coordination/coordinator_instance.cpp): the Raft
leader health-checks every data instance (StateCheck RPC analog, :478-502);
after `FAILOVER_MISS_THRESHOLD` consecutive misses of the MAIN it runs
TryFailover (:542-585): pick the most up-to-date alive replica, commit the
new topology through Raft, then promote/demote the data instances.
"""

from __future__ import annotations

import logging
import threading
import time

from ..utils.locks import tracked_lock
from .data_instance import mgmt_call
from .raft import RaftNode

log = logging.getLogger(__name__)


class CoordinatorInstance:
    HEALTH_CHECK_INTERVAL = 0.5
    FAILOVER_MISS_THRESHOLD = 3

    def __init__(self, node_id: str, host: str, raft_port: int,
                 peers: dict[str, tuple[str, int]], kvstore=None,
                 routers: list[str] | None = None):
        # bolt addresses of ALL coordinators (config-derived), served in
        # the ROUTE role so drivers survive losing their bootstrap router
        self.routers = list(routers or [])
        # replicated cluster state: name -> instance descriptor
        # (initialized BEFORE RaftNode: restoring a persisted snapshot
        # calls _restore during RaftNode.__init__)
        self.instances: dict[str, dict] = {}
        self.main_name: str | None = None
        self._lock = tracked_lock("Coordinator._lock")
        self.raft = RaftNode(node_id, host, raft_port, peers,
                             apply_fn=self._apply, kvstore=kvstore,
                             snapshot_fn=self._snapshot,
                             restore_fn=self._restore)
        self._miss_counts: dict[str, int] = {}
        self._stop = threading.Event()
        self._health_thread: threading.Thread | None = None

    # --- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.raft.start()
        self._health_thread = threading.Thread(target=self._health_loop,
                                               daemon=True)
        self._health_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.raft.stop()

    # --- replicated state machine -------------------------------------------

    def _apply(self, command: dict) -> None:
        """Applied on EVERY coordinator for each committed Raft entry."""
        op = command.get("op")
        with self._lock:
            if op == "register_instance":
                self.instances[command["name"]] = {
                    "name": command["name"],
                    "mgmt_address": command["mgmt_address"],
                    "replication_address": command["replication_address"],
                    "bolt_address": command.get("bolt_address"),
                    "role": "replica",
                }
            elif op == "unregister_instance":
                self.instances.pop(command["name"], None)
                if self.main_name == command["name"]:
                    self.main_name = None
            elif op == "set_main":
                name = command["name"]
                for inst in self.instances.values():
                    inst["role"] = "replica"
                if name in self.instances:
                    self.instances[name]["role"] = "main"
                    self.main_name = name

    def _snapshot(self) -> dict:
        """State-machine snapshot for Raft log compaction."""
        with self._lock:
            return {"instances": {k: dict(v)
                                  for k, v in self.instances.items()},
                    "main_name": self.main_name}

    def _restore(self, state: dict) -> None:
        """Replace the state machine from a Raft snapshot (restart replay
        or leader install-snapshot for a lagging coordinator)."""
        with self._lock:
            self.instances = {k: dict(v)
                              for k, v in state.get("instances",
                                                    {}).items()}
            self.main_name = state.get("main_name")

    # --- client operations (leader only) ------------------------------------

    def register_instance(self, name: str, mgmt_address: str,
                          replication_address: str,
                          bolt_address: str | None = None) -> bool:
        return self.raft.propose({
            "op": "register_instance", "name": name,
            "mgmt_address": mgmt_address,
            "replication_address": replication_address,
            "bolt_address": bolt_address})

    def route_table(self) -> dict:
        """Bolt ROUTE table from LIVE replicated cluster state (reference:
        coordinator_instance.cpp routing): MAIN serves writes, replicas
        serve reads; this coordinator serves further ROUTE requests."""
        with self._lock:
            writers = [i["bolt_address"] for i in self.instances.values()
                       if i["role"] == "main" and i.get("bolt_address")]
            readers = [i["bolt_address"] for i in self.instances.values()
                       if i["role"] == "replica" and i.get("bolt_address")]
        return {"writers": writers, "readers": readers or writers}

    def unregister_instance(self, name: str) -> bool:
        return self.raft.propose({"op": "unregister_instance", "name": name})

    def set_instance_to_main(self, name: str) -> bool:
        """Explicit promotion: commit through Raft, then reconfigure."""
        with self._lock:
            if name not in self.instances:
                return False
        if not self.raft.propose({"op": "set_main", "name": name}):
            return False
        self._reconfigure_data_instances(name)
        return True

    def show_instances(self) -> list[list]:
        with self._lock:
            instances = [dict(i) for i in self.instances.values()]
        rows = []
        is_leader = self.raft.is_leader()
        for inst in sorted(instances, key=lambda i: i["name"]):
            health = "unknown"
            if is_leader:
                misses = self._miss_counts.get(inst["name"], 0)
                health = "up" if misses == 0 else (
                    "down" if misses >= self.FAILOVER_MISS_THRESHOLD
                    else "degraded")
            rows.append([inst["name"], inst["mgmt_address"],
                         inst["role"], health])
        rows.append([self.raft.node_id, f"raft:{self.raft.port}",
                     "leader" if is_leader else "coordinator", "up"])
        return rows

    # --- health checks + failover (leader) ----------------------------------

    def _health_loop(self) -> None:
        while not self._stop.wait(self.HEALTH_CHECK_INTERVAL):
            if not self.raft.is_leader():
                continue
            with self._lock:
                instances = [dict(i) for i in self.instances.values()]
                main_name = self.main_name
            for inst in instances:
                resp = mgmt_call(inst["mgmt_address"],
                                 {"kind": "state_check"}, timeout=1.0)
                name = inst["name"]
                if resp is None or not resp.get("ok"):
                    self._miss_counts[name] = \
                        self._miss_counts.get(name, 0) + 1
                else:
                    self._miss_counts[name] = 0
            if main_name is not None and \
                    self._miss_counts.get(main_name, 0) >= \
                    self.FAILOVER_MISS_THRESHOLD:
                self._try_failover(main_name)

    def _try_failover(self, failed_main: str) -> None:
        """Choose the most up-to-date alive replica and promote it."""
        with self._lock:
            candidates = [dict(i) for i in self.instances.values()
                          if i["name"] != failed_main]
        best_name, best_ts = None, -1
        for inst in candidates:
            resp = mgmt_call(inst["mgmt_address"], {"kind": "state_check"},
                             timeout=1.0)
            if resp is None or not resp.get("ok"):
                continue
            ts = resp.get("last_commit_ts", 0)
            if ts > best_ts:
                best_name, best_ts = inst["name"], ts
        if best_name is None:
            log.error("failover: no alive replica to promote")
            return
        log.warning("failover: promoting %s (last_commit_ts=%d) to MAIN",
                    best_name, best_ts)
        if not self.raft.propose({"op": "set_main", "name": best_name}):
            log.error("failover: raft commit failed")
            return
        self._reconfigure_data_instances(best_name)

    def _reconfigure_data_instances(self, new_main: str) -> None:
        with self._lock:
            instances = [dict(i) for i in self.instances.values()]
        replicas = []
        for inst in instances:
            if inst["name"] == new_main:
                continue
            # demote (best effort — the failed MAIN may be unreachable)
            port = int(inst["replication_address"].rpartition(":")[2])
            mgmt_call(inst["mgmt_address"],
                      {"kind": "demote", "replication_port": port},
                      timeout=2.0)
            replicas.append({"name": inst["name"],
                             "address": inst["replication_address"],
                             "mode": "SYNC"})
        resp = mgmt_call(
            next(i["mgmt_address"] for i in instances
                 if i["name"] == new_main),
            {"kind": "promote", "replicas": replicas}, timeout=10.0)
        if resp is None or not resp.get("ok"):
            log.error("failover: promote of %s reported %s", new_main, resp)
