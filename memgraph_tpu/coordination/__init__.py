"""High-availability coordination: Raft consensus + automatic failover.

Counterpart of the reference's coordinator layer
(/root/reference/src/coordination/ — RaftState over NuRaft at
raft_state.cpp:370, health-checked failover at
coordinator_instance.cpp:478-585). The environment has no Raft library, so
raft.py is a from-scratch implementation of the Raft protocol (elections,
log replication, commit on majority) sized for the control plane: the
replicated state machine holds the cluster topology (which data instance is
MAIN), not data — the data plane stays WAL-frame replication.
"""

from .raft import RaftNode
from .coordinator import CoordinatorInstance

__all__ = ["RaftNode", "CoordinatorInstance"]
