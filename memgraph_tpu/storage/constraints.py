"""Existence and unique constraints.

Capability map to the reference's storage/v2/constraints/: existence
constraints validated per-write, unique constraints validated at commit time
under the engine lock (reference: inmemory/storage.cpp:1156-1172). Unique
keys use the canonical binary value encoding so composite and nested values
compare correctly.
"""

from __future__ import annotations

import threading

from ..exceptions import ConstraintViolation
from .property_store import value_key


class ExistenceConstraints:
    """Set of (label_id, prop_id): every vertex with label must have prop."""

    def __init__(self) -> None:
        self._constraints: set[tuple[int, int]] = set()

    def create(self, label_id: int, prop_id: int, vertices, namer=None) -> None:
        for v in vertices:
            if label_id in v.labels and prop_id not in v.properties and not v.deleted:
                raise ConstraintViolation(
                    self._message(label_id, prop_id, namer),
                    constraint=("existence", label_id, (prop_id,)))
        self._constraints.add((label_id, prop_id))

    def drop(self, label_id: int, prop_id: int) -> bool:
        try:
            self._constraints.remove((label_id, prop_id))
            return True
        except KeyError:
            return False

    def has(self, label_id: int, prop_id: int) -> bool:
        return (label_id, prop_id) in self._constraints

    def all(self):
        return sorted(self._constraints)

    @staticmethod
    def _message(label_id, prop_id, namer):
        if namer:
            return (f"Node with label {namer.label(label_id)} is missing "
                    f"required property {namer.prop(prop_id)}")
        return f"Existence constraint violated (label {label_id}, property {prop_id})"

    def validate_vertex(self, labels, properties, namer=None) -> None:
        for (label_id, prop_id) in self._constraints:
            if label_id in labels and prop_id not in properties:
                raise ConstraintViolation(
                    self._message(label_id, prop_id, namer),
                    constraint=("existence", label_id, (prop_id,)))


def _canonical(v):
    """Canonicalize values so key equality matches Cypher value equality:
    1 == 1.0 (but true != 1), applied recursively through containers."""
    if isinstance(v, bool):
        return v
    if isinstance(v, float) and v.is_integer() and abs(v) < 2 ** 63:
        return int(v)
    if isinstance(v, list):
        return [_canonical(x) for x in v]
    if isinstance(v, dict):
        return {k: _canonical(x) for k, x in v.items()}
    return v


class _UniqueSlot:
    """Committed key registry for one unique constraint."""

    __slots__ = ("by_key", "by_gid")

    def __init__(self) -> None:
        self.by_key: dict[bytes, int] = {}
        self.by_gid: dict[int, bytes] = {}

    def register(self, gid: int, new_key: bytes | None) -> None:
        old_key = self.by_gid.get(gid)
        if old_key == new_key:
            return
        if old_key is not None:
            # same-commit handover may have already reassigned the key to
            # another gid — only release it if we still own it
            if self.by_key.get(old_key) == gid:
                self.by_key.pop(old_key)
            del self.by_gid[gid]
        if new_key is not None:
            self.by_key[new_key] = gid
            self.by_gid[gid] = new_key


class UniqueConstraints:
    """Set of (label_id, (prop_ids...)) with committed-value registries.

    Registered values track *committed* state only; commit-time validation
    (under the engine lock, so commits are serialized) checks each touched
    vertex's new values against the registry and against the other vertices
    committing in the same transaction.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._maps: dict[tuple[int, tuple[int, ...]], _UniqueSlot] = {}

    @staticmethod
    def _key(values) -> bytes:
        return b"\x1f".join(value_key(_canonical(v)) for v in values)

    def create(self, label_id: int, prop_ids: tuple[int, ...], vertices,
               namer=None) -> None:
        slot = _UniqueSlot()
        for v in vertices:
            k = self._vertex_key(v, label_id, prop_ids)
            if k is None:
                continue
            if k in slot.by_key:
                raise ConstraintViolation(
                    self._message(label_id, prop_ids, namer),
                    constraint=("unique", label_id, prop_ids))
            slot.register(v.gid, k)
        with self._lock:
            self._maps[(label_id, prop_ids)] = slot

    def drop(self, label_id: int, prop_ids: tuple[int, ...]) -> bool:
        with self._lock:
            return self._maps.pop((label_id, prop_ids), None) is not None

    def has(self, label_id: int, prop_ids: tuple[int, ...]) -> bool:
        return (label_id, prop_ids) in self._maps

    def all(self):
        return sorted(self._maps)

    def _vertex_key(self, v, label_id, prop_ids):
        if label_id not in v.labels or v.deleted:
            return None
        values = []
        for pid in prop_ids:
            if pid not in v.properties:
                return None
            values.append(v.properties[pid])
        return self._key(values)

    @staticmethod
    def _message(label_id, prop_ids, namer):
        if namer:
            props = ", ".join(namer.prop(p) for p in prop_ids)
            return (f"Unique constraint violated on label "
                    f"{namer.label(label_id)} properties ({props})")
        return f"Unique constraint violated (label {label_id}, properties {prop_ids})"

    def validate_commit(self, touched_vertices, namer=None) -> list:
        """Validate touched vertices; return registrations to apply on success.

        Called under the engine lock. Checks both the committed registry and
        collisions *within* this commit's pending set.
        """
        registrations = []
        for (label_id, prop_ids), slot in self._maps.items():
            # first pass: keys this commit releases (old owner loses the key),
            # so a same-transaction handover (delete A, create B with A's
            # value) validates correctly
            new_keys: dict[int, bytes | None] = {}
            released: set[bytes] = set()
            for v in touched_vertices:
                new_key = self._vertex_key(v, label_id, prop_ids)
                new_keys[v.gid] = new_key
                old_key = slot.by_gid.get(v.gid)
                if old_key is not None and old_key != new_key:
                    released.add(old_key)
            pending: dict[bytes, int] = {}
            for v in touched_vertices:
                new_key = new_keys[v.gid]
                if new_key is not None:
                    owner = slot.by_key.get(new_key)
                    if (owner is not None and owner != v.gid
                            and new_key not in released):
                        raise ConstraintViolation(
                            self._message(label_id, prop_ids, namer),
                            constraint=("unique", label_id, prop_ids))
                    other = pending.get(new_key)
                    if other is not None and other != v.gid:
                        raise ConstraintViolation(
                            self._message(label_id, prop_ids, namer),
                            constraint=("unique", label_id, prop_ids))
                    pending[new_key] = v.gid
                if new_key is not None or v.gid in slot.by_gid:
                    registrations.append((slot, v.gid, new_key))
        return registrations

    def apply_registrations(self, registrations) -> None:
        with self._lock:
            for slot, gid, new_key in registrations:
                slot.register(gid, new_key)


class TypeConstraints:
    """(label_id, prop_id) -> required type name (IS TYPED ...)."""

    _CHECKS = {
        "STRING": lambda v: isinstance(v, str),
        "INTEGER": lambda v: isinstance(v, int) and not isinstance(v, bool),
        "FLOAT": lambda v: isinstance(v, float),
        "BOOLEAN": lambda v: isinstance(v, bool),
        "LIST": lambda v: isinstance(v, list),
        "MAP": lambda v: isinstance(v, dict),
    }

    def __init__(self) -> None:
        self._constraints: dict[tuple[int, int], str] = {}

    def create(self, label_id: int, prop_id: int, type_name: str,
               vertices, namer=None) -> None:
        type_name = type_name.upper()
        check = self._CHECKS.get(type_name)
        if check is None:
            raise ConstraintViolation(f"Unsupported type constraint {type_name}")
        for v in vertices:
            if label_id in v.labels and prop_id in v.properties and not v.deleted:
                if not check(v.properties[prop_id]):
                    raise ConstraintViolation(
                        f"Type constraint ({type_name}) violated",
                        constraint=("type", label_id, (prop_id,)))
        self._constraints[(label_id, prop_id)] = type_name

    def drop(self, label_id: int, prop_id: int) -> bool:
        return self._constraints.pop((label_id, prop_id), None) is not None

    def all(self):
        return sorted((k[0], k[1], v) for k, v in self._constraints.items())

    def validate_vertex(self, labels, properties, namer=None) -> None:
        for (label_id, prop_id), type_name in self._constraints.items():
            if label_id in labels and prop_id in properties:
                if not self._CHECKS[type_name](properties[prop_id]):
                    raise ConstraintViolation(
                        f"Type constraint ({type_name}) violated",
                        constraint=("type", label_id, (prop_id,)))


class Constraints:
    def __init__(self) -> None:
        self.existence = ExistenceConstraints()
        self.unique = UniqueConstraints()
        self.type = TypeConstraints()
