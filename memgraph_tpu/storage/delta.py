"""MVCC delta records: undo operations forming per-object version chains.

Same model as the reference (storage/v2/delta.hpp:244, delta_action.hpp:21-32):
each mutation pushes an *undo* delta at the head of the object's chain, tagged
with the writing transaction's CommitInfo. While the transaction is active the
CommitInfo timestamp is the transaction id (>= TRANSACTION_ID_START); commit
flips it — atomically for every delta of the transaction, since they share the
one CommitInfo object — to the commit timestamp. Readers walk the chain
applying undos until they reach their snapshot.
"""

from __future__ import annotations

import enum
from typing import Any


class CommitInfo:
    """Shared by all deltas of one transaction; timestamp flips on commit."""

    __slots__ = ("timestamp",)

    def __init__(self, txn_or_commit_ts: int) -> None:
        self.timestamp = txn_or_commit_ts


class DeltaAction(enum.Enum):
    # vertex/edge existence (undo directions)
    DELETE_OBJECT = 1      # undo of create: "before this txn, object didn't exist"
    RECREATE_OBJECT = 2    # undo of delete: "before this txn, object existed"
    # vertex state
    ADD_LABEL = 3          # undo of remove_label
    REMOVE_LABEL = 4       # undo of add_label
    SET_PROPERTY = 5       # undo: restore previous value (vertex or edge)
    ADD_IN_EDGE = 6        # undo of remove_in_edge
    ADD_OUT_EDGE = 7       # undo of remove_out_edge
    REMOVE_IN_EDGE = 8     # undo of add_in_edge
    REMOVE_OUT_EDGE = 9    # undo of add_out_edge
    # batch-insert amortization: ONE undo for all adjacency entries a bulk
    # insert appended to a pre-existing vertex (payload: tuple of entries).
    # Keeps hub vertices from growing one delta per spoke during bulk loads.
    REMOVE_IN_EDGES_BULK = 10
    REMOVE_OUT_EDGES_BULK = 11


# actions that only affect the adjacency lists of a materialized state —
# readers that need labels/properties/existence only can skip both copying
# the (possibly huge) adjacency lists and applying these undos
EDGE_ACTIONS = frozenset({
    DeltaAction.ADD_IN_EDGE, DeltaAction.ADD_OUT_EDGE,
    DeltaAction.REMOVE_IN_EDGE, DeltaAction.REMOVE_OUT_EDGE,
    DeltaAction.REMOVE_IN_EDGES_BULK, DeltaAction.REMOVE_OUT_EDGES_BULK,
})


class Delta:
    """One undo record. `payload` depends on action:

    DELETE_OBJECT / RECREATE_OBJECT: None
    ADD_LABEL / REMOVE_LABEL:        label_id (int)
    SET_PROPERTY:                    (property_id, previous_value)
    *_IN_EDGE / *_OUT_EDGE:          (edge_type_id, other_vertex, edge)
    """

    __slots__ = ("action", "payload", "commit_info", "next", "obj")

    def __init__(self, action: DeltaAction, payload: Any,
                 commit_info: CommitInfo, next_delta: "Delta | None",
                 obj: Any) -> None:
        self.action = action
        self.payload = payload
        self.commit_info = commit_info
        self.next = next_delta  # older delta (towards the past)
        self.obj = obj          # owning Vertex/Edge (for abort/GC)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Delta({self.action.name}, ts={self.commit_info.timestamp}, "
                f"payload={self.payload!r})")


def apply_undo(state: "MaterializedState", delta: Delta) -> None:
    """Apply one undo record to a materialized read state."""
    a = delta.action
    if a is DeltaAction.DELETE_OBJECT:
        state.exists = False
    elif a is DeltaAction.RECREATE_OBJECT:
        state.exists = True
        state.deleted = False
    elif a is DeltaAction.ADD_LABEL:
        state.labels.add(delta.payload)
    elif a is DeltaAction.REMOVE_LABEL:
        state.labels.discard(delta.payload)
    elif a is DeltaAction.SET_PROPERTY:
        prop_id, prev = delta.payload
        if prev is None:
            state.properties.pop(prop_id, None)
        else:
            state.properties[prop_id] = prev
    elif a is DeltaAction.ADD_IN_EDGE:
        state.in_edges.append(delta.payload)
    elif a is DeltaAction.REMOVE_IN_EDGE:
        state.in_edges.remove(delta.payload)
    elif a is DeltaAction.ADD_OUT_EDGE:
        state.out_edges.append(delta.payload)
    elif a is DeltaAction.REMOVE_OUT_EDGE:
        state.out_edges.remove(delta.payload)
    elif a is DeltaAction.REMOVE_IN_EDGES_BULK:
        drop = set(delta.payload)
        state.in_edges = [e for e in state.in_edges if e not in drop]
    elif a is DeltaAction.REMOVE_OUT_EDGES_BULK:
        drop = set(delta.payload)
        state.out_edges = [e for e in state.out_edges if e not in drop]
    else:  # pragma: no cover
        raise AssertionError(f"unknown delta action {a}")


class MaterializedState:
    """A reader's reconstructed view of one object at its snapshot."""

    __slots__ = ("exists", "deleted", "labels", "properties", "in_edges",
                 "out_edges")

    def __init__(self, exists=True, deleted=False, labels=None, properties=None,
                 in_edges=None, out_edges=None):
        self.exists = exists
        self.deleted = deleted
        self.labels = labels if labels is not None else set()
        self.properties = properties if properties is not None else {}
        self.in_edges = in_edges if in_edges is not None else []
        self.out_edges = out_edges if out_edges is not None else []
