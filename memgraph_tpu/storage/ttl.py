"""TTL: background expiry of vertices by a ttl property.

Counterpart of /root/reference/src/storage/v2/ttl.{hpp,cpp}: vertices
carrying a `ttl` property (microseconds-since-epoch expiry time) are deleted
by a periodic background job; replication-aware (runs on MAIN only).
Enabled via `ENABLE TTL EVERY <duration>`-style queries or the API.
"""

from __future__ import annotations

import threading
import time


TTL_PROPERTY = "ttl"


class TtlRunner:
    def __init__(self, interpreter_context, period_sec: float = 1.0,
                 batch_size: int = 10_000):
        self.ictx = interpreter_context
        self.period_sec = period_sec
        self.batch_size = batch_size
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.enabled = False
        self.runs = 0
        self.deleted_total = 0

    def start(self) -> None:
        if self._thread is not None:
            return
        self.enabled = True
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.enabled = False
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.period_sec):
            try:
                self.run_once()
            except Exception:
                import logging
                logging.getLogger(__name__).exception("ttl sweep failed")

    def run_once(self) -> int:
        """One expiry sweep; returns number of deleted vertices."""
        replication = getattr(self.ictx, "replication", None)
        if replication is not None and replication.role == "replica":
            return 0  # MAIN-only (reference: memgraph.cpp:816-823 analog)
        storage = self.ictx.storage
        pid = storage.property_mapper.maybe_name_to_id(TTL_PROPERTY)
        if pid is None:
            return 0
        now_us = int(time.time() * 1_000_000)
        deleted = 0
        from ..exceptions import SerializationError
        acc = storage.access()
        try:
            doomed = []
            for va in acc.vertices():
                expiry = va.get_property(pid)
                if isinstance(expiry, int) and not isinstance(expiry, bool) \
                        and expiry <= now_us:
                    doomed.append(va)
                    if len(doomed) >= self.batch_size:
                        break
            for va in doomed:
                try:
                    acc.delete_vertex(va, detach=True)
                    deleted += 1
                except SerializationError:
                    pass  # concurrent writer owns it; next sweep
            acc.commit()
        except SerializationError:
            acc.abort()
            return 0
        self.runs += 1
        self.deleted_total += deleted
        return deleted


import weakref

_RUNNERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_RUNNERS_LOCK = threading.Lock()


def ttl_runner(interpreter_context) -> TtlRunner:
    with _RUNNERS_LOCK:
        runner = _RUNNERS.get(interpreter_context)
        if runner is None:
            runner = TtlRunner(interpreter_context)
            _RUNNERS[interpreter_context] = runner
        return runner
