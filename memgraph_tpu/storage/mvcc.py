"""MVCC visibility and write-ownership rules.

Semantics follow the reference's ApplyDeltasForRead / PrepareForWrite
(storage/v2/mvcc.hpp:33-140) re-expressed over the Python delta model:

Read at snapshot S (transaction T):
  start from the object's *current* state, then walk the delta (undo) chain
  newest-first, applying each undo whose writer is invisible to T:
    - writer is another still-active transaction (ts >= TRANSACTION_ID_START,
      ts != T.id), or
    - writer committed after S (ts > S), or
    - writer is T itself but the reader asked for View.OLD.
  Stop at the first visible delta (chain is ordered newest→oldest, so
  once a writer is visible all older ones are too).

Write by T:
  the head delta must be either absent, written by T itself, or committed at
  or before T.start_ts; otherwise a concurrent writer owns the object →
  SerializationError (optimistic, first-writer-wins).
"""

from __future__ import annotations

from ..exceptions import SerializationError
from .common import TRANSACTION_ID_START, View
from .delta import (EDGE_ACTIONS, CommitInfo, Delta, DeltaAction,
                    MaterializedState, apply_undo)
from .objects import Edge, Vertex


def _writer_invisible(ts: int, txn_id: int, start_ts: int, view: View) -> bool:
    if ts >= TRANSACTION_ID_START:
        if ts == txn_id:
            return view is View.OLD  # own write: visible only under NEW
        return True                  # other active txn: never visible
    return ts > start_ts             # committed after our snapshot


def state_is_current(obj: Vertex | Edge, txn, view: View) -> bool:
    """True when `txn`'s view of `obj` equals its live fields: the undo walk
    stops at the first visible delta, so a visible (or absent) chain head
    means no undo applies. Caller should hold obj.lock for an atomic answer.
    """
    delta = obj.delta
    if delta is None:
        return True
    ts = delta.commit_info.timestamp
    return not _writer_invisible(ts, txn.id, txn.effective_start_ts(), view)


def materialize_vertex(vertex: Vertex, txn, view: View,
                       need_edges: bool = True) -> MaterializedState:
    """Reconstruct `vertex` as seen by `txn` under `view`.

    need_edges=False skips copying the adjacency lists AND applying edge
    undos — labels/properties/existence readers on supernode hubs must not
    pay an O(degree) list copy per property access (round-5 write-path
    profile: this copy dominated hub UNWIND SET).
    """
    with vertex.lock:
        state = MaterializedState(
            exists=True,
            deleted=vertex.deleted,
            labels=set(vertex.labels),
            properties=dict(vertex.properties),
            in_edges=list(vertex.in_edges) if need_edges else [],
            out_edges=list(vertex.out_edges) if need_edges else [],
        )
        delta = vertex.delta
    _walk(delta, state, txn, view, apply_edges=need_edges)
    return state


def materialize_edge(edge: Edge, txn, view: View) -> MaterializedState:
    with edge.lock:
        state = MaterializedState(
            exists=True,
            deleted=edge.deleted,
            properties=dict(edge.properties),
        )
        delta = edge.delta
    _walk(delta, state, txn, view)
    return state


def _walk(delta: Delta | None, state: MaterializedState, txn, view: View,
          apply_edges: bool = True) -> None:
    start_ts = txn.effective_start_ts()
    txn_id = txn.id
    while delta is not None:
        ts = delta.commit_info.timestamp
        if not _writer_invisible(ts, txn_id, start_ts, view):
            break
        if apply_edges or delta.action not in EDGE_ACTIONS:
            apply_undo(state, delta)
        delta = delta.next
    # Callers treat visibility as `state.exists and not state.deleted`;
    # the flags stay separate so accessors can distinguish "never existed at
    # this snapshot" from "deleted" (different client-facing errors).


def prepare_for_write(obj: Vertex | Edge, txn) -> None:
    """Assert `txn` may mutate `obj`; raise SerializationError otherwise.

    Caller must hold obj.lock.
    """
    delta = obj.delta
    if delta is None:
        return
    ts = delta.commit_info.timestamp
    if ts == txn.id:
        return
    if ts >= TRANSACTION_ID_START:
        raise SerializationError(
            "Cannot serialize due to concurrent write (object owned by an "
            "active transaction). Retry the transaction.")
    if ts > txn.start_ts:
        raise SerializationError(
            "Cannot serialize: object modified by a transaction committed "
            "after this transaction started. Retry the transaction.")


def push_delta(obj: Vertex | Edge, txn, action: DeltaAction, payload) -> Delta:
    """Create an undo delta at the head of obj's chain and register it with txn.

    Caller must hold obj.lock and have called prepare_for_write.
    """
    delta = Delta(action, payload, txn.commit_info, obj.delta, obj)
    obj.delta = delta
    txn.deltas.append(delta)
    return delta

