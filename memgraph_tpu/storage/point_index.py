"""Spatial point index: uniform grid buckets per (label, property).

Counterpart of the reference's point index
(/root/reference/src/storage/v2/indices/point_index.cpp): accelerates
point.distance / withinbbox queries. Grid cells hash (floor(x/cell),
floor(y/cell)); WGS-84 uses degree cells (distance filtering re-validates
exactly, so cell size only affects candidate counts).
"""

from __future__ import annotations

import math
import threading
from collections import defaultdict

from ..exceptions import TypeException
from ..utils.point import Point


class PointIndex:
    def __init__(self, label_id: int, prop_id: int, cell_size: float = 1.0):
        self.label_id = label_id
        self.prop_id = prop_id
        self.cell_size = cell_size
        self._lock = threading.Lock()
        self._cells: dict[tuple[int, int], dict[int, tuple]] = \
            defaultdict(dict)  # cell -> {gid: (vertex, point)}
        self._by_gid: dict[int, tuple[int, int]] = {}

    def _cell(self, p: Point) -> tuple[int, int]:
        return (math.floor(p.x / self.cell_size),
                math.floor(p.y / self.cell_size))

    def add_vertex(self, vertex) -> None:
        value = vertex.properties.get(self.prop_id)
        with self._lock:
            self._remove_locked(vertex.gid)
            if (self.label_id not in vertex.labels or vertex.deleted
                    or not isinstance(value, Point)):
                return
            cell = self._cell(value)
            self._cells[cell][vertex.gid] = (vertex, value)
            self._by_gid[vertex.gid] = cell

    def remove_vertex(self, gid: int) -> None:
        with self._lock:
            self._remove_locked(gid)

    def _remove_locked(self, gid: int) -> None:
        cell = self._by_gid.pop(gid, None)
        if cell is not None:
            self._cells[cell].pop(gid, None)

    def rebuild(self, vertices) -> None:
        with self._lock:
            self._cells.clear()
            self._by_gid.clear()
        for v in vertices:
            self.add_vertex(v)

    def within_distance(self, center: Point, radius: float
                        ) -> list[tuple[int, float]]:
        """[(gid, distance)] within radius (exact re-validation per hit)."""
        # conservative cell radius: WGS degrees ≈ 111km
        cell_r = radius / (111_000.0 if center.crs.is_wgs else 1.0)
        cr = max(1, math.ceil(cell_r / self.cell_size))
        cx, cy = self._cell(center)
        out = []
        with self._lock:
            for dx in range(-cr, cr + 1):
                for dy in range(-cr, cr + 1):
                    for gid, (v, p) in self._cells.get(
                            (cx + dx, cy + dy), {}).items():
                        try:
                            d = center.distance(p)
                        except TypeException:
                            continue  # mixed-CRS point is never a hit
                        if d <= radius:
                            out.append((gid, d))
        out.sort(key=lambda t: t[1])
        return out

    def within_bbox(self, lo: Point, hi: Point) -> list[int]:
        clo, chi = self._cell(lo), self._cell(hi)
        out = []
        with self._lock:
            for cx in range(clo[0], chi[0] + 1):
                for cy in range(clo[1], chi[1] + 1):
                    for gid, (v, p) in self._cells.get((cx, cy), {}).items():
                        if lo.x <= p.x <= hi.x and lo.y <= p.y <= hi.y:
                            out.append(gid)
        return out

    def size(self) -> int:
        with self._lock:
            return len(self._by_gid)


class PointIndices:
    def __init__(self, storage) -> None:
        self.storage = storage
        self._lock = threading.Lock()
        self._indexes: dict[tuple[int, int], PointIndex] = {}
        storage.on_commit_hooks.append(self._on_commit)

    def create(self, label_name: str, prop_name: str) -> PointIndex:
        from ..exceptions import QueryException
        lid = self.storage.label_mapper.name_to_id(label_name)
        pid = self.storage.property_mapper.name_to_id(prop_name)
        with self._lock:
            if (lid, pid) in self._indexes:
                raise QueryException("point index already exists")
        index = PointIndex(lid, pid)
        index.rebuild(list(self.storage._vertices.values()))
        with self._lock:
            self._indexes[(lid, pid)] = index
        return index

    def drop(self, label_name: str, prop_name: str) -> bool:
        lid = self.storage.label_mapper.maybe_name_to_id(label_name)
        pid = self.storage.property_mapper.maybe_name_to_id(prop_name)
        with self._lock:
            return self._indexes.pop((lid, pid), None) is not None

    def get(self, label_name: str, prop_name: str) -> PointIndex | None:
        lid = self.storage.label_mapper.maybe_name_to_id(label_name)
        pid = self.storage.property_mapper.maybe_name_to_id(prop_name)
        with self._lock:
            return self._indexes.get((lid, pid))

    def all(self):
        with self._lock:
            return dict(self._indexes)

    def _on_commit(self, txn, commit_ts) -> None:
        with self._lock:
            indexes = list(self._indexes.values())
        if not indexes:
            return
        for vertex in txn.touched_vertices.values():
            for index in indexes:
                if vertex.deleted:
                    index.remove_vertex(vertex.gid)
                else:
                    index.add_vertex(vertex)


def point_indices(storage) -> PointIndices:
    if storage.indices.point is None:
        storage.indices.point = PointIndices(storage)
    return storage.indices.point
