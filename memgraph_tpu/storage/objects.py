"""In-memory vertex and edge records.

The reference packs Vertex into 80 bytes with small_vectors and a tagged delta
pointer (storage/v2/vertex.hpp:32-73). In the Python host layer we keep the
same *shape* — gid, labels, properties, adjacency, delta head, per-object
lock — with __slots__ for density. Adjacency entries are
(edge_type_id, other_vertex, edge) triples, mirroring the reference's
(EdgeType, Vertex*, EdgeRef) tuples so edge objects are only touched when
edge properties are needed.
"""

from __future__ import annotations

import threading
from typing import Optional

from .delta import Delta


# in/out degree at which a per-vertex adjacency map (neighbor gid -> entry
# list) is built lazily, making bound-endpoint edge lookups — the MERGE
# existence probe — O(1) instead of O(degree) on supernode hubs
ADJ_INDEX_THRESHOLD = 64


class Vertex:
    __slots__ = ("gid", "labels", "properties", "in_edges", "out_edges",
                 "deleted", "delta", "lock", "adj_in", "adj_out")

    def __init__(self, gid: int, delta: Optional[Delta] = None) -> None:
        self.gid = gid
        self.labels: set[int] = set()
        self.properties: dict[int, object] = {}
        # entries: (edge_type_id, other_vertex, edge)
        self.in_edges: list[tuple] = []
        self.out_edges: list[tuple] = []
        self.deleted = False
        self.delta = delta
        self.lock = threading.Lock()
        # lazy supernode adjacency maps: other_gid -> [entry, ...].
        # None = not built; kept exactly in sync with in_edges/out_edges by
        # every path that mutates those lists (or invalidated back to None).
        self.adj_in: Optional[dict] = None
        self.adj_out: Optional[dict] = None

    def __repr__(self) -> str:  # pragma: no cover
        return f"Vertex(gid={self.gid}, labels={self.labels}, deleted={self.deleted})"


def adj_map_add(vertex: "Vertex", side: str, entry: tuple) -> None:
    """Mirror an adjacency-list append into the vertex's lazy adjacency map
    (no-op while the map is unbuilt). Caller holds vertex.lock."""
    adj = vertex.adj_in if side == "in" else vertex.adj_out
    if adj is not None:
        adj.setdefault(entry[1].gid, []).append(entry)


def adj_map_remove(vertex: "Vertex", side: str, entry: tuple) -> None:
    """Mirror an adjacency-list removal. Caller holds vertex.lock."""
    adj = vertex.adj_in if side == "in" else vertex.adj_out
    if adj is None:
        return
    bucket = adj.get(entry[1].gid)
    if bucket is None:
        return
    try:
        bucket.remove(entry)
    except ValueError:
        pass
    if not bucket:
        del adj[entry[1].gid]


def adj_map_build(vertex: "Vertex", side: str) -> dict:
    """Build (and install) the adjacency map from the live adjacency list.
    Caller holds vertex.lock."""
    adj: dict = {}
    entries = vertex.in_edges if side == "in" else vertex.out_edges
    for entry in entries:
        adj.setdefault(entry[1].gid, []).append(entry)
    if side == "in":
        vertex.adj_in = adj
    else:
        vertex.adj_out = adj
    return adj


class Edge:
    __slots__ = ("gid", "edge_type", "from_vertex", "to_vertex", "properties",
                 "deleted", "delta", "lock")

    def __init__(self, gid: int, edge_type: int, from_vertex: Vertex,
                 to_vertex: Vertex, delta: Optional[Delta] = None) -> None:
        self.gid = gid
        self.edge_type = edge_type
        self.from_vertex = from_vertex
        self.to_vertex = to_vertex
        self.properties: dict[int, object] = {}
        self.deleted = False
        self.delta = delta
        self.lock = threading.Lock()

    def __repr__(self) -> str:  # pragma: no cover
        return (f"Edge(gid={self.gid}, type={self.edge_type}, "
                f"{self.from_vertex.gid}->{self.to_vertex.gid})")
