"""In-memory vertex and edge records.

The reference packs Vertex into 80 bytes with small_vectors and a tagged delta
pointer (storage/v2/vertex.hpp:32-73). In the Python host layer we keep the
same *shape* — gid, labels, properties, adjacency, delta head, per-object
lock — with __slots__ for density. Adjacency entries are
(edge_type_id, other_vertex, edge) triples, mirroring the reference's
(EdgeType, Vertex*, EdgeRef) tuples so edge objects are only touched when
edge properties are needed.
"""

from __future__ import annotations

import threading
from typing import Optional

from .delta import Delta


class Vertex:
    __slots__ = ("gid", "labels", "properties", "in_edges", "out_edges",
                 "deleted", "delta", "lock")

    def __init__(self, gid: int, delta: Optional[Delta] = None) -> None:
        self.gid = gid
        self.labels: set[int] = set()
        self.properties: dict[int, object] = {}
        # entries: (edge_type_id, other_vertex, edge)
        self.in_edges: list[tuple] = []
        self.out_edges: list[tuple] = []
        self.deleted = False
        self.delta = delta
        self.lock = threading.Lock()

    def __repr__(self) -> str:  # pragma: no cover
        return f"Vertex(gid={self.gid}, labels={self.labels}, deleted={self.deleted})"


class Edge:
    __slots__ = ("gid", "edge_type", "from_vertex", "to_vertex", "properties",
                 "deleted", "delta", "lock")

    def __init__(self, gid: int, edge_type: int, from_vertex: Vertex,
                 to_vertex: Vertex, delta: Optional[Delta] = None) -> None:
        self.gid = gid
        self.edge_type = edge_type
        self.from_vertex = from_vertex
        self.to_vertex = to_vertex
        self.properties: dict[int, object] = {}
        self.deleted = False
        self.delta = delta
        self.lock = threading.Lock()

    def __repr__(self) -> str:  # pragma: no cover
        return (f"Edge(gid={self.gid}, type={self.edge_type}, "
                f"{self.from_vertex.gid}->{self.to_vertex.gid})")
