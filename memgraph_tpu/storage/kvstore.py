"""Durable key-value store for metadata.

Counterpart of the reference's RocksDB-backed kvstore
(/root/reference/src/kvstore/kvstore.hpp): durable string->bytes map used
by auth, settings, trigger and stream metadata. Backed by sqlite3 (stdlib;
the RocksDB-class dependency this environment doesn't ship) with WAL mode.
"""

from __future__ import annotations

import os
import sqlite3
from typing import Iterator, Optional

from ..utils.locks import tracked_lock


class KVStore:
    def __init__(self, path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._lock = tracked_lock("KVStore._lock")
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv (k TEXT PRIMARY KEY, v BLOB)")
        self._conn.commit()

    def put(self, key: str, value: bytes | str) -> None:
        from ..utils import faultinject as FI
        FI.fire("kvstore.put")
        if isinstance(value, str):
            value = value.encode("utf-8")
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?) "
                "ON CONFLICT(k) DO UPDATE SET v = excluded.v", (key, value))
            self._conn.commit()

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return bytes(row[0]) if row else None

    def get_str(self, key: str) -> Optional[str]:
        raw = self.get(key)
        return raw.decode("utf-8") if raw is not None else None

    def delete(self, key: str) -> bool:
        with self._lock:
            cur = self._conn.execute("DELETE FROM kv WHERE k = ?", (key,))
            self._conn.commit()
            return cur.rowcount > 0

    def items_with_prefix(self, prefix: str) -> Iterator[tuple[str, bytes]]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT k, v FROM kv WHERE k LIKE ? ORDER BY k",
                (prefix + "%",)).fetchall()
        for k, v in rows:
            yield k, bytes(v)

    def delete_prefix(self, prefix: str) -> int:
        with self._lock:
            cur = self._conn.execute("DELETE FROM kv WHERE k LIKE ?",
                                     (prefix + "%",))
            self._conn.commit()
            return cur.rowcount

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class Settings:
    """Durable runtime settings (reference: utils/settings.hpp +
    flags/run_time_configurable.cpp) with change observers."""

    def __init__(self, kvstore: Optional[KVStore] = None) -> None:
        self._kv = kvstore
        self._cache: dict[str, str] = {}
        self._observers: dict[str, list] = {}
        if kvstore is not None:
            for key, value in kvstore.items_with_prefix("setting:"):
                self._cache[key[len("setting:"):]] = value.decode("utf-8")

    def set(self, name: str, value: str) -> None:
        self._cache[name] = value
        if self._kv is not None:
            self._kv.put(f"setting:{name}", value)
        for fn in self._observers.get(name, []):
            fn(value)

    def get(self, name: str, default: Optional[str] = None) -> Optional[str]:
        return self._cache.get(name, default)

    def all(self) -> dict[str, str]:
        return dict(self._cache)

    def observe(self, name: str, fn) -> None:
        self._observers.setdefault(name, []).append(fn)


def ensure_settings(ictx) -> "Settings":
    """The one place that lazily attaches the runtime Settings store to
    an interpreter context (shared by the interpreter's SET DATABASE
    SETTING path and main.py's license wiring)."""
    settings = getattr(ictx, "settings", None)
    if settings is None:
        settings = ictx.settings = Settings(getattr(ictx, "kvstore", None))
    return settings
