"""Compact binary encoding for property values.

Role parity with the reference's PropertyStore
(storage/v2/property_store.cpp — custom little-endian encoding with small
inline buffers): a self-describing, compact, deterministic binary codec for
all supported value types. In this build the in-memory representation stays
native Python dicts (the host hot path), and this codec is the durability and
replication wire format for properties (snapshots, WAL deltas) plus the
content-addressable form used for unique-constraint keys.

Format: each value is [1-byte tag][payload]. Integers use zig-zag varints;
strings/bytes are length-prefixed UTF-8; lists/maps are count-prefixed;
temporal types encode as their microsecond payloads; maps encode string keys.
A property *set* encodes as varint(count) then (varint(prop_id), value)*
sorted by prop_id — deterministic for hashing.
"""

from __future__ import annotations

import struct
from io import BytesIO

from ..exceptions import StorageError
from ..utils.point import CrsType, Point
from ..utils.temporal import (Date, Duration, LocalDateTime, LocalTime,
                              ZonedDateTime)

# value tags
T_NULL = 0x00
T_FALSE = 0x01
T_TRUE = 0x02
T_INT = 0x03
T_DOUBLE = 0x04
T_STRING = 0x05
T_LIST = 0x06
T_MAP = 0x07
T_DATE = 0x08
T_LOCAL_TIME = 0x09
T_LOCAL_DATETIME = 0x0A
T_DURATION = 0x0B
T_ZONED_DATETIME = 0x0C
T_POINT = 0x0D
T_BYTES = 0x0E
T_ENUM = 0x0F


def _write_varint(buf: BytesIO, n: int) -> None:
    if n < 0:
        raise StorageError("varint must be non-negative")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes((b | 0x80,)))
        else:
            buf.write(bytes((b,)))
            return


def _read_varint(buf: BytesIO) -> int:
    shift = 0
    result = 0
    while True:
        raw = buf.read(1)
        if not raw:
            raise StorageError("truncated varint")
        b = raw[0]
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result
        shift += 7


def _read_exact(buf: BytesIO, n: int) -> bytes:
    """Short reads mean a torn/corrupt blob — surface StorageError, never
    a silently-shortened value (found by the truncation fuzzer)."""
    raw = buf.read(n)
    if len(raw) != n:
        raise StorageError(
            f"truncated value payload: wanted {n} bytes, got {len(raw)}")
    return raw


def _big_zigzag(n: int) -> int:
    # zig-zag over unbounded Python ints: non-negatives → even, negatives → odd
    return (n << 1) if n >= 0 else ((-n << 1) - 1)


def _unzigzag(n: int) -> int:
    return (n >> 1) if not n & 1 else -((n + 1) >> 1)


def _is_enum(v) -> bool:
    from .enums import EnumValue
    return isinstance(v, EnumValue)


def encode_value(buf: BytesIO, v) -> None:
    if v is None:
        buf.write(bytes((T_NULL,)))
    elif v is True:
        buf.write(bytes((T_TRUE,)))
    elif v is False:
        buf.write(bytes((T_FALSE,)))
    elif isinstance(v, int):
        buf.write(bytes((T_INT,)))
        _write_varint(buf, _big_zigzag(v))
    elif isinstance(v, float):
        buf.write(bytes((T_DOUBLE,)))
        buf.write(struct.pack("<d", v))
    elif isinstance(v, str):
        raw = v.encode("utf-8")
        buf.write(bytes((T_STRING,)))
        _write_varint(buf, len(raw))
        buf.write(raw)
    elif isinstance(v, bytes):
        buf.write(bytes((T_BYTES,)))
        _write_varint(buf, len(v))
        buf.write(v)
    elif isinstance(v, (list, tuple)):
        buf.write(bytes((T_LIST,)))
        _write_varint(buf, len(v))
        for item in v:
            encode_value(buf, item)
    elif isinstance(v, dict):
        buf.write(bytes((T_MAP,)))
        _write_varint(buf, len(v))
        for k in sorted(v):
            if not isinstance(k, str):
                raise StorageError("map property keys must be strings")
            raw = k.encode("utf-8")
            _write_varint(buf, len(raw))
            buf.write(raw)
            encode_value(buf, v[k])
    elif isinstance(v, Date):
        buf.write(bytes((T_DATE,)))
        _write_varint(buf, _big_zigzag(v.d.toordinal()))
    elif isinstance(v, LocalTime):
        buf.write(bytes((T_LOCAL_TIME,)))
        _write_varint(buf, v._micros())
    elif isinstance(v, LocalDateTime):
        buf.write(bytes((T_LOCAL_DATETIME,)))
        _write_varint(buf, _big_zigzag(v.timestamp_micros()))
    elif isinstance(v, Duration):
        buf.write(bytes((T_DURATION,)))
        _write_varint(buf, _big_zigzag(v.micros))
    elif isinstance(v, ZonedDateTime):
        buf.write(bytes((T_ZONED_DATETIME,)))
        _write_varint(buf, _big_zigzag(v.timestamp_micros()))
        tz = v.timezone_name().encode("utf-8")
        _write_varint(buf, len(tz))
        buf.write(tz)
    elif _is_enum(v):
        buf.write(bytes((T_ENUM,)))
        for part in (v.enum_name, v.value_name):
            raw = part.encode("utf-8")
            _write_varint(buf, len(raw))
            buf.write(raw)
        _write_varint(buf, v.position)
    elif isinstance(v, Point):
        buf.write(bytes((T_POINT,)))
        _write_varint(buf, v.crs.value)
        buf.write(struct.pack("<d", v.x))
        buf.write(struct.pack("<d", v.y))
        if v.crs.dims == 3:
            buf.write(struct.pack("<d", v.z))
    else:
        raise StorageError(f"unsupported property value type: {type(v)!r}")


def decode_value(buf: BytesIO):
    raw = buf.read(1)
    if not raw:
        raise StorageError("truncated value")
    tag = raw[0]
    if tag == T_NULL:
        return None
    if tag == T_TRUE:
        return True
    if tag == T_FALSE:
        return False
    if tag == T_INT:
        return _unzigzag(_read_varint(buf))
    if tag == T_DOUBLE:
        return struct.unpack("<d", _read_exact(buf, 8))[0]
    if tag == T_STRING:
        n = _read_varint(buf)
        return _read_exact(buf, n).decode("utf-8")
    if tag == T_BYTES:
        n = _read_varint(buf)
        return _read_exact(buf, n)
    if tag == T_LIST:
        n = _read_varint(buf)
        return [decode_value(buf) for _ in range(n)]
    if tag == T_MAP:
        n = _read_varint(buf)
        out = {}
        for _ in range(n):
            klen = _read_varint(buf)
            key = _read_exact(buf, klen).decode("utf-8")
            out[key] = decode_value(buf)
        return out
    if tag == T_DATE:
        import datetime as _dt
        return Date(_dt.date.fromordinal(_unzigzag(_read_varint(buf))))
    if tag == T_LOCAL_TIME:
        from ..utils.temporal import _micros_to_time
        return LocalTime(_micros_to_time(_read_varint(buf)))
    if tag == T_LOCAL_DATETIME:
        import datetime as _dt
        micros = _unzigzag(_read_varint(buf))
        return LocalDateTime(_dt.datetime(1970, 1, 1)
                             + _dt.timedelta(microseconds=micros))
    if tag == T_DURATION:
        return Duration(_unzigzag(_read_varint(buf)))
    if tag == T_ZONED_DATETIME:
        import datetime as _dt
        micros = _unzigzag(_read_varint(buf))
        tzlen = _read_varint(buf)
        tzname = _read_exact(buf, tzlen).decode("utf-8")
        dt = _dt.datetime.fromtimestamp(micros / 1_000_000, _dt.timezone.utc)
        try:
            from zoneinfo import ZoneInfo
            dt = dt.astimezone(ZoneInfo(tzname))
        except (ImportError, KeyError, ValueError, OSError):
            pass  # unknown/unavailable tz db: keep UTC instant
        return ZonedDateTime(dt)
    if tag == T_ENUM:
        from .enums import EnumValue
        enum_name = _read_exact(buf, _read_varint(buf)).decode("utf-8")
        value_name = _read_exact(buf, _read_varint(buf)).decode("utf-8")
        position = _read_varint(buf)
        return EnumValue(enum_name, value_name, position)
    if tag == T_POINT:
        crs = CrsType(_read_varint(buf))
        x = struct.unpack("<d", _read_exact(buf, 8))[0]
        y = struct.unpack("<d", _read_exact(buf, 8))[0]
        z = struct.unpack("<d", _read_exact(buf, 8))[0] \
            if crs.dims == 3 else None
        return Point(x, y, z, crs)
    raise StorageError(f"unknown value tag 0x{tag:02x}")


# Flag-driven blob compression (reference: property_store.hpp:38-40 +
# utils/compressor.cpp — zlib, gated by
# --storage-property-store-compression-enabled). Set by main.py; the
# decoder auto-detects, so mixed-config blobs always read correctly.
COMPRESSION = {"enabled": False, "level": 6, "min_bytes": 64}

# envelope marker: a legacy blob starts with a varint property count, and
# the only legal single-byte blob starting 0x00 is the 1-byte empty set —
# so "0x00 + more bytes" is free to mean "zlib payload follows"
_COMPRESSED_MARK = b"\x00"


def encode_properties(props: dict[int, object]) -> bytes:
    """Deterministically encode a {prop_id: value} set. When compression
    is enabled, blobs over min_bytes are zlib-wrapped (marker 0x00)."""
    buf = BytesIO()
    _write_varint(buf, len(props))
    for pid in sorted(props):
        _write_varint(buf, pid)
        encode_value(buf, props[pid])
    raw = buf.getvalue()
    if COMPRESSION["enabled"] and len(raw) >= COMPRESSION["min_bytes"]:
        import zlib
        packed = _COMPRESSED_MARK + zlib.compress(raw, COMPRESSION["level"])
        if len(packed) < len(raw):
            return packed
    return raw


def decode_properties(data: bytes) -> dict[int, object]:
    if len(data) > 1 and data[:1] == _COMPRESSED_MARK:
        import zlib
        try:
            data = zlib.decompress(data[1:])
        except zlib.error as e:
            raise StorageError(f"corrupt compressed property blob: {e}") \
                from e
    buf = BytesIO(data)
    try:
        n = _read_varint(buf)
        out = {}
        for _ in range(n):
            pid = _read_varint(buf)
            out[pid] = decode_value(buf)
        return out
    except (struct.error, UnicodeDecodeError, ValueError,
            OverflowError) as e:
        # torn/corrupt blob (truncated payload, invalid utf-8, unknown
        # CRS id, out-of-range temporal): surface the domain error, not
        # the codec internals (found by the property fuzzers)
        raise StorageError(f"corrupt property blob: {e}") from e


def value_key(v) -> bytes:
    """Canonical bytes for a single value (unique-constraint keys)."""
    buf = BytesIO()
    encode_value(buf, v)
    return buf.getvalue()
