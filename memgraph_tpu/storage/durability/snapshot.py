"""Binary snapshots of the full storage state.

Counterpart of the reference's sectioned snapshot format
(/root/reference/src/storage/v2/durability/snapshot.cpp, marker.hpp):
magic + version header, interning tables, vertices, edges, index +
constraint metadata, all encoded with the property codec. Snapshots are
written atomically (tmp + rename) into <durability_dir>/snapshots.

Format v2 chunks the vertex and edge sections (varint chunk count, then
per chunk varint byte-length + varint item-count + payload) so create
and load pipeline each chunk through a worker pool — the same
parallel-durability shape as the reference's threaded snapshot writers
(memgraph.cpp:531-534 --storage-parallel-schema-recovery). v1 files
remain readable.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from io import BytesIO

from ...exceptions import DurabilityError
from ..property_store import (_read_varint, _write_varint, decode_value,
                              encode_value)

MAGIC = b"MGTPUSNAP"
VERSION = 3   # v3: per-chunk flag byte (bit0 = zlib payload)
CHUNK_ITEMS = 50_000

_POOL: ThreadPoolExecutor | None = None
_POOL_LOCK = threading.Lock()


POOL_WORKERS = 0          # 0 = cpu count (--storage-snapshot-thread-count)


def _pool() -> ThreadPoolExecutor:
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ThreadPoolExecutor(
                max_workers=POOL_WORKERS or max(2, (os.cpu_count() or 2)),
                thread_name_prefix="snapshot-worker")
        return _POOL


def _reset_pool_after_fork() -> None:
    """fork() copies the executor OBJECT but none of its worker
    threads: a forked child (mp_executor / mgshard workers) that
    inherited a live pool would submit snapshot chunks no thread will
    ever run and hang forever. Drop the carcass so the child lazily
    builds its own pool on first use."""
    global _POOL
    _POOL = None
    # the lock may have been held by a parent thread at fork time;
    # replace it rather than inherit a permanently-locked instance
    globals()["_POOL_LOCK"] = threading.Lock()


os.register_at_fork(after_in_child=_reset_pool_after_fork)

# section markers
SEC_MAPPERS = 0x01
SEC_VERTICES = 0x02
SEC_EDGES = 0x03
SEC_INDICES = 0x04
SEC_CONSTRAINTS = 0x05
SEC_STREAM_OFFSETS = 0x06
SEC_END = 0xFF


def _encode_vertex_chunk(items) -> bytes:
    from ...storage.common import View
    buf = BytesIO()
    for va in items:
        _write_varint(buf, va.gid)
        labels = va.labels(View.OLD)
        _write_varint(buf, len(labels))
        for l in labels:
            _write_varint(buf, l)
        props = va.properties(View.OLD)
        _write_varint(buf, len(props))
        for pid in sorted(props):
            _write_varint(buf, pid)
            encode_value(buf, props[pid])
    return buf.getvalue()


def _encode_edge_chunk(items) -> bytes:
    from ...storage.common import View
    buf = BytesIO()
    for ea in items:
        _write_varint(buf, ea.gid)
        _write_varint(buf, ea.edge_type)
        _write_varint(buf, ea.from_vertex().gid)
        _write_varint(buf, ea.to_vertex().gid)
        props = ea.properties(View.OLD)
        _write_varint(buf, len(props))
        for pid in sorted(props):
            _write_varint(buf, pid)
            encode_value(buf, props[pid])
    return buf.getvalue()


def _write_chunked(buf, items, encode_chunk) -> None:
    chunks = [items[i:i + CHUNK_ITEMS]
              for i in range(0, len(items), CHUNK_ITEMS)] or [[]]
    payloads = list(_pool().map(encode_chunk, chunks))
    _write_varint(buf, len(chunks))
    from ..property_store import COMPRESSION
    for chunk, payload in zip(chunks, payloads):
        flags = 0
        if COMPRESSION["enabled"] and len(payload) >= 512:
            import zlib
            packed = zlib.compress(payload, COMPRESSION["level"])
            if len(packed) < len(payload):
                payload, flags = packed, 1
        _write_varint(buf, len(payload))
        _write_varint(buf, len(chunk))
        buf.write(bytes([flags]))
        buf.write(payload)


def _read_chunked(buf, decode_chunk, version=VERSION) -> list:
    n_chunks = _read_varint(buf)
    raw = []
    for _ in range(n_chunks):
        nbytes = _read_varint(buf)
        count = _read_varint(buf)
        flags = buf.read(1)[0] if version >= 3 else 0
        payload = buf.read(nbytes)
        if flags & 1:
            import zlib
            payload = zlib.decompress(payload)
        raw.append((payload, count))
    out: list = []
    for part in _pool().map(lambda rc: decode_chunk(*rc), raw):
        out.extend(part)
    return out


def _decode_vertex_chunk(payload: bytes, count: int) -> list:
    buf = BytesIO(payload)
    return [_decode_v1_vertex(buf) for _ in range(count)]


def _decode_edge_chunk(payload: bytes, count: int) -> list:
    buf = BytesIO(payload)
    return [_decode_v1_edge(buf) for _ in range(count)]


def _decode_v1_vertex(buf):
    gid = _read_varint(buf)
    labels = [_read_varint(buf) for _ in range(_read_varint(buf))]
    props = {}
    for _ in range(_read_varint(buf)):
        pid = _read_varint(buf)
        props[pid] = decode_value(buf)
    return (gid, labels, props)


def _decode_v1_edge(buf):
    gid = _read_varint(buf)
    etype = _read_varint(buf)
    from_gid = _read_varint(buf)
    to_gid = _read_varint(buf)
    props = {}
    for _ in range(_read_varint(buf)):
        pid = _read_varint(buf)
        props[pid] = decode_value(buf)
    return (gid, etype, from_gid, to_gid, props)


def snapshot_dir(storage) -> str:
    base = storage.config.durability_dir
    if not base:
        raise DurabilityError("durability_dir is not configured")
    path = os.path.join(base, "snapshots")
    os.makedirs(path, exist_ok=True)
    return path


def create_snapshot(storage) -> str:
    """Write a consistent snapshot; returns its path.

    Consistency: takes the engine lock to pin a commit timestamp, then
    reads settled state (the storage-level accessor guarantees no
    concurrent DDL; concurrent txn writes carry uncommitted deltas which
    are skipped via the delta==None fast path or materialized as OLD).
    """
    # direct Accessor construction: access() is gated for SUSPENDED
    # databases, but the suspend path itself snapshots through here
    from ..storage import Accessor
    acc = Accessor(storage, storage.config.isolation_level)
    try:
        ts = acc.txn.start_ts
        buf = BytesIO()
        buf.write(MAGIC)
        buf.write(struct.pack("<HQQ", VERSION, ts, int(time.time())))

        # mappers
        buf.write(bytes((SEC_MAPPERS,)))
        for mapper in (storage.label_mapper, storage.property_mapper,
                       storage.edge_type_mapper):
            names = mapper.to_list()
            _write_varint(buf, len(names))
            for name in names:
                raw = name.encode("utf-8")
                _write_varint(buf, len(raw))
                buf.write(raw)

        # vertices + edges: chunked, encoded in parallel on the pool
        from ...storage.common import View
        vertices = list(acc.vertices(View.OLD))
        buf.write(bytes((SEC_VERTICES,)))
        _write_chunked(buf, vertices, _encode_vertex_chunk)

        edges = list(acc.edges(View.OLD))
        buf.write(bytes((SEC_EDGES,)))
        _write_chunked(buf, edges, _encode_edge_chunk)

        # indices
        buf.write(bytes((SEC_INDICES,)))
        label_idx = storage.indices.label.labels()
        _write_varint(buf, len(label_idx))
        for lid in label_idx:
            _write_varint(buf, lid)
        lp_idx = storage.indices.label_property.keys()
        _write_varint(buf, len(lp_idx))
        for (lid, pids) in lp_idx:
            _write_varint(buf, lid)
            _write_varint(buf, len(pids))
            for p in pids:
                _write_varint(buf, p)
        et_idx = storage.indices.edge_type.types()
        _write_varint(buf, len(et_idx))
        for tid in et_idx:
            _write_varint(buf, tid)

        # constraints
        buf.write(bytes((SEC_CONSTRAINTS,)))
        existence = storage.constraints.existence.all()
        _write_varint(buf, len(existence))
        for (lid, pid) in existence:
            _write_varint(buf, lid)
            _write_varint(buf, pid)
        unique = storage.constraints.unique.all()
        _write_varint(buf, len(unique))
        for (lid, pids) in unique:
            _write_varint(buf, lid)
            _write_varint(buf, len(pids))
            for p in pids:
                _write_varint(buf, p)
        typec = storage.constraints.type.all()
        _write_varint(buf, len(typec))
        for (lid, pid, tname) in typec:
            _write_varint(buf, lid)
            _write_varint(buf, pid)
            raw = tname.encode("utf-8")
            _write_varint(buf, len(raw))
            buf.write(raw)

        # stream-offset table: the WAL segments holding OP_STREAM_OFFSET
        # records are pruned once this snapshot covers them, so the
        # snapshot must carry the offsets itself
        offsets = dict(storage.stream_offsets)
        buf.write(bytes((SEC_STREAM_OFFSETS,)))
        _write_varint(buf, len(offsets))
        for name in sorted(offsets):
            raw = name.encode("utf-8")
            _write_varint(buf, len(raw))
            buf.write(raw)
            pos = json.dumps(offsets[name], sort_keys=True).encode("utf-8")
            _write_varint(buf, len(pos))
            buf.write(pos)

        buf.write(bytes((SEC_END,)))
        data = buf.getvalue()
    finally:
        acc.abort()

    # atomic publish: tmp write + fsync + rename + directory fsync — a
    # crash at any point leaves either the old snapshot set or the new
    # one, never a half-written "latest"
    path = os.path.join(snapshot_dir(storage),
                        f"snapshot_{int(time.time() * 1e6)}_{ts}.mgsnap")
    tmp = f"{path}.{os.getpid()}.tmp"
    from ...utils import faultinject as FI
    from . import wal as W
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        FI.fire("snapshot.rename")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    W.fsync_dir(snapshot_dir(storage))
    _apply_retention(storage,
                     keep=getattr(storage.config,
                                  'snapshot_retention_count', 3))
    # WAL retention rides the snapshot cadence: segments fully covered by
    # this snapshot will never be replayed again
    wal_file = getattr(storage, "wal_file", None)
    W.prune_wal_segments(storage, ts,
                         active_path=wal_file.path if wal_file else None)
    return path


def _apply_retention(storage, keep: int = 3) -> None:
    keep = max(1, keep)          # snaps[:-0] would retain EVERYTHING
    d = snapshot_dir(storage)
    snaps = sorted(p for p in os.listdir(d) if p.endswith(".mgsnap"))
    for old in snaps[:-keep]:
        try:
            os.remove(os.path.join(d, old))
        except OSError:
            pass


def list_snapshots(storage):
    d = snapshot_dir(storage)
    out = []
    for p in sorted(os.listdir(d)):
        if p.endswith(".mgsnap"):
            full = os.path.join(d, p)
            out.append((full, os.path.getmtime(full)))
    return out


def load_snapshot(path: str) -> dict:
    """Parse a snapshot file into a plain dict (applied by recovery)."""
    with open(path, "rb") as f:
        data = f.read()
    buf = BytesIO(data)
    if buf.read(len(MAGIC)) != MAGIC:
        raise DurabilityError(f"{path}: bad snapshot magic")
    version, ts, wall = struct.unpack("<HQQ", buf.read(18))
    if version not in (1, 2, 3):
        raise DurabilityError(f"{path}: unsupported snapshot version "
                              f"{version}")
    out = {"timestamp": ts, "wall_time": wall}

    def read_name_list():
        n = _read_varint(buf)
        return [buf.read(_read_varint(buf)).decode("utf-8")
                for _ in range(n)]

    while True:
        marker = buf.read(1)[0]
        if marker == SEC_END:
            break
        if marker == SEC_MAPPERS:
            out["labels"] = read_name_list()
            out["properties"] = read_name_list()
            out["edge_types"] = read_name_list()
        elif marker == SEC_VERTICES:
            if version >= 2:
                out["vertices"] = _read_chunked(buf, _decode_vertex_chunk, version)
            else:
                n = _read_varint(buf)
                out["vertices"] = [_decode_v1_vertex(buf)
                                   for _ in range(n)]
        elif marker == SEC_EDGES:
            if version >= 2:
                out["edges"] = _read_chunked(buf, _decode_edge_chunk, version)
            else:
                n = _read_varint(buf)
                out["edges"] = [_decode_v1_edge(buf) for _ in range(n)]
        elif marker == SEC_INDICES:
            out["label_indices"] = [_read_varint(buf)
                                    for _ in range(_read_varint(buf))]
            lp = []
            for _ in range(_read_varint(buf)):
                lid = _read_varint(buf)
                pids = tuple(_read_varint(buf)
                             for _ in range(_read_varint(buf)))
                lp.append((lid, pids))
            out["label_property_indices"] = lp
            out["edge_type_indices"] = [_read_varint(buf)
                                        for _ in range(_read_varint(buf))]
        elif marker == SEC_CONSTRAINTS:
            out["existence_constraints"] = [
                (_read_varint(buf), _read_varint(buf))
                for _ in range(_read_varint(buf))]
            uq = []
            for _ in range(_read_varint(buf)):
                lid = _read_varint(buf)
                pids = tuple(_read_varint(buf)
                             for _ in range(_read_varint(buf)))
                uq.append((lid, pids))
            out["unique_constraints"] = uq
            tc = []
            for _ in range(_read_varint(buf)):
                lid = _read_varint(buf)
                pid = _read_varint(buf)
                tname = buf.read(_read_varint(buf)).decode("utf-8")
                tc.append((lid, pid, tname))
            out["type_constraints"] = tc
        elif marker == SEC_STREAM_OFFSETS:
            offsets = {}
            for _ in range(_read_varint(buf)):
                name = buf.read(_read_varint(buf)).decode("utf-8")
                offsets[name] = json.loads(
                    buf.read(_read_varint(buf)).decode("utf-8"))
            out["stream_offsets"] = offsets
        else:
            raise DurabilityError(f"{path}: unknown section 0x{marker:02x}")
    return out
