"""Binary snapshots of the full storage state.

Counterpart of the reference's sectioned snapshot format
(/root/reference/src/storage/v2/durability/snapshot.cpp, marker.hpp):
magic + version header, interning tables, vertices, edges, index +
constraint metadata, all encoded with the property codec. Snapshots are
written atomically (tmp + rename) into <durability_dir>/snapshots.
"""

from __future__ import annotations

import os
import struct
import time
from io import BytesIO

from ...exceptions import DurabilityError
from ..property_store import (_read_varint, _write_varint, decode_value,
                              encode_value)

MAGIC = b"MGTPUSNAP"
VERSION = 1

# section markers
SEC_MAPPERS = 0x01
SEC_VERTICES = 0x02
SEC_EDGES = 0x03
SEC_INDICES = 0x04
SEC_CONSTRAINTS = 0x05
SEC_END = 0xFF


def snapshot_dir(storage) -> str:
    base = storage.config.durability_dir
    if not base:
        raise DurabilityError("durability_dir is not configured")
    path = os.path.join(base, "snapshots")
    os.makedirs(path, exist_ok=True)
    return path


def create_snapshot(storage) -> str:
    """Write a consistent snapshot; returns its path.

    Consistency: takes the engine lock to pin a commit timestamp, then
    reads settled state (the storage-level accessor guarantees no
    concurrent DDL; concurrent txn writes carry uncommitted deltas which
    are skipped via the delta==None fast path or materialized as OLD).
    """
    acc = storage.access()
    try:
        ts = acc.txn.start_ts
        buf = BytesIO()
        buf.write(MAGIC)
        buf.write(struct.pack("<HQQ", VERSION, ts, int(time.time())))

        # mappers
        buf.write(bytes((SEC_MAPPERS,)))
        for mapper in (storage.label_mapper, storage.property_mapper,
                       storage.edge_type_mapper):
            names = mapper.to_list()
            _write_varint(buf, len(names))
            for name in names:
                raw = name.encode("utf-8")
                _write_varint(buf, len(raw))
                buf.write(raw)

        # vertices
        from ...storage.common import View
        vertices = list(acc.vertices(View.OLD))
        buf.write(bytes((SEC_VERTICES,)))
        _write_varint(buf, len(vertices))
        for va in vertices:
            _write_varint(buf, va.gid)
            labels = va.labels(View.OLD)
            _write_varint(buf, len(labels))
            for l in labels:
                _write_varint(buf, l)
            props = va.properties(View.OLD)
            _write_varint(buf, len(props))
            for pid in sorted(props):
                _write_varint(buf, pid)
                encode_value(buf, props[pid])

        # edges
        edges = list(acc.edges(View.OLD))
        buf.write(bytes((SEC_EDGES,)))
        _write_varint(buf, len(edges))
        for ea in edges:
            _write_varint(buf, ea.gid)
            _write_varint(buf, ea.edge_type)
            _write_varint(buf, ea.from_vertex().gid)
            _write_varint(buf, ea.to_vertex().gid)
            props = ea.properties(View.OLD)
            _write_varint(buf, len(props))
            for pid in sorted(props):
                _write_varint(buf, pid)
                encode_value(buf, props[pid])

        # indices
        buf.write(bytes((SEC_INDICES,)))
        label_idx = storage.indices.label.labels()
        _write_varint(buf, len(label_idx))
        for lid in label_idx:
            _write_varint(buf, lid)
        lp_idx = storage.indices.label_property.keys()
        _write_varint(buf, len(lp_idx))
        for (lid, pids) in lp_idx:
            _write_varint(buf, lid)
            _write_varint(buf, len(pids))
            for p in pids:
                _write_varint(buf, p)
        et_idx = storage.indices.edge_type.types()
        _write_varint(buf, len(et_idx))
        for tid in et_idx:
            _write_varint(buf, tid)

        # constraints
        buf.write(bytes((SEC_CONSTRAINTS,)))
        existence = storage.constraints.existence.all()
        _write_varint(buf, len(existence))
        for (lid, pid) in existence:
            _write_varint(buf, lid)
            _write_varint(buf, pid)
        unique = storage.constraints.unique.all()
        _write_varint(buf, len(unique))
        for (lid, pids) in unique:
            _write_varint(buf, lid)
            _write_varint(buf, len(pids))
            for p in pids:
                _write_varint(buf, p)
        typec = storage.constraints.type.all()
        _write_varint(buf, len(typec))
        for (lid, pid, tname) in typec:
            _write_varint(buf, lid)
            _write_varint(buf, pid)
            raw = tname.encode("utf-8")
            _write_varint(buf, len(raw))
            buf.write(raw)

        buf.write(bytes((SEC_END,)))
        data = buf.getvalue()
    finally:
        acc.abort()

    path = os.path.join(snapshot_dir(storage),
                        f"snapshot_{int(time.time() * 1e6)}_{ts}.mgsnap")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _apply_retention(storage)
    return path


def _apply_retention(storage, keep: int = 3) -> None:
    d = snapshot_dir(storage)
    snaps = sorted(p for p in os.listdir(d) if p.endswith(".mgsnap"))
    for old in snaps[:-keep]:
        try:
            os.remove(os.path.join(d, old))
        except OSError:
            pass


def list_snapshots(storage):
    d = snapshot_dir(storage)
    out = []
    for p in sorted(os.listdir(d)):
        if p.endswith(".mgsnap"):
            full = os.path.join(d, p)
            out.append((full, os.path.getmtime(full)))
    return out


def load_snapshot(path: str) -> dict:
    """Parse a snapshot file into a plain dict (applied by recovery)."""
    with open(path, "rb") as f:
        data = f.read()
    buf = BytesIO(data)
    if buf.read(len(MAGIC)) != MAGIC:
        raise DurabilityError(f"{path}: bad snapshot magic")
    version, ts, wall = struct.unpack("<HQQ", buf.read(18))
    if version != VERSION:
        raise DurabilityError(f"{path}: unsupported snapshot version "
                              f"{version}")
    out = {"timestamp": ts, "wall_time": wall}

    def read_name_list():
        n = _read_varint(buf)
        return [buf.read(_read_varint(buf)).decode("utf-8")
                for _ in range(n)]

    while True:
        marker = buf.read(1)[0]
        if marker == SEC_END:
            break
        if marker == SEC_MAPPERS:
            out["labels"] = read_name_list()
            out["properties"] = read_name_list()
            out["edge_types"] = read_name_list()
        elif marker == SEC_VERTICES:
            n = _read_varint(buf)
            vertices = []
            for _ in range(n):
                gid = _read_varint(buf)
                labels = [_read_varint(buf)
                          for _ in range(_read_varint(buf))]
                props = {}
                for _ in range(_read_varint(buf)):
                    pid = _read_varint(buf)
                    props[pid] = decode_value(buf)
                vertices.append((gid, labels, props))
            out["vertices"] = vertices
        elif marker == SEC_EDGES:
            n = _read_varint(buf)
            edges = []
            for _ in range(n):
                gid = _read_varint(buf)
                etype = _read_varint(buf)
                from_gid = _read_varint(buf)
                to_gid = _read_varint(buf)
                props = {}
                for _ in range(_read_varint(buf)):
                    pid = _read_varint(buf)
                    props[pid] = decode_value(buf)
                edges.append((gid, etype, from_gid, to_gid, props))
            out["edges"] = edges
        elif marker == SEC_INDICES:
            out["label_indices"] = [_read_varint(buf)
                                    for _ in range(_read_varint(buf))]
            lp = []
            for _ in range(_read_varint(buf)):
                lid = _read_varint(buf)
                pids = tuple(_read_varint(buf)
                             for _ in range(_read_varint(buf)))
                lp.append((lid, pids))
            out["label_property_indices"] = lp
            out["edge_type_indices"] = [_read_varint(buf)
                                        for _ in range(_read_varint(buf))]
        elif marker == SEC_CONSTRAINTS:
            out["existence_constraints"] = [
                (_read_varint(buf), _read_varint(buf))
                for _ in range(_read_varint(buf))]
            uq = []
            for _ in range(_read_varint(buf)):
                lid = _read_varint(buf)
                pids = tuple(_read_varint(buf)
                             for _ in range(_read_varint(buf)))
                uq.append((lid, pids))
            out["unique_constraints"] = uq
            tc = []
            for _ in range(_read_varint(buf)):
                lid = _read_varint(buf)
                pid = _read_varint(buf)
                tname = buf.read(_read_varint(buf)).decode("utf-8")
                tc.append((lid, pid, tname))
            out["type_constraints"] = tc
        else:
            raise DurabilityError(f"{path}: unknown section 0x{marker:02x}")
    return out
