"""Recovery: latest snapshot + newer WAL transactions → storage state.

Counterpart of the reference's recovery orchestration
(/root/reference/src/storage/v2/durability/durability.cpp): pick the newest
loadable snapshot, rebuild objects/indexes/constraints, then replay WAL
transactions with commit_ts greater than the snapshot timestamp.
"""

from __future__ import annotations

import logging
import os
from io import BytesIO

from ...exceptions import DurabilityError
from ...utils.ids import NameIdMapper
from .snapshot import list_snapshots, load_snapshot
from . import wal as W
from ..property_store import _read_varint, decode_value

log = logging.getLogger(__name__)


def recover(storage) -> dict:
    """Full recovery into an (assumed empty) storage. Returns stats.

    WAL segments replay streamed (constant memory) in seqnum order; a
    damaged record truncates that segment's replay at the last complete
    transaction before it, and a hole in the segment chain refuses
    recovery outright (replaying around it would forge history)."""
    stats = {"snapshot": None, "wal_transactions": 0, "wal_corruption": []}
    snaps = list_snapshots(storage)
    snapshot_ts = 0
    if snaps:
        path = snaps[-1][0]
        data = load_snapshot(path)
        _apply_snapshot(storage, data)
        snapshot_ts = data["timestamp"]
        stats["snapshot"] = path
    segments = W.list_wal_segments(storage)
    W.check_segment_chain(segments)
    for wal_path, _seq in segments:
        def note(reason, offset, _p=wal_path):
            stats["wal_corruption"].append((_p, reason, offset))
        for commit_ts, ops in W.iter_wal_transactions(wal_path, note):
            if commit_ts <= snapshot_ts:
                continue
            _apply_wal_txn(storage, ops)
            stats["wal_transactions"] += 1
            with storage._engine_lock:
                storage._timestamp = max(storage._timestamp, commit_ts)
    storage._bump_topology()
    return stats


def recover_snapshot_from(storage, source: str) -> None:
    """RECOVER SNAPSHOT FROM "<uri>": load a snapshot from an explicit
    local path, http(s):// URL, or s3:// object (reference:
    storage/v2/inmemory/storage.hpp:158-168 remote snapshot load).

    The remote bytes are staged into the snapshots directory first
    (atomic rename), so a half-downloaded file is never loaded and the
    snapshot also becomes part of the local retention set."""
    import os
    import tempfile
    import time as _time
    from .snapshot import create_snapshot, snapshot_dir

    def _stage(reader, suffix="remote"):
        """Download to a tmp file, VALIDATE, only then rename into the
        snapshots dir — a corrupt download must never become the
        "latest" snapshot and poison every later recovery."""
        d = snapshot_dir(storage)
        final = os.path.join(
            d, f"snapshot_{int(_time.time() * 1e6)}_{suffix}.mgsnap")
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                while True:
                    chunk = reader(1 << 20)
                    if not chunk:
                        break
                    f.write(chunk)
                f.flush()
                os.fsync(f.fileno())
            staged = load_snapshot(tmp)      # raises on corrupt payload
            os.replace(tmp, final)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return final, staged

    if source.startswith(("http://", "https://")):
        import urllib.request
        from ...utils.retry import RetryPolicy

        def _download():
            with urllib.request.urlopen(source, timeout=60) as resp:
                return _stage(resp.read)

        try:
            # transient fetch failures (droppy link, restarting peer) get
            # a bounded backoff instead of failing the whole RECOVER
            path, data = RetryPolicy(
                base_delay=0.2, max_delay=5.0, max_retries=3).call(
                _download,
                on_retry=lambda attempt, e: log.warning(
                    "snapshot download from %s failed (attempt %d): %s — "
                    "retrying", source, attempt + 1, e))
        except OSError as e:   # URLError/HTTPError/timeouts subclass this
            raise DurabilityError(
                f"cannot fetch snapshot from {source!r}: {e}") from e
    elif source.startswith("s3://"):
        try:
            import boto3
        except ImportError as e:
            raise DurabilityError(
                "s3:// snapshot sources need the boto3 client library, "
                "which is not installed in this environment") from e
        bucket, _, key = source[len("s3://"):].partition("/")
        body = boto3.client("s3").get_object(Bucket=bucket,
                                             Key=key)["Body"]
        path, data = _stage(body.read)
    else:
        if not os.path.exists(source):
            raise DurabilityError(f"snapshot source {source!r} not found")
        data = load_snapshot(source)
    _clear_storage(storage)
    _apply_snapshot(storage, data)
    # NEW durability epoch: the local WAL predates the foreign snapshot
    # and must never replay on top of it at the next restart — advance
    # past every local WAL commit and persist a fresh local snapshot
    # that restart recovery will pick as the baseline
    max_wal_ts = 0
    for wal_path in W.list_wal_files(storage):
        try:
            for commit_ts, _ops in W.iter_wal_transactions(wal_path):
                max_wal_ts = max(max_wal_ts, commit_ts)
        except DurabilityError:
            pass
    with storage._engine_lock:
        storage._timestamp = max(storage._timestamp, max_wal_ts + 1)
    create_snapshot(storage)
    storage._bump_topology()


def recover_latest_snapshot(storage) -> None:
    """RECOVER SNAPSHOT query: wipe current state, load newest snapshot."""
    snaps = list_snapshots(storage)
    if not snaps:
        raise DurabilityError("no snapshots available")
    _clear_storage(storage)
    data = load_snapshot(snaps[-1][0])
    _apply_snapshot(storage, data)
    storage._bump_topology()


def _clear_storage(storage) -> None:
    storage._vertices.clear()
    storage._edges.clear()
    storage.stream_offsets.clear()
    from ..indexes import Indices
    from ..constraints import Constraints
    storage.indices = Indices()
    storage.constraints = Constraints()


def _apply_snapshot(storage, data: dict) -> None:
    storage.label_mapper = NameIdMapper.from_list(data.get("labels", []))
    storage.property_mapper = NameIdMapper.from_list(
        data.get("properties", []))
    storage.edge_type_mapper = NameIdMapper.from_list(
        data.get("edge_types", []))

    from ..objects import Edge, Vertex
    top_vgid = -1
    for (gid, labels, props) in data.get("vertices", []):
        v = Vertex(gid)
        v.labels = set(labels)
        v.properties = dict(props)
        storage._vertices[gid] = v
        top_vgid = max(top_vgid, gid)
    top_egid = -1
    for (gid, etype, from_gid, to_gid, props) in data.get("edges", []):
        from_v = storage._vertices.get(from_gid)
        to_v = storage._vertices.get(to_gid)
        if from_v is None or to_v is None:
            raise DurabilityError(
                f"edge {gid} references missing vertex")
        e = Edge(gid, etype, from_v, to_v)
        e.properties = dict(props)
        from_v.out_edges.append((etype, to_v, e))
        to_v.in_edges.append((etype, from_v, e))
        storage._edges[gid] = e
        top_egid = max(top_egid, gid)

    # snapshot apply also runs LIVE on replicas (remote-snapshot
    # catch-up) while readers hold storage accessors: the gid counters
    # and the visibility timestamp publish under their owning locks,
    # bumped once per snapshot rather than once per row
    with storage._gid_lock:
        storage._next_vertex_gid = max(storage._next_vertex_gid,
                                       top_vgid + 1)
        storage._next_edge_gid = max(storage._next_edge_gid,
                                     top_egid + 1)
    with storage._engine_lock:
        storage._timestamp = max(storage._timestamp,
                                 data["timestamp"] + 1)

    for lid in data.get("label_indices", []):
        storage.create_label_index(lid)
    for (lid, pids) in data.get("label_property_indices", []):
        storage.create_label_property_index(lid, pids)
    for tid in data.get("edge_type_indices", []):
        storage.create_edge_type_index(tid)
    for (lid, pid) in data.get("existence_constraints", []):
        storage.create_existence_constraint(lid, pid)
    for (lid, pids) in data.get("unique_constraints", []):
        storage.create_unique_constraint(lid, pids)
    for (lid, pid, tname) in data.get("type_constraints", []):
        storage.create_type_constraint(lid, pid, tname)
    # WAL segments older than the snapshot are pruned, so the snapshot
    # must carry the stream-offset table itself
    for name, position in (data.get("stream_offsets") or {}).items():
        storage.stream_offsets[name] = position


def _apply_batch_vertices(storage, vertices, changed) -> None:
    """Replay the vertex half of a BATCH_INSERT record with the same
    amortization as the live path: objects rebuilt row-by-row, indexes
    updated with one bulk merge per index."""
    from ..objects import Vertex
    fresh = []
    top_gid = -1
    for (gid, labels, props) in vertices:
        changed.add(gid)
        v = storage._vertices.get(gid)
        if v is None:
            v = Vertex(gid)
            storage._vertices[gid] = v
            top_gid = max(top_gid, gid)
        v.labels = set(labels)
        v.properties = dict(props)
        fresh.append(v)
    if top_gid >= 0:
        with storage._gid_lock:
            storage._next_vertex_gid = max(storage._next_vertex_gid,
                                           top_gid + 1)
    per_label: dict = {}
    for v in fresh:
        for lid in v.labels:
            per_label.setdefault(lid, []).append(v)
    for lid, group in per_label.items():
        storage.indices.label.bulk_add(lid, group)
    storage.indices.label_property.bulk_add(fresh)


def _apply_batch_edges(storage, edges, changed) -> None:
    from ..objects import Edge, adj_map_add
    fresh = []
    top_gid = -1
    for (gid, etype, from_gid, to_gid, props) in edges:
        changed.add(from_gid)
        changed.add(to_gid)
        if gid in storage._edges:
            storage._edges[gid].properties = dict(props)
            continue
        from_v = storage._vertices.get(from_gid)
        to_v = storage._vertices.get(to_gid)
        if from_v is None or to_v is None:
            raise DurabilityError(
                f"batch edge {gid} references missing vertex")
        e = Edge(gid, etype, from_v, to_v)
        e.properties = dict(props)
        out_entry = (etype, to_v, e)
        in_entry = (etype, from_v, e)
        from_v.out_edges.append(out_entry)
        adj_map_add(from_v, "out", out_entry)
        to_v.in_edges.append(in_entry)
        adj_map_add(to_v, "in", in_entry)
        storage._edges[gid] = e
        top_gid = max(top_gid, gid)
        fresh.append(e)
    if top_gid >= 0:
        with storage._gid_lock:
            storage._next_edge_gid = max(storage._next_edge_gid,
                                         top_gid + 1)
    storage.indices.edge_type.bulk_add(fresh)


def _apply_wal_txn(storage, ops):
    """Replay one committed transaction's forward records (idempotent).

    BATCH_INSERT vertices apply in frame order, but BATCH_INSERT edges are
    deferred to the end of the transaction so they may reference vertices
    created by per-row records appearing later in the same transaction.

    Returns the set of vertex gids whose state changed (for the
    topology change log: replica WAL apply must feed version-keyed
    delta caches exactly like local commits do)."""
    from ..objects import Edge, Vertex
    changed: set = set()
    batches = []   # decoded BATCH_INSERT payloads, replayed across passes
    for kind, payload in ops:
        buf = BytesIO(payload)
        if kind == W.OP_BATCH_INSERT:
            vertices, edges = W.decode_batch_insert(buf)
            _apply_batch_vertices(storage, vertices, changed)
            batches.append(edges)
        elif kind == W.OP_MAPPER_SYNC:
            tables = []
            for _ in range(3):
                n = _read_varint(buf)
                tables.append([buf.read(_read_varint(buf)).decode("utf-8")
                               for _ in range(n)])
            storage.label_mapper = NameIdMapper.from_list(tables[0])
            storage.property_mapper = NameIdMapper.from_list(tables[1])
            storage.edge_type_mapper = NameIdMapper.from_list(tables[2])
        elif kind in (W.OP_CREATE_VERTEX, W.OP_VERTEX_STATE):
            gid = _read_varint(buf)
            changed.add(gid)
            labels = {_read_varint(buf) for _ in range(_read_varint(buf))}
            props = {}
            for _ in range(_read_varint(buf)):
                pid = _read_varint(buf)
                props[pid] = decode_value(buf)
            v = storage._vertices.get(gid)
            if v is None:
                v = Vertex(gid)
                storage._vertices[gid] = v
                # WAL apply runs live on replicas: counter publication
                # takes the same lock the allocation path holds
                with storage._gid_lock:
                    storage._next_vertex_gid = max(
                        storage._next_vertex_gid, gid + 1)
            v.labels = labels
            v.properties = props
            for lid in labels:
                storage.indices.label.add(lid, v)
            storage.indices.label_property.update_on_change(v)
        elif kind == W.OP_DELETE_VERTEX:
            gid = _read_varint(buf)
            changed.add(gid)
            v = storage._vertices.pop(gid, None)
            if v is not None:
                v.deleted = True
                for lid in list(v.labels):
                    storage.indices.label.remove_entry(lid, v)
                storage.indices.label_property.remove_entry(v)
        elif kind == W.OP_CREATE_EDGE:
            gid = _read_varint(buf)
            etype = _read_varint(buf)
            from_gid = _read_varint(buf)
            to_gid = _read_varint(buf)
            changed.add(from_gid)
            changed.add(to_gid)
            props = {}
            for _ in range(_read_varint(buf)):
                pid = _read_varint(buf)
                props[pid] = decode_value(buf)
            if gid in storage._edges:
                storage._edges[gid].properties = props
                continue
            from_v = storage._vertices.get(from_gid)
            to_v = storage._vertices.get(to_gid)
            if from_v is None or to_v is None:
                raise DurabilityError(
                    f"WAL edge {gid} references missing vertex")
            e = Edge(gid, etype, from_v, to_v)
            e.properties = props
            from ..objects import adj_map_add
            out_entry = (etype, to_v, e)
            in_entry = (etype, from_v, e)
            from_v.out_edges.append(out_entry)
            adj_map_add(from_v, "out", out_entry)
            to_v.in_edges.append(in_entry)
            adj_map_add(to_v, "in", in_entry)
            storage._edges[gid] = e
            storage.indices.edge_type.add(e)
            with storage._gid_lock:
                storage._next_edge_gid = max(storage._next_edge_gid,
                                             gid + 1)
        elif kind == W.OP_EDGE_STATE:
            gid = _read_varint(buf)
            props = {}
            for _ in range(_read_varint(buf)):
                pid = _read_varint(buf)
                props[pid] = decode_value(buf)
            e = storage._edges.get(gid)
            if e is not None:
                e.properties = props
        elif kind == W.OP_DELETE_EDGE:
            gid = _read_varint(buf)
            e = storage._edges.pop(gid, None)
            if e is not None:
                from ..objects import adj_map_remove
                entry_out = (e.edge_type, e.to_vertex, e)
                entry_in = (e.edge_type, e.from_vertex, e)
                try:
                    e.from_vertex.out_edges.remove(entry_out)
                except ValueError:
                    pass
                adj_map_remove(e.from_vertex, "out", entry_out)
                try:
                    e.to_vertex.in_edges.remove(entry_in)
                except ValueError:
                    pass
                adj_map_remove(e.to_vertex, "in", entry_in)
                storage.indices.edge_type.remove_entry(e)
                changed.add(e.from_vertex.gid)
                changed.add(e.to_vertex.gid)
        elif kind == W.OP_STREAM_OFFSET:
            # stream offsets ride the data commit: restoring them here is
            # what makes recovery (and replica apply — replication shares
            # this function) resume ingestion exactly once
            name, position = W.decode_stream_offset(buf)
            storage.stream_offsets[name] = position
        else:
            raise DurabilityError(f"unknown WAL op 0x{kind:02x}")
    for edges in batches:
        _apply_batch_edges(storage, edges, changed)
    return changed


def wire_durability(storage) -> "W.WalFile | None":
    """Attach a WAL sink if configured; returns the WalFile."""
    if not storage.config.wal_enabled or not storage.config.durability_dir:
        return None
    wal_file = W.WalFile(storage)
    storage.wal_sink = wal_file.sink
    # snapshot-time WAL retention needs the active segment path
    storage.wal_file = wal_file
    return wal_file
