"""Write-ahead log: per-commit forward-operation records.

Counterpart of the reference's WAL (/root/reference/src/storage/v2/
durability/wal.hpp — WalDeltaData records ordered by commit timestamp).
Design difference, chosen for the undo-delta MVCC model: instead of
re-deriving fine-grained forward deltas from undo chains, each commit logs
the *final state* of every object it touched (create/state/delete records).
Replay is idempotent per record, which also makes these records directly
shippable to replicas (replication reuses this encoder).

Record framing: [u32 length][u8 kind][payload]; txn frame:
  TXN_BEGIN(commit_ts) op* TXN_END(commit_ts)
fsync policy: every commit (default) or batched.
"""

from __future__ import annotations

import os
import struct
import threading
from io import BytesIO

from ...exceptions import DurabilityError
from ..property_store import _read_varint, _write_varint, decode_value, \
    encode_value

OP_TXN_BEGIN = 0x01
OP_TXN_END = 0x02
OP_CREATE_VERTEX = 0x10     # gid, labels, props
OP_VERTEX_STATE = 0x11      # gid, labels, props (overwrite)
OP_DELETE_VERTEX = 0x12     # gid
OP_CREATE_EDGE = 0x20       # gid, type, from, to, props
OP_EDGE_STATE = 0x21        # gid, props
OP_DELETE_EDGE = 0x22       # gid
OP_MAPPER_SYNC = 0x30       # label/property/edge-type name tables
OP_BATCH_INSERT = 0x40      # one bulk-insert batch, columnar layout


def _encode_batch_insert(batch, deleted_v, deleted_e) -> bytes:
    """Columnar payload for one batch_insert() call: delta-encoded gid
    ranges, a label-set dictionary, and per-property value columns with
    presence bitmaps — one record per batch instead of one per object.
    Objects that also died inside the transaction are filtered out (they
    never become durable), matching the per-object encoder's rule."""
    vertices = [v for v in batch.vertices if v not in deleted_v]
    edges = [e for e in batch.edges
             if e not in deleted_e and e.from_vertex not in deleted_v
             and e.to_vertex not in deleted_v]
    p = BytesIO()

    def gid_column(objs) -> None:
        prev = 0
        for i, o in enumerate(objs):
            _write_varint(p, o.gid if i == 0 else o.gid - prev)
            prev = o.gid

    def prop_columns(objs) -> None:
        cols: dict[int, list] = {}
        for i, o in enumerate(objs):
            for pid, value in o.properties.items():
                cols.setdefault(pid, []).append((i, value))
        _write_varint(p, len(cols))
        n = len(objs)
        for pid in sorted(cols):
            _write_varint(p, pid)
            present = bytearray((n + 7) // 8)
            for i, _v in cols[pid]:
                present[i >> 3] |= 1 << (i & 7)
            p.write(bytes(present))
            for _i, value in cols[pid]:
                encode_value(p, value)

    _write_varint(p, len(vertices))
    gid_column(vertices)
    # label-set dictionary: bulk rows overwhelmingly share one label set
    label_sets: dict[tuple, int] = {}
    set_idx = []
    for v in vertices:
        key = tuple(sorted(v.labels))
        idx = label_sets.setdefault(key, len(label_sets))
        set_idx.append(idx)
    _write_varint(p, len(label_sets))
    for key in label_sets:
        _write_varint(p, len(key))
        for lid in key:
            _write_varint(p, lid)
    for idx in set_idx:
        _write_varint(p, idx)
    prop_columns(vertices)

    _write_varint(p, len(edges))
    gid_column(edges)
    for e in edges:
        _write_varint(p, e.edge_type)
    for e in edges:
        _write_varint(p, e.from_vertex.gid)
    for e in edges:
        _write_varint(p, e.to_vertex.gid)
    prop_columns(edges)
    return p.getvalue()


def decode_batch_insert(buf: BytesIO):
    """Decode one OP_BATCH_INSERT payload into
    (vertices: [(gid, labels, props)], edges: [(gid, etype, from, to, props)]).
    """
    def gid_column(n) -> list[int]:
        gids = []
        prev = 0
        for i in range(n):
            d = _read_varint(buf)
            prev = d if i == 0 else prev + d
            gids.append(prev)
        return gids

    def prop_columns(n) -> list[dict]:
        props: list[dict] = [{} for _ in range(n)]
        for _ in range(_read_varint(buf)):
            pid = _read_varint(buf)
            present = buf.read((n + 7) // 8)
            rows = [i for i in range(n) if present[i >> 3] & (1 << (i & 7))]
            for i in rows:
                props[i][pid] = decode_value(buf)
        return props

    n_v = _read_varint(buf)
    v_gids = gid_column(n_v)
    label_sets = []
    for _ in range(_read_varint(buf)):
        label_sets.append([_read_varint(buf)
                           for _ in range(_read_varint(buf))])
    v_labels = [label_sets[_read_varint(buf)] for _ in range(n_v)]
    v_props = prop_columns(n_v)
    vertices = list(zip(v_gids, v_labels, v_props))

    n_e = _read_varint(buf)
    e_gids = gid_column(n_e)
    e_types = [_read_varint(buf) for _ in range(n_e)]
    e_from = [_read_varint(buf) for _ in range(n_e)]
    e_to = [_read_varint(buf) for _ in range(n_e)]
    e_props = prop_columns(n_e)
    edges = list(zip(e_gids, e_types, e_from, e_to, e_props))
    return vertices, edges


def encode_txn_ops(storage, txn, commit_ts: int) -> bytes:
    """Build the WAL byte frame for a transaction at commit time.

    Called under the engine lock, BEFORE the visibility flip — objects'
    direct fields hold the transaction's final state (MVCC keeps older
    versions in undo chains, which WAL doesn't need).
    """
    from ..delta import DeltaAction

    created_v, deleted_v = set(), set()
    created_e, deleted_e = set(), set()
    for delta in txn.deltas:
        if delta.action is DeltaAction.DELETE_OBJECT:
            from ..objects import Vertex
            (created_v if isinstance(delta.obj, Vertex)
             else created_e).add(delta.obj)
        elif delta.action is DeltaAction.RECREATE_OBJECT:
            from ..objects import Vertex
            (deleted_v if isinstance(delta.obj, Vertex)
             else deleted_e).add(delta.obj)

    buf = BytesIO()

    def frame(kind: int, payload: bytes) -> None:
        buf.write(struct.pack("<IB", len(payload) + 1, kind))
        buf.write(payload)

    p = BytesIO()
    _write_varint(p, commit_ts)
    frame(OP_TXN_BEGIN, p.getvalue())

    # mapper sync keeps name tables replayable without separate logging
    p = BytesIO()
    for mapper in (storage.label_mapper, storage.property_mapper,
                   storage.edge_type_mapper):
        names = mapper.to_list()
        _write_varint(p, len(names))
        for name in names:
            raw = name.encode("utf-8")
            _write_varint(p, len(raw))
            p.write(raw)
    frame(OP_MAPPER_SYNC, p.getvalue())

    def vertex_state_payload(v) -> bytes:
        p = BytesIO()
        _write_varint(p, v.gid)
        _write_varint(p, len(v.labels))
        for l in sorted(v.labels):
            _write_varint(p, l)
        _write_varint(p, len(v.properties))
        for pid in sorted(v.properties):
            _write_varint(p, pid)
            encode_value(p, v.properties[pid])
        return p.getvalue()

    # bulk-insert batches: one columnar BATCH_INSERT record per batch;
    # their objects are then excluded from the per-object loops below
    # (final state read here, under the engine lock, so later in-txn
    # mutations of batch-created objects are captured by the record)
    batch_objs: set = set()
    for batch in (getattr(txn, "batches", None) or ()):
        frame(OP_BATCH_INSERT,
              _encode_batch_insert(batch, deleted_v, deleted_e))
        batch_objs.update(batch.vertices)
        batch_objs.update(batch.edges)

    for v in txn.touched_vertices.values():
        if v in batch_objs:
            continue  # carried by a BATCH_INSERT record
        if v in created_v and v in deleted_v:
            continue  # created and deleted within the txn
        if v in deleted_v:
            p = BytesIO()
            _write_varint(p, v.gid)
            frame(OP_DELETE_VERTEX, p.getvalue())
        elif v in created_v:
            frame(OP_CREATE_VERTEX, vertex_state_payload(v))
        else:
            frame(OP_VERTEX_STATE, vertex_state_payload(v))

    for e in txn.touched_edges.values():
        if e in batch_objs:
            continue  # carried by a BATCH_INSERT record
        if e in created_e and e in deleted_e:
            continue
        if e in deleted_e:
            p = BytesIO()
            _write_varint(p, e.gid)
            frame(OP_DELETE_EDGE, p.getvalue())
        elif e in created_e:
            p = BytesIO()
            _write_varint(p, e.gid)
            _write_varint(p, e.edge_type)
            _write_varint(p, e.from_vertex.gid)
            _write_varint(p, e.to_vertex.gid)
            _write_varint(p, len(e.properties))
            for pid in sorted(e.properties):
                _write_varint(p, pid)
                encode_value(p, e.properties[pid])
            frame(OP_CREATE_EDGE, p.getvalue())
        else:
            p = BytesIO()
            _write_varint(p, e.gid)
            _write_varint(p, len(e.properties))
            for pid in sorted(e.properties):
                _write_varint(p, pid)
                encode_value(p, e.properties[pid])
            frame(OP_EDGE_STATE, p.getvalue())

    p = BytesIO()
    _write_varint(p, commit_ts)
    frame(OP_TXN_END, p.getvalue())
    return buf.getvalue()


class WalFile:
    """Append-only WAL writer with fsync-per-commit (configurable)."""

    def __init__(self, storage, sync_every_commit: bool = True) -> None:
        base = storage.config.durability_dir
        if not base:
            raise DurabilityError("durability_dir is not configured")
        self.dir = os.path.join(base, "wal")
        os.makedirs(self.dir, exist_ok=True)
        import time
        self.path = os.path.join(self.dir,
                                 f"wal_{int(time.time() * 1e6)}.mgwal")
        self._file = open(self.path, "ab")
        self._lock = threading.Lock()
        self.sync_every_commit = sync_every_commit
        self.storage = storage

    def sink(self, frame: bytes, commit_ts: int) -> None:
        """storage.wal_sink hook: frame pre-encoded under the engine lock."""
        with self._lock:
            self._file.write(frame)
            self._file.flush()
            if self.sync_every_commit:
                os.fsync(self._file.fileno())

    def close(self) -> None:
        with self._lock:
            self._file.close()


def iter_records_from_bytes(data: bytes):
    """Yield (kind, payload_bytes) frames; tolerates a truncated tail."""
    pos = 0
    n = len(data)
    while pos + 5 <= n:
        (length, kind) = struct.unpack_from("<IB", data, pos)
        payload_len = length - 1
        start = pos + 5
        if start + payload_len > n:
            break  # truncated tail (crash mid-write) — stop cleanly
        yield kind, data[start:start + payload_len]
        pos = start + payload_len


def iter_wal_records(path: str):
    with open(path, "rb") as f:
        yield from iter_records_from_bytes(f.read())


def iter_txns_from_bytes(data: bytes):
    """Group frames into (commit_ts, [(kind, payload)]) transactions.
    Incomplete transactions (no TXN_END) are discarded."""
    current_ts = None
    ops = []
    for kind, payload in iter_records_from_bytes(data):
        if kind == OP_TXN_BEGIN:
            current_ts = _read_varint(BytesIO(payload))
            ops = []
        elif kind == OP_TXN_END:
            end_ts = _read_varint(BytesIO(payload))
            if current_ts is not None and end_ts == current_ts:
                yield current_ts, ops
            current_ts = None
            ops = []
        else:
            if current_ts is not None:
                ops.append((kind, payload))


def iter_wal_transactions(path: str):
    with open(path, "rb") as f:
        yield from iter_txns_from_bytes(f.read())


def list_wal_files(storage) -> list[str]:
    base = storage.config.durability_dir
    if not base:
        return []
    d = os.path.join(base, "wal")
    if not os.path.isdir(d):
        return []
    return [os.path.join(d, p) for p in sorted(os.listdir(d))
            if p.endswith(".mgwal")]
