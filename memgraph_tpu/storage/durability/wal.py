"""Write-ahead log: per-commit forward-operation records.

Counterpart of the reference's WAL (/root/reference/src/storage/v2/
durability/wal.hpp — WalDeltaData records ordered by commit timestamp).
Design difference, chosen for the undo-delta MVCC model: instead of
re-deriving fine-grained forward deltas from undo chains, each commit logs
the *final state* of every object it touched (create/state/delete records).
Replay is idempotent per record, which also makes these records directly
shippable to replicas (replication reuses this encoder).

Record framing v2: [u32 length][u8 kind][u32 crc32][payload] where
length = 5 + len(payload) and the CRC covers kind + payload; txn frame:
  TXN_BEGIN(commit_ts) op* TXN_END(commit_ts)
fsync policy: every commit (default) or batched.

On-disk WAL segments (v2) carry a 19-byte header —
  [9s magic "MGTPUWAL2"][u16 version][u64 seqnum]
— and are named wal_<seqnum:012d>.mgwal with a monotonic segment
sequence number persisted by the filenames themselves (the previous
wall-clock-microsecond names could collide or reorder across a clock
step). Segments rotate at StorageConfig.wal_segment_size bytes; closed
segments whose every transaction is covered by the newest snapshot are
pruned (oldest-first only, so the seqnum chain never gets a hole).
Recovery streams each segment in chunks, verifies per-record CRCs,
truncates at the first damaged record (logging what it dropped), and
refuses a seqnum gap in the chain. Legacy headerless v1 files
([u32 length][u8 kind][payload], no CRC) remain readable.
"""

from __future__ import annotations

import logging
import os
import struct
import threading
import zlib
from io import BytesIO

from ...exceptions import DurabilityError
from ...utils import faultinject as FI
from ...utils.locks import tracked_lock
from ..property_store import _read_varint, _write_varint, decode_value, \
    encode_value

log = logging.getLogger(__name__)

WAL_MAGIC = b"MGTPUWAL2"
WAL_VERSION = 2
_HEADER_LEN = len(WAL_MAGIC) + 10          # magic + u16 version + u64 seq
_RECORD_HEADER = struct.Struct("<IBI")     # length, kind, crc32
_MAX_RECORD_BYTES = 1 << 30                # length-field sanity bound
DEFAULT_SEGMENT_SIZE = 64 * 1024 * 1024

OP_TXN_BEGIN = 0x01
OP_TXN_END = 0x02
OP_CREATE_VERTEX = 0x10     # gid, labels, props
OP_VERTEX_STATE = 0x11      # gid, labels, props (overwrite)
OP_DELETE_VERTEX = 0x12     # gid
OP_CREATE_EDGE = 0x20       # gid, type, from, to, props
OP_EDGE_STATE = 0x21        # gid, props
OP_DELETE_EDGE = 0x22       # gid
OP_MAPPER_SYNC = 0x30       # label/property/edge-type name tables
OP_BATCH_INSERT = 0x40      # one bulk-insert batch, columnar layout
OP_STREAM_OFFSET = 0x50     # stream name + source position, in-txn


def _crc(kind: int, payload: bytes) -> int:
    return zlib.crc32(payload, zlib.crc32(bytes((kind,))))


def frame_record(kind: int, payload: bytes) -> bytes:
    """One v2 record: [u32 length][u8 kind][u32 crc32][payload]."""
    return _RECORD_HEADER.pack(len(payload) + 5, kind,
                               _crc(kind, payload)) + payload


def _encode_batch_insert(batch, deleted_v, deleted_e) -> bytes:
    """Columnar payload for one batch_insert() call: delta-encoded gid
    ranges, a label-set dictionary, and per-property value columns with
    presence bitmaps — one record per batch instead of one per object.
    Objects that also died inside the transaction are filtered out (they
    never become durable), matching the per-object encoder's rule."""
    vertices = [v for v in batch.vertices if v not in deleted_v]
    edges = [e for e in batch.edges
             if e not in deleted_e and e.from_vertex not in deleted_v
             and e.to_vertex not in deleted_v]
    p = BytesIO()

    def gid_column(objs) -> None:
        prev = 0
        for i, o in enumerate(objs):
            _write_varint(p, o.gid if i == 0 else o.gid - prev)
            prev = o.gid

    def prop_columns(objs) -> None:
        cols: dict[int, list] = {}
        for i, o in enumerate(objs):
            for pid, value in o.properties.items():
                cols.setdefault(pid, []).append((i, value))
        _write_varint(p, len(cols))
        n = len(objs)
        for pid in sorted(cols):
            _write_varint(p, pid)
            present = bytearray((n + 7) // 8)
            for i, _v in cols[pid]:
                present[i >> 3] |= 1 << (i & 7)
            p.write(bytes(present))
            for _i, value in cols[pid]:
                encode_value(p, value)

    _write_varint(p, len(vertices))
    gid_column(vertices)
    # label-set dictionary: bulk rows overwhelmingly share one label set
    label_sets: dict[tuple, int] = {}
    set_idx = []
    for v in vertices:
        key = tuple(sorted(v.labels))
        idx = label_sets.setdefault(key, len(label_sets))
        set_idx.append(idx)
    _write_varint(p, len(label_sets))
    for key in label_sets:
        _write_varint(p, len(key))
        for lid in key:
            _write_varint(p, lid)
    for idx in set_idx:
        _write_varint(p, idx)
    prop_columns(vertices)

    _write_varint(p, len(edges))
    gid_column(edges)
    for e in edges:
        _write_varint(p, e.edge_type)
    for e in edges:
        _write_varint(p, e.from_vertex.gid)
    for e in edges:
        _write_varint(p, e.to_vertex.gid)
    prop_columns(edges)
    return p.getvalue()


def encode_stream_offset(name: str, position) -> bytes:
    """OP_STREAM_OFFSET payload: varint-length-prefixed stream name +
    varint-length-prefixed JSON position (FileSource byte offsets and
    Kafka per-(topic, partition) offset maps both fit)."""
    import json
    p = BytesIO()
    raw = name.encode("utf-8")
    _write_varint(p, len(raw))
    p.write(raw)
    pos = json.dumps(position, sort_keys=True).encode("utf-8")
    _write_varint(p, len(pos))
    p.write(pos)
    return p.getvalue()


def decode_stream_offset(buf: BytesIO) -> tuple[str, object]:
    """Decode one OP_STREAM_OFFSET payload into (name, position)."""
    import json
    name = buf.read(_read_varint(buf)).decode("utf-8")
    position = json.loads(buf.read(_read_varint(buf)).decode("utf-8"))
    return name, position


def decode_batch_insert(buf: BytesIO):
    """Decode one OP_BATCH_INSERT payload into
    (vertices: [(gid, labels, props)], edges: [(gid, etype, from, to, props)]).
    """
    def gid_column(n) -> list[int]:
        gids = []
        prev = 0
        for i in range(n):
            d = _read_varint(buf)
            prev = d if i == 0 else prev + d
            gids.append(prev)
        return gids

    def prop_columns(n) -> list[dict]:
        props: list[dict] = [{} for _ in range(n)]
        for _ in range(_read_varint(buf)):
            pid = _read_varint(buf)
            present = buf.read((n + 7) // 8)
            rows = [i for i in range(n) if present[i >> 3] & (1 << (i & 7))]
            for i in rows:
                props[i][pid] = decode_value(buf)
        return props

    n_v = _read_varint(buf)
    v_gids = gid_column(n_v)
    label_sets = []
    for _ in range(_read_varint(buf)):
        label_sets.append([_read_varint(buf)
                           for _ in range(_read_varint(buf))])
    v_labels = [label_sets[_read_varint(buf)] for _ in range(n_v)]
    v_props = prop_columns(n_v)
    vertices = list(zip(v_gids, v_labels, v_props))

    n_e = _read_varint(buf)
    e_gids = gid_column(n_e)
    e_types = [_read_varint(buf) for _ in range(n_e)]
    e_from = [_read_varint(buf) for _ in range(n_e)]
    e_to = [_read_varint(buf) for _ in range(n_e)]
    e_props = prop_columns(n_e)
    edges = list(zip(e_gids, e_types, e_from, e_to, e_props))
    return vertices, edges


def encode_txn_ops(storage, txn, commit_ts: int) -> bytes:
    """Build the WAL byte frame for a transaction at commit time.

    Called under the engine lock, BEFORE the visibility flip — objects'
    direct fields hold the transaction's final state (MVCC keeps older
    versions in undo chains, which WAL doesn't need).
    """
    from ..delta import DeltaAction

    created_v, deleted_v = set(), set()
    created_e, deleted_e = set(), set()
    for delta in txn.deltas:
        if delta.action is DeltaAction.DELETE_OBJECT:
            from ..objects import Vertex
            (created_v if isinstance(delta.obj, Vertex)
             else created_e).add(delta.obj)
        elif delta.action is DeltaAction.RECREATE_OBJECT:
            from ..objects import Vertex
            (deleted_v if isinstance(delta.obj, Vertex)
             else deleted_e).add(delta.obj)

    buf = BytesIO()

    def frame(kind: int, payload: bytes) -> None:
        buf.write(frame_record(kind, payload))

    p = BytesIO()
    _write_varint(p, commit_ts)
    frame(OP_TXN_BEGIN, p.getvalue())

    # mapper sync keeps name tables replayable without separate logging
    p = BytesIO()
    for mapper in (storage.label_mapper, storage.property_mapper,
                   storage.edge_type_mapper):
        names = mapper.to_list()
        _write_varint(p, len(names))
        for name in names:
            raw = name.encode("utf-8")
            _write_varint(p, len(raw))
            p.write(raw)
    frame(OP_MAPPER_SYNC, p.getvalue())

    def vertex_state_payload(v) -> bytes:
        p = BytesIO()
        _write_varint(p, v.gid)
        _write_varint(p, len(v.labels))
        for l in sorted(v.labels):
            _write_varint(p, l)
        _write_varint(p, len(v.properties))
        for pid in sorted(v.properties):
            _write_varint(p, pid)
            encode_value(p, v.properties[pid])
        return p.getvalue()

    # bulk-insert batches: one columnar BATCH_INSERT record per batch;
    # their objects are then excluded from the per-object loops below
    # (final state read here, under the engine lock, so later in-txn
    # mutations of batch-created objects are captured by the record)
    batch_objs: set = set()
    for batch in (getattr(txn, "batches", None) or ()):
        frame(OP_BATCH_INSERT,
              _encode_batch_insert(batch, deleted_v, deleted_e))
        batch_objs.update(batch.vertices)
        batch_objs.update(batch.edges)

    for v in txn.touched_vertices.values():
        if v in batch_objs:
            continue  # carried by a BATCH_INSERT record
        if v in created_v and v in deleted_v:
            continue  # created and deleted within the txn
        if v in deleted_v:
            p = BytesIO()
            _write_varint(p, v.gid)
            frame(OP_DELETE_VERTEX, p.getvalue())
        elif v in created_v:
            frame(OP_CREATE_VERTEX, vertex_state_payload(v))
        else:
            frame(OP_VERTEX_STATE, vertex_state_payload(v))

    for e in txn.touched_edges.values():
        if e in batch_objs:
            continue  # carried by a BATCH_INSERT record
        if e in created_e and e in deleted_e:
            continue
        if e in deleted_e:
            p = BytesIO()
            _write_varint(p, e.gid)
            frame(OP_DELETE_EDGE, p.getvalue())
        elif e in created_e:
            p = BytesIO()
            _write_varint(p, e.gid)
            _write_varint(p, e.edge_type)
            _write_varint(p, e.from_vertex.gid)
            _write_varint(p, e.to_vertex.gid)
            _write_varint(p, len(e.properties))
            for pid in sorted(e.properties):
                _write_varint(p, pid)
                encode_value(p, e.properties[pid])
            frame(OP_CREATE_EDGE, p.getvalue())
        else:
            p = BytesIO()
            _write_varint(p, e.gid)
            _write_varint(p, len(e.properties))
            for pid in sorted(e.properties):
                _write_varint(p, pid)
                encode_value(p, e.properties[pid])
            frame(OP_EDGE_STATE, p.getvalue())

    # stream offsets ride the same commit frame: replayed on recovery
    # and shipped over replication, so consumer-side commit() is an
    # optimization, not the exactly-once boundary
    for name in sorted(getattr(txn, "stream_offsets", None) or {}):
        frame(OP_STREAM_OFFSET,
              encode_stream_offset(name, txn.stream_offsets[name]))

    p = BytesIO()
    _write_varint(p, commit_ts)
    frame(OP_TXN_END, p.getvalue())
    return buf.getvalue()


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename/create inside it is durable (a
    crashed rename otherwise may resurrect the old directory entry)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class WalFile:
    """Append-only segmented WAL writer with fsync-per-commit
    (configurable) and size-based rotation."""

    def __init__(self, storage, sync_every_commit: bool = True) -> None:
        base = storage.config.durability_dir
        if not base:
            raise DurabilityError("durability_dir is not configured")
        self.dir = os.path.join(base, "wal")
        os.makedirs(self.dir, exist_ok=True)
        self.segment_size = getattr(storage.config, "wal_segment_size",
                                    DEFAULT_SEGMENT_SIZE)
        self._lock = tracked_lock("WalFile._lock")
        self.sync_every_commit = sync_every_commit
        self.storage = storage
        self._seq = next_segment_seq(self.dir)
        # bytes flushed but not yet fsynced (batched-fsync mode): the
        # saturation plane's wal_fsync_backlog check reads this gauge —
        # a growing backlog is acked-but-volatile data at risk
        self._unsynced_bytes = 0
        self._open_segment()

    def _open_segment(self) -> None:
        self.path = os.path.join(self.dir, f"wal_{self._seq:012d}.mgwal")
        self._file = open(self.path, "ab")
        if self._file.tell() == 0:
            self._file.write(WAL_MAGIC
                             + struct.pack("<HQ", WAL_VERSION, self._seq))
            self._file.flush()
            os.fsync(self._file.fileno())
            fsync_dir(self.dir)  # the new segment's dirent is durable

    def sink(self, frame: bytes, commit_ts: int) -> None:
        """storage.wal_sink hook: frame pre-encoded under the engine lock."""
        from ...observability.metrics import global_metrics
        with self._lock:
            FI.faulty_write("wal.write", self._file, frame)
            self._file.flush()
            if self.sync_every_commit:
                FI.fire("wal.fsync")
                import time
                t0 = time.perf_counter()
                os.fsync(self._file.fileno())
                global_metrics.observe("wal.fsync_latency_sec",
                                       time.perf_counter() - t0)
            else:
                self._unsynced_bytes += len(frame)
                global_metrics.set_gauge("wal.fsync_backlog_bytes",
                                         float(self._unsynced_bytes))
            if self._file.tell() >= self.segment_size:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        from ...observability.metrics import global_metrics
        self._file.flush()
        os.fsync(self._file.fileno())
        if self._unsynced_bytes:
            self._unsynced_bytes = 0
            global_metrics.set_gauge("wal.fsync_backlog_bytes", 0.0)
        self._file.close()
        self._seq += 1
        self._open_segment()
        global_metrics.increment("wal.segments_rotated")

    def rotate(self) -> str:
        """Force a rotation (returns the new active segment path)."""
        with self._lock:
            self._rotate_locked()
            return self.path

    def close(self) -> None:
        with self._lock:
            self._file.close()


# --- reading ---------------------------------------------------------------


def iter_records_from_bytes(data: bytes, on_corruption=None):
    """Yield (kind, payload_bytes) v2 records from an in-memory frame;
    stops cleanly at a truncated tail or the first bad-CRC record
    (invoking on_corruption(reason, offset) if given)."""
    pos = 0
    n = len(data)
    while pos + 9 <= n:
        length, kind, crc = _RECORD_HEADER.unpack_from(data, pos)
        if length < 5 or length > _MAX_RECORD_BYTES:
            if on_corruption:
                on_corruption("bad record length", pos)
            return
        payload_len = length - 5
        start = pos + 9
        if start + payload_len > n:
            if on_corruption:
                on_corruption("truncated record", pos)
            return  # torn tail (crash mid-write) — stop cleanly
        payload = data[start:start + payload_len]
        if _crc(kind, payload) != crc:
            if on_corruption:
                on_corruption("crc mismatch", pos)
            return
        yield kind, payload
        pos = start + payload_len


def _iter_records_stream(f, first: bytes, base_offset: int,
                         on_corruption=None, chunk_size: int = 1 << 20):
    """Stream v2 records from an open file in chunks — recovery of a
    multi-GB segment must not double peak RSS by slurping the file."""
    buf = bytearray(first)
    off = 0            # parse position inside buf
    consumed = base_offset   # absolute file offset of buf[0]
    eof = False

    def fill(need: int) -> bool:
        nonlocal eof
        while len(buf) - off < need and not eof:
            chunk = f.read(chunk_size)
            if not chunk:
                eof = True
            else:
                buf.extend(chunk)
        return len(buf) - off >= need

    while True:
        if off > chunk_size:   # compact the consumed prefix
            del buf[:off]
            consumed += off
            off = 0
        if not fill(9):
            if len(buf) - off and on_corruption:
                on_corruption("truncated record header", consumed + off)
            return
        length, kind, crc = _RECORD_HEADER.unpack_from(buf, off)
        if length < 5 or length > _MAX_RECORD_BYTES:
            if on_corruption:
                on_corruption("bad record length", consumed + off)
            return
        if not fill(4 + length):
            if on_corruption:
                on_corruption("truncated record", consumed + off)
            return
        payload = bytes(buf[off + 9:off + 4 + length])
        if _crc(kind, payload) != crc:
            if on_corruption:
                on_corruption("crc mismatch", consumed + off)
            return
        yield kind, payload
        off += 4 + length


def _iter_records_stream_v1(f, first: bytes, chunk_size: int = 1 << 20):
    """Legacy v1 framing ([u32 len][u8 kind][payload], no CRC), streamed."""
    buf = bytearray(first)
    off = 0
    eof = False

    def fill(need: int) -> bool:
        nonlocal eof
        while len(buf) - off < need and not eof:
            chunk = f.read(chunk_size)
            if not chunk:
                eof = True
            else:
                buf.extend(chunk)
        return len(buf) - off >= need

    while True:
        if off > chunk_size:
            del buf[:off]
            off = 0
        if not fill(5):
            return
        length, kind = struct.unpack_from("<IB", buf, off)
        if length < 1 or length > _MAX_RECORD_BYTES or not fill(4 + length):
            return  # truncated tail — stop cleanly
        yield kind, bytes(buf[off + 5:off + 4 + length])
        off += 4 + length


def read_segment_header(path: str) -> tuple[int, int] | None:
    """(version, seqnum) for a v2 segment; None for a legacy v1 file."""
    with open(path, "rb") as f:
        head = f.read(_HEADER_LEN)
    if not head.startswith(WAL_MAGIC) or len(head) < _HEADER_LEN:
        return None
    version, seq = struct.unpack_from("<HQ", head, len(WAL_MAGIC))
    return version, seq


def iter_wal_records(path: str, on_corruption=None):
    """Stream (kind, payload) records from one segment file. Damage
    truncates iteration at the first bad record; what was dropped is
    logged (and counted) so operators can see the data loss boundary."""
    def report(reason: str, offset: int) -> None:
        from ...observability.metrics import global_metrics
        try:
            dropped = os.path.getsize(path) - offset
        except OSError:
            dropped = -1
        log.warning("WAL %s: %s at offset %d — truncating recovery here "
                    "(%d trailing byte(s) dropped)", path, reason, offset,
                    dropped)
        global_metrics.increment("wal.recovery_truncations")
        if on_corruption:
            on_corruption(reason, offset)

    with open(path, "rb") as f:
        head = f.read(_HEADER_LEN)
        if head.startswith(WAL_MAGIC):
            if len(head) < _HEADER_LEN:
                report("truncated segment header", 0)
                return
            version = struct.unpack_from("<H", head, len(WAL_MAGIC))[0]
            if version > WAL_VERSION:
                raise DurabilityError(
                    f"{path}: unsupported WAL version {version}")
            yield from _iter_records_stream(f, b"", _HEADER_LEN, report)
        else:
            yield from _iter_records_stream_v1(f, head)


def _group_txns(records):
    """Group (kind, payload) records into (commit_ts, ops) transactions.
    Incomplete transactions (no TXN_END) are discarded."""
    current_ts = None
    ops = []
    for kind, payload in records:
        if kind == OP_TXN_BEGIN:
            current_ts = _read_varint(BytesIO(payload))
            ops = []
        elif kind == OP_TXN_END:
            end_ts = _read_varint(BytesIO(payload))
            if current_ts is not None and end_ts == current_ts:
                yield current_ts, ops
            current_ts = None
            ops = []
        else:
            if current_ts is not None:
                ops.append((kind, payload))


def iter_txns_from_bytes(data: bytes):
    yield from _group_txns(iter_records_from_bytes(data))


def iter_wal_transactions(path: str, on_corruption=None):
    yield from _group_txns(iter_wal_records(path, on_corruption))


# --- segment chain management ----------------------------------------------


def list_wal_segments(storage) -> list[tuple[str, int | None]]:
    """All WAL segments in replay order: legacy (headerless) files first
    in name order, then v2 segments by seqnum. Each entry is
    (path, seqnum-or-None)."""
    base = storage.config.durability_dir
    if not base:
        return []
    d = os.path.join(base, "wal")
    if not os.path.isdir(d):
        return []
    legacy, v2 = [], []
    for name in sorted(os.listdir(d)):
        if not name.endswith(".mgwal"):
            continue
        path = os.path.join(d, name)
        try:
            header = read_segment_header(path)
        except OSError:
            continue
        if header is None:
            legacy.append((path, None))
        else:
            v2.append((path, header[1]))
    v2.sort(key=lambda item: item[1])
    return legacy + v2


def check_segment_chain(segments) -> None:
    """Refuse a hole in the v2 seqnum chain: a missing middle segment
    means committed transactions are gone, and replaying around the gap
    would silently resurrect a torn history."""
    seqs = [seq for _, seq in segments if seq is not None]
    for prev, cur in zip(seqs, seqs[1:]):
        if cur != prev + 1:
            raise DurabilityError(
                f"WAL segment chain has a gap: segment {prev} is followed "
                f"by {cur} (missing {prev + 1}..{cur - 1}) — refusing to "
                "replay a torn history")


def list_wal_files(storage) -> list[str]:
    return [path for path, _seq in list_wal_segments(storage)]


def next_segment_seq(wal_dir: str) -> int:
    """Next monotonic segment seqnum: one past the highest existing v2
    header seq (legacy files don't participate — they sort before every
    v2 segment in replay order)."""
    best = 0
    if os.path.isdir(wal_dir):
        for name in os.listdir(wal_dir):
            if not name.endswith(".mgwal"):
                continue
            try:
                header = read_segment_header(os.path.join(wal_dir, name))
            except OSError:
                continue
            if header is not None:
                best = max(best, header[1])
    return best + 1


def prune_wal_segments(storage, snapshot_ts: int,
                       active_path: str | None = None) -> list[str]:
    """Delete leading segments fully covered by the newest snapshot.

    Only a PREFIX of the chain is ever removed (stop at the first
    segment holding a transaction newer than the snapshot), so the
    seqnum chain stays contiguous. The active segment is never touched.
    Returns the deleted paths."""
    deleted = []
    for path, _seq in list_wal_segments(storage):
        if active_path is not None and \
                os.path.abspath(path) == os.path.abspath(active_path):
            break
        max_ts = 0
        for commit_ts, _ops in iter_wal_transactions(path):
            max_ts = max(max_ts, commit_ts)
        if max_ts > snapshot_ts:
            break
        try:
            os.remove(path)
            deleted.append(path)
        except OSError:
            break
    if deleted:
        fsync_dir(os.path.join(storage.config.durability_dir, "wal"))
        log.info("WAL retention: pruned %d segment(s) covered by "
                 "snapshot ts %d", len(deleted), snapshot_ts)
    return deleted
