"""ON_DISK_TRANSACTIONAL storage: demand-paged graph over sqlite.

Third storage mode, mirroring the reference's RocksDB-backed DiskStorage
(/root/reference/src/storage/v2/disk/storage.cpp, ADRs/003_rocksdb.md):
durable committed state lives in an embedded KV-style store (sqlite here —
the environment's RocksDB-class embedded engine), transactions run the
same optimistic MVCC as the in-memory engine, and the in-memory object
table becomes a demand-paged CACHE of the durable state.

Design:
  - `PagedVertex`/`PagedEdge` carry a `loaded` flag; every accessor read or
    write hydrates the object from sqlite first (DiskAccessor overrides the
    state/materialize entry points).
  - Object identity is canonical: the paged tables return one object per
    gid, so `is`-comparisons and MVCC delta chains behave exactly as in the
    in-memory engine.
  - Commit: after the in-memory MVCC commit succeeds, the touched objects
    are written through to sqlite in ONE sqlite transaction (the analog of
    the reference's RocksDB write-batch at commit,
    disk/storage.cpp commit path).
  - Eviction: hydrated, clean (no delta chain) objects are dehydrated when
    the cache exceeds `disk_cache_objects` and no other transaction is
    active — the same safety rule as GC (evicted state must already be
    visible to every possible reader).
  - Snapshots/WAL are not used in this mode (sqlite IS the durability),
    matching the reference where RocksDB owns persistence in disk mode.

Like the reference (storage mode switching docs), a database can only be
switched to/from ON_DISK_TRANSACTIONAL while empty.
"""

from __future__ import annotations

import json
import os
import sqlite3
import struct
import threading
from typing import Iterator, Optional

from .common import Gid, IsolationLevel, StorageMode, View
from .mvcc import materialize_edge, materialize_vertex
from .objects import Edge, Vertex
from .property_store import decode_properties, encode_properties
from .storage import Accessor, InMemoryStorage, StorageConfig


class _AsOf:
    """Pseudo-transaction pinning reads at a commit timestamp — used to
    materialize exactly the committed-at-ts state for persistence, immune
    to concurrent writers that already own the object head."""

    def __init__(self, ts: int) -> None:
        self._ts = ts
        self.id = 0          # matches no delta owner

    def effective_start_ts(self) -> int:
        return self._ts

_ADJ = struct.Struct("<qqq")  # edge_gid, edge_type, other_gid


class PagedVertex(Vertex):
    __slots__ = ("loaded",)

    def __init__(self, gid: int, loaded: bool = True) -> None:
        super().__init__(gid)
        self.loaded = loaded


class PagedEdge(Edge):
    __slots__ = ("loaded",)

    def __init__(self, gid: int, edge_type: int, from_vertex, to_vertex,
                 loaded: bool = True) -> None:
        super().__init__(gid, edge_type, from_vertex, to_vertex)
        self.loaded = loaded


class _PagedTable:
    """dict-compatible view over cache + sqlite backing rows."""

    def __init__(self, storage: "DiskStorage", kind: str) -> None:
        self._s = storage
        self._kind = kind          # "v" | "e"
        self.cache: dict[int, object] = {}

    # -- dict protocol used by the engine ------------------------------
    def __contains__(self, gid: int) -> bool:
        return self.get(gid) is not None

    def __getitem__(self, gid: int):
        obj = self.get(gid)
        if obj is None:
            raise KeyError(gid)
        return obj

    def get(self, gid: int, default=None):
        obj = self.cache.get(gid)
        if obj is not None:
            return obj
        obj = self._s._load_stub(self._kind, gid)
        return obj if obj is not None else default

    def __setitem__(self, gid: int, obj) -> None:
        self.cache[gid] = obj

    def pop(self, gid: int, default=None):
        return self.cache.pop(gid, default)

    def items(self):
        """CACHED items only — used by GC, and only cached objects can
        carry delta chains or tombstones."""
        return list(self.cache.items())

    def __len__(self) -> int:
        return self._s._count(self._kind, len(self.cache))

    def values(self) -> Iterator:
        """All objects: cached ones plus backing rows not in cache.

        Hydrates lazily. To keep full scans memory-bounded, objects this
        scan loaded are evicted in batches once the cache exceeds budget —
        but only while at most one transaction (the scanner's own) is
        active, because with concurrent writers an eviction could split
        object identity (stale reload vs a writer's delta-carrying
        object)."""
        seen = set(self.cache.keys())
        for obj in list(self.cache.values()):
            yield self._s._hydrated(obj)
        loaded_by_scan: list[int] = []
        for gid in self._s._backing_gids(self._kind):
            if gid in seen:
                continue
            obj = self.get(gid)
            if obj is None:
                continue
            yield obj
            loaded_by_scan.append(gid)
            if len(loaded_by_scan) >= 8192 and \
                    len(self.cache) > self._s.cache_budget:
                self._s._evict_scan_batch(self._kind, loaded_by_scan[:-1])
                loaded_by_scan = loaded_by_scan[-1:]


class DiskAccessor(Accessor):
    """Accessor that re-resolves objects to their CANONICAL (and hydrated)
    instance before every state read/write.

    Stale references — index buckets, adjacency triples, or accessors held
    across an eviction — are thereby re-pointed at the authoritative object
    on each use, so eviction can never serve stale state or lose a write.
    Within one transaction objects stay canonical (eviction only runs with
    no active transactions)."""

    def _canon_v(self, vertex):
        c = self.storage._vertices.get(vertex.gid)
        return self.storage._hydrated(c) if c is not None else vertex

    def _canon_e(self, edge):
        c = self.storage._edges.get(edge.gid)
        return self.storage._hydrated(c) if c is not None else edge

    def _vertex_state(self, vertex, view, need_edges=True):
        return super()._vertex_state(self._canon_v(vertex), view, need_edges)

    def _edge_state(self, edge, view):
        return super()._edge_state(self._canon_e(edge), view)

    def _neighbor_entries(self, vertex, side, other_gid, view):
        # adjacency triples may reference evicted (dehydrated) objects —
        # the supernode fast path is an in-memory-engine optimization
        return None

    def _vertex_add_label(self, vertex, label_id):
        return super()._vertex_add_label(self._canon_v(vertex), label_id)

    def _vertex_remove_label(self, vertex, label_id):
        return super()._vertex_remove_label(self._canon_v(vertex), label_id)

    def _vertex_set_property(self, vertex, prop_id, value):
        return super()._vertex_set_property(self._canon_v(vertex), prop_id,
                                            value)

    def _edge_set_property(self, edge, prop_id, value):
        return super()._edge_set_property(self._canon_e(edge), prop_id,
                                          value)

    def create_edge(self, from_va, to_va, edge_type_id):
        from_va.vertex = self._canon_v(from_va.vertex)
        to_va.vertex = self._canon_v(to_va.vertex)
        return super().create_edge(from_va, to_va, edge_type_id)

    def delete_vertex(self, va, detach=False):
        va.vertex = self._canon_v(va.vertex)
        for (_, other, edge) in list(va.vertex.in_edges) + \
                list(va.vertex.out_edges):
            self.storage._hydrated(other)
            self.storage._hydrated(edge)
        return super().delete_vertex(va, detach=detach)

    def delete_edge(self, ea):
        ea.edge = self._canon_e(ea.edge)
        self.storage._hydrated(ea.edge.from_vertex)
        self.storage._hydrated(ea.edge.to_vertex)
        return super().delete_edge(ea)


class DiskStorage(InMemoryStorage):
    """The ON_DISK_TRANSACTIONAL engine."""

    # per-commit sqlite persistence walks touched objects row-by-row; the
    # bulk lane's batch bookkeeping doesn't reach _persist_commit, so keep
    # the planner on the per-row operators for this engine
    supports_batch_insert = False

    def __init__(self, config: Optional[StorageConfig] = None) -> None:
        config = config or StorageConfig()
        config.storage_mode = StorageMode.ON_DISK_TRANSACTIONAL
        if not config.durability_dir:
            raise ValueError("ON_DISK_TRANSACTIONAL requires durability_dir")
        super().__init__(config)
        os.makedirs(config.durability_dir, exist_ok=True)
        self._db_path = os.path.join(config.durability_dir, "disk.sqlite3")
        self._conn = sqlite3.connect(self._db_path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._sql_lock = threading.RLock()
        with self._sql_lock, self._conn:
            # ts = commit timestamp of the row; rows apply only in ts
            # order (conditional upsert) so late out-of-order persists from
            # concurrent committers cannot clobber newer state. Deletes are
            # NULL-data tombstones for the same reason.
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS vertices "
                "(gid INTEGER PRIMARY KEY, data BLOB, ts INTEGER)")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS edges "
                "(gid INTEGER PRIMARY KEY, data BLOB, ts INTEGER)")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS meta "
                "(key TEXT PRIMARY KEY, value TEXT)")
        self._vertices = _PagedTable(self, "v")
        self._edges = _PagedTable(self, "e")
        self.cache_budget = getattr(config, "disk_cache_objects", 100_000)
        self._load_meta()

    # ------------------------------------------------------------------
    # hydration / paging
    # ------------------------------------------------------------------

    def _hydrated(self, obj):
        if isinstance(obj, (PagedVertex, PagedEdge)) and not obj.loaded:
            with obj.lock:  # double-checked: loaded is set LAST, inside
                if not obj.loaded:
                    if isinstance(obj, PagedVertex):
                        self._hydrate_vertex(obj)
                    else:
                        self._hydrate_edge(obj)
        return obj

    def _canonical_vertex(self, gid: int) -> PagedVertex:
        v = self._vertices.cache.get(gid)
        if v is None:
            v = PagedVertex(gid, loaded=False)
            self._vertices.cache[gid] = v
        return v

    def _canonical_edge(self, gid: int, etype: int, fro, to) -> PagedEdge:
        e = self._edges.cache.get(gid)
        if e is None:
            e = PagedEdge(gid, etype, fro, to, loaded=False)
            self._edges.cache[gid] = e
        return e

    def _row(self, kind: str, gid: int):
        table = "vertices" if kind == "v" else "edges"
        with self._sql_lock:
            cur = self._conn.execute(
                f"SELECT data FROM {table} WHERE gid=?", (gid,))
            row = cur.fetchone()
        return row[0] if row else None   # tombstones have data NULL

    def _load_stub(self, kind: str, gid: int):
        """Create (unhydrated) canonical object for a backing row."""
        blob = self._row(kind, gid)
        if blob is None:
            return None
        if kind == "v":
            v = self._canonical_vertex(gid)
            return self._hydrated(v)
        # edges need endpoints decoded up front
        etype, fgid, tgid = struct.unpack_from("<qqq", blob, 0)
        fro = self._canonical_vertex(fgid)
        to = self._canonical_vertex(tgid)
        e = self._canonical_edge(gid, etype, fro, to)
        return self._hydrated(e)

    def _hydrate_vertex(self, v: PagedVertex) -> None:
        """Populate from sqlite. Caller holds v.lock; sets loaded last."""
        blob = self._row("v", v.gid)
        if blob is None:
            v.loaded = True
            return
        off = 0
        n_labels, n_in, n_out, props_len = struct.unpack_from("<qqqq", blob)
        off = 32
        labels = struct.unpack_from(f"<{n_labels}q", blob, off)
        off += 8 * n_labels
        v.labels = set(labels)
        in_adj = []
        for _ in range(n_in):
            egid, etype, ogid = _ADJ.unpack_from(blob, off)
            off += _ADJ.size
            other = self._canonical_vertex(ogid)
            edge = self._canonical_edge(egid, etype, other, v)
            in_adj.append((etype, other, edge))
        out_adj = []
        for _ in range(n_out):
            egid, etype, ogid = _ADJ.unpack_from(blob, off)
            off += _ADJ.size
            other = self._canonical_vertex(ogid)
            edge = self._canonical_edge(egid, etype, v, other)
            out_adj.append((etype, other, edge))
        v.in_edges = in_adj
        v.out_edges = out_adj
        v.properties = decode_properties(blob[off:off + props_len])
        v.loaded = True

    def _hydrate_edge(self, e: PagedEdge) -> None:
        """Populate from sqlite. Caller holds e.lock; sets loaded last."""
        blob = self._row("e", e.gid)
        if blob is not None:
            e.properties = decode_properties(blob[_ADJ.size:])
        e.loaded = True

    def _encode_state_vertex(self, st) -> bytes:
        props = encode_properties(st.properties)
        parts = [struct.pack("<qqqq", len(st.labels), len(st.in_edges),
                             len(st.out_edges), len(props))]
        parts.append(struct.pack(f"<{len(st.labels)}q", *sorted(st.labels)))
        for (etype, other, edge) in st.in_edges:
            parts.append(_ADJ.pack(edge.gid, etype, other.gid))
        for (etype, other, edge) in st.out_edges:
            parts.append(_ADJ.pack(edge.gid, etype, other.gid))
        parts.append(props)
        return b"".join(parts)

    def _encode_state_edge(self, e: Edge, st) -> bytes:
        return struct.pack("<qqq", e.edge_type, e.from_vertex.gid,
                           e.to_vertex.gid) + encode_properties(st.properties)

    def _backing_gids(self, kind: str) -> list[int]:
        table = "vertices" if kind == "v" else "edges"
        with self._sql_lock:
            rows = self._conn.execute(
                f"SELECT gid FROM {table} WHERE data IS NOT NULL").fetchall()
        return [r[0] for r in rows]

    def _count(self, kind: str, cached: int) -> int:
        """Approximate count: durable rows + uncommitted in-flight creates
        (cache objects still carrying a delta chain). Matches the "approx"
        contract of approx_vertex_count."""
        table = "vertices" if kind == "v" else "edges"
        cache = (self._vertices if kind == "v" else self._edges).cache
        pending = [gid for gid, obj in list(cache.items())
                   if obj.delta is not None and not obj.deleted
                   and not isinstance(obj, (PagedVertex, PagedEdge))]
        with self._sql_lock:
            n = self._conn.execute(
                f"SELECT COUNT(*) FROM {table} WHERE data IS NOT NULL"
            ).fetchone()[0]
            extra = len(pending)
            for i in range(0, len(pending), 500):
                chunk = pending[i:i + 500]
                marks = ",".join("?" * len(chunk))
                extra -= self._conn.execute(
                    f"SELECT COUNT(*) FROM {table} WHERE data IS NOT NULL "
                    f"AND gid IN ({marks})", chunk).fetchone()[0]
        return n + extra

    # ------------------------------------------------------------------
    # engine overrides
    # ------------------------------------------------------------------

    def access(self, isolation: Optional[IsolationLevel] = None) -> Accessor:
        return DiskAccessor(self, isolation or self.config.isolation_level)

    def _commit(self, txn) -> int:
        touched_v = dict(txn.touched_vertices)
        touched_e = dict(txn.touched_edges)
        commit_ts = super()._commit(txn)
        if not touched_v and not touched_e:
            return commit_ts
        # Materialize at commit_ts: the engine lock is released after the
        # visibility flip, so object heads may already carry a NEWER
        # transaction's uncommitted writes — the MVCC walk pins exactly the
        # state this commit made durable.
        as_of = _AsOf(commit_ts)
        # encode OUTSIDE _sql_lock: materialize takes object locks, and
        # hydration's lock order is object lock -> _sql_lock
        v_rows, e_rows = [], []
        for gid, v in touched_v.items():
            st = materialize_vertex(v, as_of, View.OLD)
            if st.deleted or not st.exists:
                v_rows.append((gid, None, commit_ts))      # tombstone
            else:
                v_rows.append((gid, self._encode_state_vertex(st),
                               commit_ts))
        for gid, e in touched_e.items():
            st = materialize_edge(e, as_of, View.OLD)
            if st.deleted or not st.exists:
                e_rows.append((gid, None, commit_ts))
            else:
                e_rows.append((gid, self._encode_state_edge(e, st),
                               commit_ts))
        upsert = ("INSERT INTO {t} (gid, data, ts) VALUES (?,?,?) "
                  "ON CONFLICT(gid) DO UPDATE SET data=excluded.data, "
                  "ts=excluded.ts WHERE excluded.ts >= {t}.ts")
        with self._sql_lock, self._conn:
            if v_rows:
                self._conn.executemany(upsert.format(t="vertices"), v_rows)
            if e_rows:
                self._conn.executemany(upsert.format(t="edges"), e_rows)
            # edge creation/deletion changes endpoint adjacency: those
            # endpoints are in touched_vertices by construction (create_edge
            # and delete_edge record both endpoints)
            self._save_meta_locked()
        self._maybe_evict()
        return commit_ts

    def _abort(self, txn) -> None:
        # hydration guarantee: every object a delta touches was hydrated
        # before the write, so the base reverse-undo works unchanged
        super()._abort(txn)


    def _evict_scan_batch(self, kind: str, gids: list) -> None:
        """Drop clean scan-loaded objects mid-scan (see values())."""
        with self._engine_lock:
            if len(self._active_txns) > 1:
                return
            cache = (self._vertices if kind == "v" else self._edges).cache
            ecache = self._edges.cache if kind == "v" else None
            for gid in gids:
                obj = cache.get(gid)
                if obj is not None and obj.delta is None and not obj.deleted:
                    del cache[gid]
                    if ecache is not None and isinstance(obj, Vertex):
                        # drop the adjacency edges it pulled in too
                        for (_, _, edge) in obj.in_edges + obj.out_edges:
                            e2 = ecache.get(edge.gid)
                            if e2 is edge and e2.delta is None:
                                del ecache[edge.gid]

    def _maybe_evict(self) -> None:
        """Drop the whole clean cache once it exceeds the budget.

        Partial eviction would split object identity: a cached neighbor's
        adjacency still references the evicted object while a fresh load
        creates a second one. Whole-cache eviction after a GC pass (which
        truncates committed delta chains) leaves no dangling intra-cache
        references. Only runs with no active transactions — the same
        safety rule as GC: evicted state is the only state any future
        reader can see."""
        vcache = self._vertices.cache
        ecache = self._edges.cache
        if len(vcache) + len(ecache) <= self.cache_budget:
            return
        # under the engine lock: transactions begin under the same lock, so
        # no txn can start between the active-check and the clear (a racing
        # start would otherwise see a cached object later replaced by a
        # fresh load — an object-identity split)
        super().collect_garbage()
        with self._engine_lock:
            if self._active_txns:
                return
            dirty = any(o.delta is not None for o in vcache.values()) or \
                any(o.delta is not None for o in ecache.values())
            if dirty:
                return
            vcache.clear()
            ecache.clear()

    # ------------------------------------------------------------------
    # meta persistence + recovery
    # ------------------------------------------------------------------

    def _save_meta_locked(self) -> None:
        # the _locked suffix is the contract: the caller holds _sql_lock
        # on the commit path, which also serializes the counter snapshot
        # against other disk commits (intraprocedural analysis blind spot)
        meta = {
            "next_vertex_gid": self._next_vertex_gid,  # mglint: disable=MG006 — caller holds _sql_lock (see _locked suffix)
            "next_edge_gid": self._next_edge_gid,  # mglint: disable=MG006 — caller holds _sql_lock (see _locked suffix)
            "timestamp": self._timestamp,  # mglint: disable=MG006 — caller holds _sql_lock (see _locked suffix)
            "labels": self.label_mapper.to_dict(),
            "properties": self.property_mapper.to_dict(),
            "edge_types": self.edge_type_mapper.to_dict(),
        }
        self._conn.execute(
            "INSERT OR REPLACE INTO meta VALUES ('meta', ?)",
            (json.dumps(meta),))

    def _load_meta(self) -> None:
        with self._sql_lock:
            row = self._conn.execute(
                "SELECT value FROM meta WHERE key='meta'").fetchone()
        if not row:
            return
        meta = json.loads(row[0])
        # construction-phase hydration: _load_meta's only call site is
        # __init__, before the storage is published to any other thread
        self._next_vertex_gid = meta["next_vertex_gid"]  # mglint: disable=MG006 — called from __init__ only, object unpublished
        self._next_edge_gid = meta["next_edge_gid"]  # mglint: disable=MG006 — called from __init__ only, object unpublished
        self._timestamp = max(self._timestamp, meta["timestamp"])  # mglint: disable=MG006 — called from __init__ only, object unpublished
        self.label_mapper.load_dict(meta["labels"])
        self.property_mapper.load_dict(meta["properties"])
        self.edge_type_mapper.load_dict(meta["edge_types"])

    def close(self) -> None:
        with self._sql_lock:
            self._conn.close()

    def info(self) -> dict:
        base = super().info()
        base["storage_mode"] = StorageMode.ON_DISK_TRANSACTIONAL.value
        base["disk_cache_objects"] = (len(self._vertices.cache)
                                      + len(self._edges.cache))
        with self._sql_lock:
            base["disk_bytes"] = os.path.getsize(self._db_path) \
                if os.path.exists(self._db_path) else 0
        return base
