"""SHOW SCHEMA INFO — live schema document.

Counterpart of /root/reference/src/storage/v2/schema_info.cpp: nodes
grouped by their exact label set with per-property counts/type
histograms/filling factors, edges grouped by (type, start labels, end
labels), plus constraints and enums. The reference tracks this
incrementally under a flag; here the document is computed on demand from
the accessor's visible state (always exact, O(V+E) per call — the right
trade for a Python host layer; the columnar/CSR caches already pay the
same sweep).

Output shape matches the reference's ToJson (schema_info_types.hpp:110-,
schema_info.cpp:419-), returned as one row with a `schema` JSON string.
"""

from __future__ import annotations

import json


def _type_name(v, storage) -> str:
    from ..utils.point import Point
    from ..utils.temporal import (Date, Duration, LocalDateTime, LocalTime,
                                  ZonedDateTime)
    from .enums import EnumValue
    if v is None:
        return "Null"
    if isinstance(v, bool):
        return "Boolean"
    if isinstance(v, int):
        return "Integer"
    if isinstance(v, float):
        return "Float"
    if isinstance(v, str):
        return "String"
    if isinstance(v, (list, tuple)):
        return "List"
    if isinstance(v, dict):
        return "Map"
    if isinstance(v, Date):
        return "Date"
    if isinstance(v, LocalTime):
        return "LocalTime"
    if isinstance(v, LocalDateTime):
        return "LocalDateTime"
    if isinstance(v, ZonedDateTime):
        return "ZonedDateTime"
    if isinstance(v, Duration):
        return "Duration"
    if isinstance(v, EnumValue):
        return "Enum::" + v.enum_name
    if isinstance(v, Point):
        return "Point3D" if getattr(v, "z", None) is not None else "Point2D"
    if isinstance(v, (bytes, bytearray)):
        return "Bytes"
    return type(v).__name__


def _prop_stats(prop_maps: list[dict], storage, pm) -> list[dict]:
    """Per-property aggregate over a group of objects' property dicts."""
    by_key: dict[str, dict] = {}
    for props in prop_maps:
        for pid, value in props.items():
            key = pm.id_to_name(pid)
            slot = by_key.setdefault(key, {"count": 0, "types": {}})
            slot["count"] += 1
            t = _type_name(value, storage)
            slot["types"][t] = slot["types"].get(t, 0) + 1
    max_count = len(prop_maps) or 1
    out = []
    for key in sorted(by_key):
        slot = by_key[key]
        out.append({
            "key": key,
            "count": slot["count"],
            "filling_factor": 100.0 * slot["count"] / max_count,
            "types": [{"type": t, "count": c}
                      for t, c in sorted(slot["types"].items())],
        })
    return out


def schema_info_json(accessor, view) -> str:
    """Build the full schema document for the accessor's visible state."""
    storage = accessor.storage
    lm, pm = storage.label_mapper, storage.property_mapper
    em = storage.edge_type_mapper

    node_groups: dict[frozenset, list[dict]] = {}
    labels_of_gid: dict[int, tuple] = {}
    for va in accessor.vertices(view):
        labels = frozenset(va.labels(view))
        node_groups.setdefault(labels, []).append(va.properties(view))
        labels_of_gid[va.gid] = tuple(sorted(
            lm.id_to_name(l) for l in labels))

    edge_groups: dict[tuple, list[dict]] = {}
    for ea in accessor.edges(view):
        key = (em.id_to_name(ea.edge_type),
               labels_of_gid.get(ea.from_vertex().gid, ()),
               labels_of_gid.get(ea.to_vertex().gid, ()))
        edge_groups.setdefault(key, []).append(ea.properties(view))

    doc: dict = {"nodes": [], "edges": [], "node_constraints": [],
                 "enums": []}
    for labels in sorted(node_groups, key=lambda s: sorted(
            lm.id_to_name(l) for l in s)):
        group = node_groups[labels]
        doc["nodes"].append({
            "labels": sorted(lm.id_to_name(l) for l in labels),
            "count": len(group),
            "properties": _prop_stats(group, storage, pm),
        })
    for (etype, start, end) in sorted(edge_groups):
        group = edge_groups[(etype, start, end)]
        doc["edges"].append({
            "type": etype,
            "start_node_labels": list(start),
            "end_node_labels": list(end),
            "count": len(group),
            "properties": _prop_stats(group, storage, pm),
        })

    cons = storage.constraints
    for (lid, pid) in cons.existence.all():
        doc["node_constraints"].append({
            "type": "existence", "label": lm.id_to_name(lid),
            "properties": [pm.id_to_name(pid)]})
    for (lid, pids) in cons.unique.all():
        doc["node_constraints"].append({
            "type": "unique", "label": lm.id_to_name(lid),
            "properties": [pm.id_to_name(p) for p in pids]})
    for (lid, pid, type_decl) in cons.type.all():
        doc["node_constraints"].append({
            "type": "data_type", "label": lm.id_to_name(lid),
            "properties": [pm.id_to_name(pid)], "data_type": type_decl})

    from .enums import enum_registry
    for name, values in enum_registry(storage).all().items():
        doc["enums"].append({"name": name, "values": list(values)})

    return json.dumps(doc, sort_keys=False)
