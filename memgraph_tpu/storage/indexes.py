"""Label and label+property indexes.

Capability map to the reference's storage/v2/indices/: LabelIndex and
LabelPropertyIndex (incl. composite properties and range scans) with
MVCC-correct reads — index entries are inserted eagerly at mutation time and
*revalidated against the reader's snapshot* at scan time; stale entries are
swept by GC. Per-index counts feed the planner's cost model
(plan/cost_estimator analog).

Ordered range scans use bisect over a sorted (order_key, gid) list that is
maintained incrementally; point lookups use hash buckets.
"""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict

from ..utils.locks import tracked_lock
from .ordering import order_key


class IndexUsage:
    """Per-index usage accounting (r14, mgstat): lookups served, rows
    returned, last-used wall time — surfaced by SHOW INDEX INFO so an
    index that only ever absorbs writes is visible instead of silent
    overhead. Updated once per scan (the scan's row count accumulates
    locally and flushes in the iterator's ``finally``), so abandoned
    iterators (LIMIT) still account what they served."""

    __slots__ = ("lookups", "rows", "last_used")

    def __init__(self) -> None:
        self.lookups = 0
        self.rows = 0
        self.last_used = 0.0

    def note(self, rows: int) -> None:
        import time
        self.lookups += 1
        self.rows += rows
        self.last_used = time.time()


class LabelIndex:
    """label_id -> insertion-ordered dict of candidate vertices.

    Supports BACKGROUND population (reference:
    src/storage/v2/async_indexer.cpp): a populating index accepts live
    writer additions but serves no candidates until its ready gate opens,
    so concurrent readers fall back to full scans and never see a
    half-built index.
    """

    def __init__(self) -> None:
        self._lock = tracked_lock("LabelIndex._lock")
        self._index: dict[int, dict] = {}
        self._ready: dict[int, threading.Event] = {}
        self._usage: dict[int, IndexUsage] = {}

    def create(self, label_id: int, vertices) -> None:
        with self._lock:
            bucket = self._index.setdefault(label_id, {})
            event = self._ready.setdefault(label_id, threading.Event())
        for v in vertices:
            if label_id in v.labels and not v.deleted:
                bucket[v.gid] = v
        event.set()

    def create_in_background(self, label_id: int,
                             vertices_fn) -> threading.Event:
        """Register the index immediately, populate on a worker thread;
        returns the ready event. `vertices_fn` materializes the vertex
        snapshot and is called only AFTER registration, so a concurrent
        writer's add() cannot fall in the unregistered window and be
        lost."""
        with self._lock:
            bucket = self._index.setdefault(label_id, {})
            event = self._ready.setdefault(label_id, threading.Event())
            if event.is_set():
                return event            # already populated

        def populate():
            try:
                for v in vertices_fn():
                    if label_id in v.labels and not v.deleted:
                        bucket[v.gid] = v
                with self._lock:
                    still_ours = self._ready.get(label_id) is event
            except Exception:
                # failed population: drop the shell so readers keep the
                # (correct) fallback path and DDL can retry
                import logging
                logging.getLogger(__name__).exception(
                    "background population of label index %d failed — "
                    "dropping the shell; CREATE INDEX can be retried",
                    label_id)
                self.drop(label_id)
                still_ours = False
            # ALWAYS wake waiters; serving is gated on the registry so a
            # concurrently-dropped index is never resurrected (we write
            # only into the captured bucket, never re-register)
            event.set()
            if not still_ours:
                bucket.clear()

        threading.Thread(target=populate, daemon=True,
                         name=f"index-build-{label_id}").start()
        return event

    def drop(self, label_id: int) -> bool:
        with self._lock:
            self._ready.pop(label_id, None)
            self._usage.pop(label_id, None)
            return self._index.pop(label_id, None) is not None

    def note_usage(self, label_id: int, rows: int) -> None:
        with self._lock:
            usage = self._usage.get(label_id)
            if usage is None:
                usage = self._usage[label_id] = IndexUsage()
            usage.note(rows)

    def usage(self, label_id: int) -> IndexUsage | None:
        return self._usage.get(label_id)

    def has(self, label_id: int) -> bool:
        return label_id in self._index

    def ready(self, label_id: int) -> bool:
        event = self._ready.get(label_id)
        return event is not None and event.is_set()

    def wait_ready(self, label_id: int, timeout: float | None = None) -> bool:
        event = self._ready.get(label_id)
        return event.wait(timeout) if event is not None else False

    def labels(self) -> list[int]:
        return list(self._index)

    def add(self, label_id: int, vertex) -> None:
        # populating buckets take live additions too: a commit racing the
        # background build must not be lost
        bucket = self._index.get(label_id)
        if bucket is not None:
            bucket[vertex.gid] = vertex

    def bulk_add(self, label_id: int, vertices) -> None:
        """Deferred batch maintenance: one dict update for a whole batch
        instead of per-row add() calls."""
        bucket = self._index.get(label_id)
        if bucket is not None:
            bucket.update((v.gid, v) for v in vertices)

    def candidates(self, label_id: int):
        bucket = self._index.get(label_id)
        if bucket is None or not self.ready(label_id):
            return None                 # not (yet) usable: callers scan
        return list(bucket.values())

    def approx_count(self, label_id: int) -> int:
        bucket = self._index.get(label_id)
        return len(bucket) if bucket is not None else 0

    def remove_entry(self, label_id: int, vertex) -> None:
        bucket = self._index.get(label_id)
        if bucket is not None:
            bucket.pop(vertex.gid, None)

    def sweep(self) -> int:
        """Drop entries for settled vertices that no longer carry the label."""
        removed = 0
        with self._lock:
            for label_id, bucket in self._index.items():
                stale = [gid for gid, v in bucket.items()
                         if v.delta is None
                         and (v.deleted or label_id not in v.labels)]
                for gid in stale:
                    del bucket[gid]
                removed += len(stale)
        return removed


class LabelPropertyIndex:
    """(label_id, (prop_id, ...)) -> sorted entries for range scans.

    Composite keys supported, as in the reference's composite label+property
    indexes. Entries are (sort_key, gid, vertex, values) kept sorted so range
    scans are bisect + slice.

    MVCC discipline (same as the reference's skip-list indexes): entries are
    **add-only** — a property change *adds* an entry under the new key and
    keeps the old one, because concurrent snapshot readers may still need to
    find the vertex under its old value. Scans revalidate every candidate
    against the reader's snapshot; stale entries are swept by GC once the
    vertex's delta chain is fully collected (no reader can need them).
    """

    def __init__(self) -> None:
        self._lock = tracked_lock("LabelPropertyIndex._lock")
        # key -> {"sorted": list[(key_tuple, gid, vertex, values)],
        #         "by_gid": dict[gid, set[key_tuple]],
        #         "eq": dict[key_tuple, list[vertex]]}   (point lookups)
        self._index: dict[tuple[int, tuple[int, ...]], dict] = {}
        self._usage: dict[tuple[int, tuple[int, ...]], IndexUsage] = {}

    @staticmethod
    def _entry_key(values) -> tuple:
        return tuple(order_key(v) for v in values)

    def create(self, label_id: int, prop_ids: tuple[int, ...], vertices) -> None:
        with self._lock:
            slot = self._index.setdefault((label_id, prop_ids),
                                          {"sorted": [], "by_gid": {},
                                           "eq": {}})
        for v in vertices:
            self.maybe_add(label_id, prop_ids, v)
        # created concurrently with writes in principle; final sort for safety
        slot["sorted"].sort(key=lambda e: (e[0], e[1]))

    def drop(self, label_id: int, prop_ids: tuple[int, ...]) -> bool:
        with self._lock:
            self._usage.pop((label_id, prop_ids), None)
            return self._index.pop((label_id, prop_ids), None) is not None

    def note_usage(self, label_id: int, prop_ids: tuple[int, ...],
                   rows: int) -> None:
        with self._lock:
            key = (label_id, prop_ids)
            usage = self._usage.get(key)
            if usage is None:
                usage = self._usage[key] = IndexUsage()
            usage.note(rows)

    def usage(self, label_id: int,
              prop_ids: tuple[int, ...]) -> IndexUsage | None:
        return self._usage.get((label_id, prop_ids))

    def has(self, label_id: int, prop_ids: tuple[int, ...]) -> bool:
        return (label_id, prop_ids) in self._index

    def keys(self) -> list[tuple[int, tuple[int, ...]]]:
        return list(self._index)

    def relevant_to(self, label_id: int):
        """All composite keys on this label (for planner rewrites)."""
        return [k for k in self._index if k[0] == label_id]

    def maybe_add(self, label_id: int, prop_ids: tuple[int, ...], vertex) -> None:
        """Insert vertex if it currently has the label and all properties."""
        slot = self._index.get((label_id, prop_ids))
        if slot is None:
            return
        if label_id not in vertex.labels or vertex.deleted:
            return
        values = []
        for pid in prop_ids:
            if pid not in vertex.properties:
                return
            values.append(vertex.properties[pid])
        self._insert(slot, vertex, values)

    def _insert(self, slot, vertex, values) -> None:
        key = self._entry_key(values)
        with self._lock:
            keys = slot["by_gid"].setdefault(vertex.gid, set())
            if key in keys:
                return
            keys.add(key)
            bisect.insort(slot["sorted"], (key, vertex.gid, vertex, tuple(values)),
                          key=lambda e: (e[0], e[1]))
            slot["eq"].setdefault(key, []).append(vertex)

    def update_on_change(self, vertex) -> None:
        """Add entries for the vertex's current state (add-only, see class doc)."""
        for (label_id, prop_ids) in list(self._index):
            self.maybe_add(label_id, prop_ids, vertex)

    def bulk_add(self, vertices) -> None:
        """Deferred batch maintenance: per index, collect every qualifying
        entry for the batch, sort ONCE, and splice into the sorted entry
        list with a single linear merge — replacing one O(log n) bisect +
        O(n) insort memmove per row with O((n+m)) per batch."""
        for (label_id, prop_ids), slot in list(self._index.items()):
            fresh = []
            for v in vertices:
                if label_id not in v.labels or v.deleted:
                    continue
                values = []
                for pid in prop_ids:
                    if pid not in v.properties:
                        values = None
                        break
                    values.append(v.properties[pid])
                if values is None:
                    continue
                fresh.append((self._entry_key(values), v.gid, v,
                              tuple(values)))
            if not fresh:
                continue
            fresh.sort(key=lambda e: (e[0], e[1]))
            with self._lock:
                by_gid = slot["by_gid"]
                deduped = []
                for entry in fresh:
                    keys = by_gid.setdefault(entry[1], set())
                    if entry[0] in keys:
                        continue
                    keys.add(entry[0])
                    deduped.append(entry)
                if not deduped:
                    continue
                eq = slot["eq"]
                for entry in deduped:
                    eq.setdefault(entry[0], []).append(entry[2])
                old = slot["sorted"]
                if old and (old[-1][0], old[-1][1]) <= \
                        (deduped[0][0], deduped[0][1]):
                    # common bulk-load case: fresh keys all sort after the
                    # existing tail (monotonic ids) — plain extend
                    old.extend(deduped)
                else:
                    merged = []
                    i = j = 0
                    while i < len(old) and j < len(deduped):
                        if (old[i][0], old[i][1]) <= \
                                (deduped[j][0], deduped[j][1]):
                            merged.append(old[i])
                            i += 1
                        else:
                            merged.append(deduped[j])
                            j += 1
                    merged.extend(old[i:])
                    merged.extend(deduped[j:])
                    slot["sorted"] = merged

    def remove_entry(self, vertex) -> None:
        """Drop every entry for a dead (GC'd) vertex."""
        with self._lock:
            for slot in self._index.values():
                keys = slot["by_gid"].pop(vertex.gid, None)
                if keys is not None:
                    slot["sorted"] = [e for e in slot["sorted"]
                                      if e[1] != vertex.gid]
                    eq = slot["eq"]
                    for key in keys:
                        bucket = eq.get(key)
                        if bucket is not None:
                            bucket[:] = [v for v in bucket
                                         if v.gid != vertex.gid]
                            if not bucket:
                                del eq[key]

    def sweep(self) -> int:
        """Drop stale entries for settled vertices (delta chain fully GC'd).

        Called from storage GC. A settled vertex has exactly one visible
        state, so any entry whose key no longer matches it is unreachable.
        """
        removed = 0
        with self._lock:
            for (label_id, prop_ids), slot in self._index.items():
                keep = []
                by_gid: dict[int, set] = {}
                for entry in slot["sorted"]:
                    key, gid, vertex, values = entry
                    if vertex.delta is None:
                        stale = (vertex.deleted
                                 or label_id not in vertex.labels
                                 or any(p not in vertex.properties
                                        for p in prop_ids)
                                 or self._entry_key(
                                     [vertex.properties[p] for p in prop_ids])
                                 != key)
                        if stale:
                            removed += 1
                            continue
                    keep.append(entry)
                    by_gid.setdefault(gid, set()).add(key)
                eq: dict = {}
                for key, _gid, vertex, _values in keep:
                    eq.setdefault(key, []).append(vertex)
                slot["sorted"] = keep
                slot["by_gid"] = by_gid
                slot["eq"] = eq
        return removed

    # --- scans --------------------------------------------------------------

    def candidates_equal(self, label_id, prop_ids, values):
        slot = self._index.get((label_id, prop_ids))
        if slot is None:
            return None
        # hash bucket per key: point lookups skip the sorted list entirely
        return list(slot["eq"].get(self._entry_key(values), ()))

    def candidates_range(self, label_id, prop_ids, lower=None, upper=None,
                         lower_inclusive=True, upper_inclusive=True):
        """Range over the FIRST property of the composite key."""
        slot = self._index.get((label_id, prop_ids))
        if slot is None:
            return None
        entries = slot["sorted"]
        lo_i, hi_i = 0, len(entries)
        if lower is not None:
            k = (order_key(lower),)
            lo_i = (bisect.bisect_left(entries, k, key=lambda e: (e[0][0],))
                    if lower_inclusive else
                    bisect.bisect_right(entries, k, key=lambda e: (e[0][0],)))
        if upper is not None:
            k = (order_key(upper),)
            hi_i = (bisect.bisect_right(entries, k, key=lambda e: (e[0][0],))
                    if upper_inclusive else
                    bisect.bisect_left(entries, k, key=lambda e: (e[0][0],)))
        return [e[2] for e in entries[lo_i:hi_i]]

    def candidates_all(self, label_id, prop_ids):
        slot = self._index.get((label_id, prop_ids))
        if slot is None:
            return None
        return [e[2] for e in slot["sorted"]]

    def approx_count(self, label_id, prop_ids) -> int:
        slot = self._index.get((label_id, prop_ids))
        return len(slot["sorted"]) if slot is not None else 0


class EdgeTypeIndex:
    """edge_type_id -> dict of candidate edges (reference: indices/edge_type_index)."""

    def __init__(self) -> None:
        self._index: dict[int, dict] = {}
        self._usage: dict[int, IndexUsage] = {}

    def create(self, edge_type_id: int, edges) -> None:
        bucket = self._index.setdefault(edge_type_id, {})
        for e in edges:
            if e.edge_type == edge_type_id and not e.deleted:
                bucket[e.gid] = e

    def drop(self, edge_type_id: int) -> bool:
        self._usage.pop(edge_type_id, None)
        return self._index.pop(edge_type_id, None) is not None

    def note_usage(self, edge_type_id: int, rows: int) -> None:
        usage = self._usage.get(edge_type_id)
        if usage is None:
            usage = self._usage[edge_type_id] = IndexUsage()
        usage.note(rows)

    def usage(self, edge_type_id: int) -> IndexUsage | None:
        return self._usage.get(edge_type_id)

    def has(self, edge_type_id: int) -> bool:
        return edge_type_id in self._index

    def types(self) -> list[int]:
        return list(self._index)

    def add(self, edge) -> None:
        bucket = self._index.get(edge.edge_type)
        if bucket is not None:
            bucket[edge.gid] = edge

    def bulk_add(self, edges) -> None:
        """Deferred batch maintenance: group by type, one update per bucket."""
        if not self._index:
            return
        by_type: dict[int, list] = {}
        for e in edges:
            by_type.setdefault(e.edge_type, []).append(e)
        for etype, group in by_type.items():
            bucket = self._index.get(etype)
            if bucket is not None:
                bucket.update((e.gid, e) for e in group)

    def candidates(self, edge_type_id: int):
        bucket = self._index.get(edge_type_id)
        if bucket is None:
            return None
        return list(bucket.values())

    def approx_count(self, edge_type_id: int) -> int:
        bucket = self._index.get(edge_type_id)
        return len(bucket) if bucket is not None else 0

    def remove_entry(self, edge) -> None:
        bucket = self._index.get(edge.edge_type)
        if bucket is not None:
            bucket.pop(edge.gid, None)


class Indices:
    """Bundle owned by the storage engine."""

    def __init__(self) -> None:
        self.label = LabelIndex()
        self.label_property = LabelPropertyIndex()
        self.edge_type = EdgeTypeIndex()
        # ANALYZE GRAPH results: (label_id, prop_id_tuple) -> stats dict
        # (() for plain label indexes); dropped alongside the index
        self.analyze_stats: dict = {}
        # vector / text / point indexes attach here (separate modules)
        self.vector = None
        self.text = None
        self.point = None

    def drop_stats(self, label_id: int, prop_ids: tuple = None) -> None:
        """Forget ANALYZE stats for a dropped index (all prefixes)."""
        if prop_ids is None:
            self.analyze_stats.pop((label_id, ()), None)
            return
        for k in [k for k in self.analyze_stats
                  if k[0] == label_id and k[1]
                  and k[1] == prop_ids[:len(k[1])]]:
            del self.analyze_stats[k]
