"""Full-text search index (BM25 inverted index with positions).

Counterpart of the reference's tantivy-backed text index
(/root/reference/src/storage/v2/indices/text_index.cpp via the mgcxx Rust
bridge — no Rust in this environment, so a native-Python inverted index
with BM25 ranking; a C++ backend slots behind the same interface).

Indexes all string properties of vertices with a given label. Exposed via
the text_search module procedures (text_search.search, matching the
reference's query_modules/text_search_module.cpp surface).

Query language (the tantivy subset the reference exposes):
  term term         OR of terms (default)
  "a b c"           phrase (consecutive positions)
  a AND b, a OR b   boolean operators (AND binds tighter)
  NOT a             negation (filters the candidate set)
  ( ... )           grouping
Ranking is BM25 over the query's positive terms; boolean structure
selects the candidate documents.
"""

from __future__ import annotations

import math
import re
import threading
from collections import Counter, defaultdict

_TOKEN_RE = re.compile(r"[a-z0-9]+")
_QUERY_RE = re.compile(r'"[^"]*"|\(|\)|[^\s()]+')


def tokenize_text(text: str) -> list[str]:
    return _TOKEN_RE.findall(text.lower())


class TextIndex:
    """One named text index over (label, [string properties])."""

    K1 = 1.5
    B = 0.75

    def __init__(self, name: str, label_id: int,
                 property_ids: list[int] | None = None):
        self.name = name
        self.label_id = label_id
        self.property_ids = property_ids  # None = all string properties
        self._lock = threading.Lock()
        # term -> {gid: (tf, positions)} — positions enable phrases
        self._postings: dict[str, dict[int, tuple[int, list[int]]]] = \
            defaultdict(dict)
        self._doc_len: dict[int, int] = {}
        self._total_len = 0

    # --- maintenance --------------------------------------------------------

    # gap between properties so phrases never match across field
    # boundaries (tantivy has per-field postings; a gap is the compact
    # equivalent for our concatenated layout)
    FIELD_GAP = 1000

    def _document_positions(self, vertex):
        """[(term, position)] with inter-property gaps; and token count."""
        out = []
        pos = 0
        count = 0
        for pid, value in sorted(vertex.properties.items()):
            if self.property_ids is not None and pid not in self.property_ids:
                continue
            if isinstance(value, str):
                toks = tokenize_text(value)
                for t in toks:
                    out.append((t, pos))
                    pos += 1
                count += len(toks)
                pos += self.FIELD_GAP
        return out, count

    def add_vertex(self, vertex) -> None:
        if self.label_id not in vertex.labels or vertex.deleted:
            return
        term_positions, n_tokens = self._document_positions(vertex)
        with self._lock:
            self._remove_locked(vertex.gid)
            if not term_positions:
                return
            positions: dict[str, list[int]] = defaultdict(list)
            for term, pos in term_positions:
                positions[term].append(pos)
            for term, plist in positions.items():
                self._postings[term][vertex.gid] = (len(plist), plist)
            self._doc_len[vertex.gid] = n_tokens
            self._total_len += n_tokens

    def remove_vertex(self, gid: int) -> None:
        with self._lock:
            self._remove_locked(gid)

    def _remove_locked(self, gid: int) -> None:
        old_len = self._doc_len.pop(gid, None)
        if old_len is None:
            return
        self._total_len -= old_len
        for term_docs in self._postings.values():
            term_docs.pop(gid, None)

    def rebuild(self, vertices) -> None:
        with self._lock:
            self._postings.clear()
            self._doc_len.clear()
            self._total_len = 0
        for v in vertices:
            self.add_vertex(v)

    # --- search -------------------------------------------------------------

    def search(self, query: str, limit: int = 10) -> list[tuple[int, float]]:
        """BM25-ranked [(gid, score)] for a boolean/phrase query."""
        with self._lock:
            n_docs = len(self._doc_len)
            if not n_docs:
                return []
            try:
                node = _parse_query(query)
            except _QuerySyntaxError:
                from ..exceptions import QueryException
                raise QueryException(
                    f"invalid text search query: {query!r}")
            if node is None:
                return []
            docs, positive = node.evaluate(self)
            if not docs:
                return []
            avg_len = self._total_len / n_docs
            scores: dict[int, float] = defaultdict(float)
            for term in positive:
                entries = self._postings.get(term)
                if not entries:
                    continue
                idf = math.log(1 + (n_docs - len(entries) + 0.5)
                               / (len(entries) + 0.5))
                for gid, (tf, _pos) in entries.items():
                    if gid not in docs:
                        continue
                    dl = self._doc_len[gid]
                    denom = tf + self.K1 * (1 - self.B
                                            + self.B * dl / avg_len)
                    scores[gid] += idf * tf * (self.K1 + 1) / denom
            for gid in docs:
                scores.setdefault(gid, 0.0)   # pure-NOT / filter matches
            ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
            return ranked[:limit]

    # caller holds self._lock
    def _docs_for_term(self, term: str) -> set[int]:
        return set(self._postings.get(term, ()))

    def _docs_for_phrase(self, terms: list[str]) -> set[int]:
        """Docs where the terms occur at consecutive positions."""
        if not terms:
            return set()
        if len(terms) == 1:
            return self._docs_for_term(terms[0])
        entries = [self._postings.get(t) for t in terms]
        if any(e is None for e in entries):
            return set()
        candidates = set(entries[0])
        for e in entries[1:]:
            candidates &= set(e)
        out = set()
        for gid in candidates:
            psets = [set(e[gid][1]) for e in entries]
            if any(all((p + i) in psets[i]
                       for i in range(1, len(terms)))
                   for p in psets[0]):
                out.add(gid)
        return out

    def _all_docs(self) -> set[int]:
        return set(self._doc_len)

    def info(self) -> dict:
        with self._lock:
            return {"name": self.name, "documents": len(self._doc_len),
                    "terms": len(self._postings)}


# --- query language ---------------------------------------------------------

class _QuerySyntaxError(Exception):
    pass


class _Term:
    def __init__(self, term):
        self.term = term

    def evaluate(self, index):
        return index._docs_for_term(self.term), {self.term}


class _Phrase:
    def __init__(self, terms):
        self.terms = terms

    def evaluate(self, index):
        return index._docs_for_phrase(self.terms), set(self.terms)


class _Bool:
    def __init__(self, op, left, right):
        self.op, self.left, self.right = op, left, right

    def evaluate(self, index):
        ld, lp = self.left.evaluate(index)
        rd, rp = self.right.evaluate(index)
        if self.op == "AND":
            return ld & rd, lp | rp
        return ld | rd, lp | rp


class _Nothing:
    def evaluate(self, index):
        return set(), set()


class _Not:
    def __init__(self, child):
        self.child = child

    def evaluate(self, index):
        cd, _ = self.child.evaluate(index)
        return index._all_docs() - cd, set()


def _parse_query(query: str):
    tokens = _QUERY_RE.findall(query)
    pos = [0]

    def peek():
        return tokens[pos[0]] if pos[0] < len(tokens) else None

    def advance():
        tok = tokens[pos[0]]
        pos[0] += 1
        return tok

    def parse_or():
        node = parse_and()
        while True:
            tok = peek()
            if tok is None or tok == ")":
                return node
            if tok.upper() == "OR":
                advance()
                node = _Bool("OR", node, parse_and())
            else:
                # bare adjacency = OR (tantivy default); trailing ANDs
                # were already consumed by parse_and
                node = _Bool("OR", node, parse_and())

    def parse_and():
        node = parse_not()
        while peek() is not None and peek().upper() == "AND":
            advance()
            node = _Bool("AND", node, parse_not())
        return node

    def parse_not():
        tok = peek()
        if tok is not None and tok.upper() == "NOT":
            advance()
            return _Not(parse_not())
        return parse_primary()

    def parse_primary():
        tok = peek()
        if tok is None:
            raise _QuerySyntaxError("unexpected end of query")
        if tok == "(":
            advance()
            node = parse_or()
            if peek() != ")":
                raise _QuerySyntaxError("missing )")
            advance()
            return node
        if tok == ")":
            raise _QuerySyntaxError("unexpected )")
        advance()
        if tok.startswith('"'):
            terms = tokenize_text(tok.strip('"'))
            if not terms:
                return _Nothing()   # punctuation-only: matches no docs
            return _Phrase(terms)
        terms = tokenize_text(tok)
        if not terms:
            return _Nothing()       # e.g. '???' — old behavior: no rows
        if len(terms) > 1:
            return _Phrase(terms)    # e.g. hyphenated-word
        return _Term(terms[0])

    if not tokens:
        return None
    node = parse_or()
    if peek() is not None:
        raise _QuerySyntaxError(f"trailing input at {peek()!r}")
    return node


class TextIndices:
    """Registry of named text indexes, kept fresh by a commit hook."""

    def __init__(self, storage) -> None:
        self.storage = storage
        self._lock = threading.Lock()
        self._indexes: dict[str, TextIndex] = {}
        storage.on_commit_hooks.append(self._on_commit)

    def create(self, name: str, label_name: str) -> TextIndex:
        from ..exceptions import QueryException
        with self._lock:
            if name in self._indexes:
                raise QueryException(f"text index {name!r} already exists")
        label_id = self.storage.label_mapper.name_to_id(label_name)
        index = TextIndex(name, label_id)
        index.rebuild(list(self.storage._vertices.values()))
        with self._lock:
            self._indexes[name] = index
        return index

    def drop(self, name: str) -> bool:
        with self._lock:
            return self._indexes.pop(name, None) is not None

    def get(self, name: str) -> TextIndex | None:
        with self._lock:
            return self._indexes.get(name)

    def all(self) -> list[TextIndex]:
        with self._lock:
            return list(self._indexes.values())

    def _on_commit(self, txn, commit_ts) -> None:
        with self._lock:
            indexes = list(self._indexes.values())
        if not indexes:
            return
        for vertex in txn.touched_vertices.values():
            for index in indexes:
                if vertex.deleted:
                    index.remove_vertex(vertex.gid)
                else:
                    index.add_vertex(vertex)


def text_indices(storage) -> TextIndices:
    if storage.indices.text is None:
        storage.indices.text = TextIndices(storage)
    return storage.indices.text
