"""Full-text search index (BM25 inverted index).

Counterpart of the reference's tantivy-backed text index
(/root/reference/src/storage/v2/indices/text_index.cpp via the mgcxx Rust
bridge — no Rust in this environment, so a native-Python inverted index
with BM25 ranking; a C++ backend slots behind the same interface).

Indexes all string properties of vertices with a given label. Exposed via
the text_search module procedures (text_search.search, matching the
reference's query_modules/text_search_module.cpp surface).
"""

from __future__ import annotations

import math
import re
import threading
from collections import Counter, defaultdict

_TOKEN_RE = re.compile(r"[a-z0-9]+")


def tokenize_text(text: str) -> list[str]:
    return _TOKEN_RE.findall(text.lower())


class TextIndex:
    """One named text index over (label, [string properties])."""

    K1 = 1.5
    B = 0.75

    def __init__(self, name: str, label_id: int,
                 property_ids: list[int] | None = None):
        self.name = name
        self.label_id = label_id
        self.property_ids = property_ids  # None = all string properties
        self._lock = threading.Lock()
        self._postings: dict[str, dict[int, int]] = defaultdict(dict)
        self._doc_len: dict[int, int] = {}
        self._total_len = 0

    # --- maintenance --------------------------------------------------------

    def _document_tokens(self, vertex) -> list[str]:
        tokens: list[str] = []
        for pid, value in vertex.properties.items():
            if self.property_ids is not None and pid not in self.property_ids:
                continue
            if isinstance(value, str):
                tokens.extend(tokenize_text(value))
        return tokens

    def add_vertex(self, vertex) -> None:
        if self.label_id not in vertex.labels or vertex.deleted:
            return
        tokens = self._document_tokens(vertex)
        with self._lock:
            self._remove_locked(vertex.gid)
            if not tokens:
                return
            counts = Counter(tokens)
            for term, tf in counts.items():
                self._postings[term][vertex.gid] = tf
            self._doc_len[vertex.gid] = len(tokens)
            self._total_len += len(tokens)

    def remove_vertex(self, gid: int) -> None:
        with self._lock:
            self._remove_locked(gid)

    def _remove_locked(self, gid: int) -> None:
        old_len = self._doc_len.pop(gid, None)
        if old_len is None:
            return
        self._total_len -= old_len
        for term_docs in self._postings.values():
            term_docs.pop(gid, None)

    def rebuild(self, vertices) -> None:
        with self._lock:
            self._postings.clear()
            self._doc_len.clear()
            self._total_len = 0
        for v in vertices:
            self.add_vertex(v)

    # --- search -------------------------------------------------------------

    def search(self, query: str, limit: int = 10) -> list[tuple[int, float]]:
        """BM25-ranked [(gid, score)] for the query terms (OR semantics)."""
        terms = tokenize_text(query)
        with self._lock:
            n_docs = len(self._doc_len)
            if not n_docs or not terms:
                return []
            avg_len = self._total_len / n_docs
            scores: dict[int, float] = defaultdict(float)
            for term in terms:
                docs = self._postings.get(term)
                if not docs:
                    continue
                idf = math.log(1 + (n_docs - len(docs) + 0.5)
                               / (len(docs) + 0.5))
                for gid, tf in docs.items():
                    dl = self._doc_len[gid]
                    denom = tf + self.K1 * (1 - self.B
                                            + self.B * dl / avg_len)
                    scores[gid] += idf * tf * (self.K1 + 1) / denom
            ranked = sorted(scores.items(), key=lambda kv: (-kv[1], kv[0]))
            return ranked[:limit]

    def info(self) -> dict:
        with self._lock:
            return {"name": self.name, "documents": len(self._doc_len),
                    "terms": len(self._postings)}


class TextIndices:
    """Registry of named text indexes, kept fresh by a commit hook."""

    def __init__(self, storage) -> None:
        self.storage = storage
        self._lock = threading.Lock()
        self._indexes: dict[str, TextIndex] = {}
        storage.on_commit_hooks.append(self._on_commit)

    def create(self, name: str, label_name: str) -> TextIndex:
        from ..exceptions import QueryException
        with self._lock:
            if name in self._indexes:
                raise QueryException(f"text index {name!r} already exists")
        label_id = self.storage.label_mapper.name_to_id(label_name)
        index = TextIndex(name, label_id)
        index.rebuild(list(self.storage._vertices.values()))
        with self._lock:
            self._indexes[name] = index
        return index

    def drop(self, name: str) -> bool:
        with self._lock:
            return self._indexes.pop(name, None) is not None

    def get(self, name: str) -> TextIndex | None:
        with self._lock:
            return self._indexes.get(name)

    def all(self) -> list[TextIndex]:
        with self._lock:
            return list(self._indexes.values())

    def _on_commit(self, txn, commit_ts) -> None:
        with self._lock:
            indexes = list(self._indexes.values())
        if not indexes:
            return
        for vertex in txn.touched_vertices.values():
            for index in indexes:
                if vertex.deleted:
                    index.remove_vertex(vertex.gid)
                else:
                    index.add_vertex(vertex)


def text_indices(storage) -> TextIndices:
    if storage.indices.text is None:
        storage.indices.text = TextIndices(storage)
    return storage.indices.text
